"""Jitted train step for the device-resident embedding cache.

One compiled XLA program per step does ALL of: import this batch's
cache-miss rows (scatter), read back the rows they evict (gather, for
host write-back to the PS), embedding gather, dense forward/backward,
dense optimizer update, AND the sparse Adagrad update applied directly
to the cached rows on device. Nothing but miss rows and slot indices
crosses the host<->device wire — the hybrid path's per-step packed
upload/download (persia_tpu/parallel/train.py make_packed_train_step)
disappears for cache hits.

The sparse update mirrors the parameter server's decayed Adagrad
bit-for-bit in structure (persia_tpu/ps/optim.py SparseAdagrad,
non-shared; reference optim.rs:246-307): the step uses the accumulator
value from BEFORE this batch's gradient is accumulated, duplicate signs
within a batch contribute a summed gradient exactly like the
middleware's dedup+sum, and untouched rows keep their accumulator
(no decay without a gradient — same as rows the PS never sees).

Host-side mapping/eviction policy lives in
persia_tpu/worker/device_cache.py; the orchestration tying both to
TrainCtx is persia_tpu/parallel/cached_engine.py.
"""

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from persia_tpu.parallel.train import (
    TrainState,
    _rebuild_embedding_inputs,
    bce_loss,
)


def init_cache_arrays(capacity: int, dim: int, acc_init: float):
    """(capacity+1, dim) value + accumulator arrays; the extra row is the
    dummy slot that padded miss entries target (writes land there and are
    never read)."""
    vals = jnp.zeros((capacity + 1, dim), jnp.float32)
    acc = jnp.full((capacity + 1, dim), acc_init, jnp.float32)
    return vals, acc


def make_cached_train_step(
    model,
    optimizer: optax.GradientTransformation,
    num_slots: int,
    dim: int,
    lr: float,
    eps: float,
    g_square_momentum: float,
    loss_fn: Callable = bce_loss,
    weight_bound: float = 0.0,
) -> Callable:
    """step(state, cache_vals, cache_acc, non_id, slot_idx, cold_idx,
    cold_vals, cold_acc, inverse, unique_slots, label) -> (state,
    cache_vals, cache_acc, loss, pred, evicted_vals, evicted_acc)

    - slot_idx: (B, S) int32 — cache slot per (sample, slot) position;
    - cold_idx: (M,) int32 — slots receiving this batch's miss rows
      (padded entries point at the dummy slot);
    - cold_vals/cold_acc: (M, D) — miss rows (+ Adagrad state) fetched
      from the PS / victim buffer;
    - inverse: (B*S,) int32 — position -> index among this batch's
      distinct signs (the mapper computes it during its probe pass);
    - unique_slots: (B*S,) int32 — distinct index -> cache slot, tail
      past the distinct count padded with the dummy slot;
    - evicted_vals/evicted_acc: (M, D) — the PREVIOUS contents of
      cold_idx slots, read before the overwrite; the host writes these
      back to the PS keyed by the evicted signs.
    """

    def step(state: TrainState, cache_vals, cache_acc, non_id_tensors,
             slot_idx, cold_idx, cold_vals, cold_acc, inverse,
             unique_slots, label):
        # read rows being evicted BEFORE their slots are reused
        evicted_vals = cache_vals[cold_idx]
        evicted_acc = cache_acc[cold_idx]
        # write-allocate this batch's misses (pads target the dummy row)
        cache_vals = cache_vals.at[cold_idx].set(cold_vals)
        cache_acc = cache_acc.at[cold_idx].set(cold_acc)

        gathered = cache_vals[slot_idx]  # (B, S, D)

        def compute_loss(params, gathered):
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            emb_values = [gathered[:, i, :] for i in range(num_slots)]
            emb_inputs = _rebuild_embedding_inputs(
                emb_values, [None] * num_slots)
            out = model.apply(
                variables, non_id_tensors, emb_inputs, train=True,
                mutable=["batch_stats"] if state.batch_stats else [],
            )
            pred, mutated = out if isinstance(out, tuple) else (out, {})
            return loss_fn(pred, label), (pred, mutated)

        grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1),
                                     has_aux=True)
        (loss, (pred, mutated)), (param_grads, emb_grad) = grad_fn(
            state.params, gathered)

        updates, new_opt_state = optimizer.update(
            param_grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=mutated.get("batch_stats", state.batch_stats),
            opt_state=new_opt_state,
            step=state.step + 1,
        )

        # Sparse Adagrad on device, touching ONLY this batch's rows and
        # allocating ONLY O(batch)-sized buffers: duplicate signs'
        # gradients dedup-sum through the mapper's inverse map (==
        # middleware dedup+sum) into a (B*S, D) buffer — NOT a dense
        # (capacity, D) one, which would cost a full-cache zero-init +
        # memory pass per step. One optimizer row per distinct sign,
        # scatter-SET back (pad rows carry zero grads and write their
        # unchanged dummy-row value; untouched cache rows are never read
        # or written — matching the PS: no accumulator decay without a
        # gradient).
        dummy = cache_vals.shape[0] - 1
        valid = (unique_slots != dummy)[:, None]
        gsum_u = jnp.zeros((inverse.shape[0], dim), jnp.float32).at[
            inverse].add(emb_grad.reshape(-1, dim))
        acc_u = cache_acc[unique_slots]  # PRE-update accumulator
        new_val_u = (cache_vals[unique_slots]
                     - lr * gsum_u * jax.lax.rsqrt(acc_u + eps))
        if weight_bound > 0:
            # the PS clamps after every update (ps/optim.py
            # apply_weight_bound; reference persia-simd lib.rs:231-251) —
            # mirror it or cached and uncached training diverge for hot
            # rows near the bound
            new_val_u = jnp.clip(new_val_u, -weight_bound, weight_bound)
        new_acc_u = jnp.where(
            valid, acc_u * g_square_momentum + gsum_u * gsum_u, acc_u)
        cache_vals = cache_vals.at[unique_slots].set(new_val_u)
        cache_acc = cache_acc.at[unique_slots].set(new_acc_u)
        return (new_state, cache_vals, cache_acc, loss, pred,
                evicted_vals, evicted_acc)

    # donate the cache arrays: they are carried state, updated in place
    return jax.jit(step, donate_argnums=(1, 2))


def make_cached_eval_step(model, num_slots: int) -> Callable:
    """Pure gather + forward for signs fully resident in the cache."""

    def step(state: TrainState, cache_vals, non_id_tensors, slot_idx):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        gathered = cache_vals[slot_idx]
        emb_values = [gathered[:, i, :] for i in range(num_slots)]
        emb_inputs = _rebuild_embedding_inputs(emb_values, [None] * num_slots)
        return model.apply(variables, non_id_tensors, emb_inputs, train=False)

    return jax.jit(step)


def pad_to_bucket(n: int, buckets: Sequence[int]) -> int:
    """Pad a miss count to a fixed size so jit reuses a few compiled
    geometries instead of recompiling per distinct count."""
    for b in buckets:
        if n <= b:
            return b
    return int(np.ceil(n / buckets[-1]) * buckets[-1])
