"""Jitted train step for the device-resident embedding cache.

One compiled XLA program per step does ALL of: import this batch's
cache-miss rows (scatter), read back the rows they evict (gather, for
host write-back to the PS), embedding gather, dense forward/backward,
dense optimizer update, AND the sparse Adagrad update applied directly
to the cached rows on device. Nothing but miss rows and slot indices
crosses the host<->device wire — the hybrid path's per-step packed
upload/download (persia_tpu/parallel/train.py make_packed_train_step)
disappears for cache hits.

The sparse update mirrors the parameter server's decayed Adagrad
bit-for-bit in structure (persia_tpu/ps/optim.py SparseAdagrad,
non-shared; reference optim.rs:246-307): the step uses the accumulator
value from BEFORE this batch's gradient is accumulated, duplicate signs
within a batch contribute a summed gradient exactly like the
middleware's dedup+sum, and untouched rows keep their accumulator
(no decay without a gradient — same as rows the PS never sees).

Host-side mapping/eviction policy lives in
persia_tpu/worker/device_cache.py; the orchestration tying both to
TrainCtx is persia_tpu/parallel/cached_engine.py.
"""

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from persia_tpu.parallel.train import (
    TrainState,
    _rebuild_embedding_inputs,
    bce_loss,
)


def _row_sharding(mesh):
    """Cache rows sharded over EVERY mesh device (data x model): the
    cache is ONE logical array partitioned by GSPMD, so per-row HBM
    scales with the device count and there is no per-trainer fork of
    optimizer state to reconcile — the single-writer invariant holds
    because there is a single (partitioned) program, XLA inserting the
    gather/scatter collectives."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def init_cache_arrays(capacity: int, dim: int, acc_init: float, mesh=None):
    """(rows, dim) value + accumulator arrays; row ``capacity`` is the
    dummy slot that padded miss entries target (writes land there and
    are never read). Under a mesh the row count is padded up to a
    multiple of the device count and the arrays are laid out with
    :func:`_row_sharding` (pad rows beyond the dummy are never
    addressed)."""
    rows = capacity + 1
    if mesh is not None:
        n_dev = int(np.prod(list(mesh.shape.values())))
        rows += (-rows) % n_dev
    vals = jnp.zeros((rows, dim), jnp.float32)
    acc = jnp.full((rows, dim), acc_init, jnp.float32)
    if mesh is not None:
        s = _row_sharding(mesh)
        vals, acc = jax.device_put(vals, s), jax.device_put(acc, s)
    return vals, acc


def _constrain_rows(mesh, cache_vals, cache_acc):
    """Pin the carried cache arrays to the row sharding (entry AND exit
    of each step: the donated output's sharding must match the input's
    for true in-place reuse)."""
    if mesh is None:
        return cache_vals, cache_acc
    s = _row_sharding(mesh)
    return (jax.lax.with_sharding_constraint(cache_vals, s),
            jax.lax.with_sharding_constraint(cache_acc, s))


def _import_cold(cache_vals, cache_acc, cold_idx, cold_vals, cold_acc):
    """Read the rows being evicted BEFORE their slots are reused, then
    write-allocate this batch's miss rows (pads target the dummy row)."""
    evicted_vals = cache_vals[cold_idx]
    evicted_acc = cache_acc[cold_idx]
    cache_vals = cache_vals.at[cold_idx].set(cold_vals)
    cache_acc = cache_acc.at[cold_idx].set(cold_acc)
    return cache_vals, cache_acc, evicted_vals, evicted_acc


def _forward_backward(model, loss_fn, state, non_id_tensors, label,
                      gathered, emb_values_of):
    """Shared dense forward/backward: differentiates w.r.t. params AND
    the raw ``gathered`` embedding tensor (``emb_values_of`` maps it to
    the model's per-slot inputs inside the loss so autodiff routes any
    scaling into the embedding gradient)."""

    def compute_loss(params, gathered):
        variables = {"params": params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        emb_values = emb_values_of(gathered)
        emb_inputs = _rebuild_embedding_inputs(
            emb_values, [None] * len(emb_values))
        out = model.apply(
            variables, non_id_tensors, emb_inputs, train=True,
            mutable=["batch_stats"] if state.batch_stats else [],
        )
        pred, mutated = out if isinstance(out, tuple) else (out, {})
        return loss_fn(pred, label), (pred, mutated)

    grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1),
                                 has_aux=True)
    return grad_fn(state.params, gathered)


def _dense_update(optimizer, state, param_grads, mutated):
    updates, new_opt_state = optimizer.update(
        param_grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    return TrainState(
        params=new_params,
        batch_stats=mutated.get("batch_stats", state.batch_stats),
        opt_state=new_opt_state,
        step=state.step + 1,
    )


def _sparse_adagrad_update(cache_vals, cache_acc, unique_slots, inverse,
                           pos_grad, dummy, dim, lr, eps,
                           g_square_momentum, weight_bound):
    """Sparse Adagrad on device, touching ONLY this batch's rows and
    allocating ONLY O(batch)-sized buffers: per-position gradients
    dedup-sum through the mapper's inverse map (== middleware
    dedup+sum) into an (Lpad, D) buffer — NOT a dense (capacity, D)
    one, which would cost a full-cache zero-init + memory pass per
    step. One optimizer row per distinct sign, scatter-SET back (pad
    rows carry zero grads and write their unchanged dummy-row value;
    untouched cache rows are never read or written — matching the PS:
    no accumulator decay without a gradient). The accumulator used is
    the PRE-update one, and the weight bound clamps after every update
    (ps/optim.py apply_weight_bound; reference persia-simd
    lib.rs:231-251) — mirror of the PS math, or cached and uncached
    training diverge."""
    valid = (unique_slots != dummy)[:, None]
    gsum_u = jnp.zeros((inverse.shape[0], dim), jnp.float32).at[
        inverse].add(pos_grad)
    acc_u = cache_acc[unique_slots]
    new_val_u = (cache_vals[unique_slots]
                 - lr * gsum_u * jax.lax.rsqrt(acc_u + eps))
    if weight_bound > 0:
        new_val_u = jnp.clip(new_val_u, -weight_bound, weight_bound)
    new_acc_u = jnp.where(
        valid, acc_u * g_square_momentum + gsum_u * gsum_u, acc_u)
    cache_vals = cache_vals.at[unique_slots].set(new_val_u)
    cache_acc = cache_acc.at[unique_slots].set(new_acc_u)
    return cache_vals, cache_acc


def make_cached_train_step(
    model,
    optimizer: optax.GradientTransformation,
    num_slots: int,
    dim: int,
    lr: float,
    eps: float,
    g_square_momentum: float,
    loss_fn: Callable = bce_loss,
    weight_bound: float = 0.0,
    capacity: int = 0,
    mesh=None,
) -> Callable:
    """step(state, cache_vals, cache_acc, non_id, slot_idx, cold_idx,
    cold_vals, cold_acc, inverse, unique_slots, label) -> (state,
    cache_vals, cache_acc, loss, pred, evicted_vals, evicted_acc)

    - slot_idx: (B, S) int32 — cache slot per (sample, slot) position;
    - cold_idx: (M,) int32 — slots receiving this batch's miss rows
      (padded entries point at the dummy slot);
    - cold_vals/cold_acc: (M, D) — miss rows (+ Adagrad state) fetched
      from the PS / victim buffer;
    - inverse: (B*S,) int32 — position -> index among this batch's
      distinct signs (the mapper computes it during its probe pass);
    - unique_slots: (B*S,) int32 — distinct index -> cache slot, tail
      past the distinct count padded with the dummy slot;
    - evicted_vals/evicted_acc: (M, D) — the PREVIOUS contents of
      cold_idx slots, read before the overwrite; the host writes these
      back to the PS keyed by the evicted signs.

    This is the single-id FAST path: a pure gather feeds the model, no
    segment scatter-add (see :func:`make_cached_bag_train_step` for
    variable-length bags).
    """

    def step(state: TrainState, cache_vals, cache_acc, non_id_tensors,
             slot_idx, cold_idx, cold_vals, cold_acc, inverse,
             unique_slots, label):
        cache_vals, cache_acc = _constrain_rows(mesh, cache_vals,
                                                cache_acc)
        cache_vals, cache_acc, evicted_vals, evicted_acc = _import_cold(
            cache_vals, cache_acc, cold_idx, cold_vals, cold_acc)

        gathered = cache_vals[slot_idx]  # (B, S, D)
        (loss, (pred, mutated)), (param_grads, emb_grad) = \
            _forward_backward(
                model, loss_fn, state, non_id_tensors, label, gathered,
                lambda g: [g[:, i, :] for i in range(num_slots)])
        new_state = _dense_update(optimizer, state, param_grads, mutated)

        # the dummy row sits at index `capacity` (NOT rows-1: under a
        # mesh the row count is padded past the dummy for even sharding)
        dummy = capacity if capacity else cache_vals.shape[0] - 1
        cache_vals, cache_acc = _sparse_adagrad_update(
            cache_vals, cache_acc, unique_slots, inverse,
            emb_grad.reshape(-1, dim), dummy, dim, lr, eps,
            g_square_momentum, weight_bound)
        cache_vals, cache_acc = _constrain_rows(mesh, cache_vals,
                                                cache_acc)
        return (new_state, cache_vals, cache_acc, loss, pred,
                evicted_vals, evicted_acc)

    # donate the cache arrays: they are carried state, updated in place
    return jax.jit(step, donate_argnums=(1, 2))


def make_cached_bag_train_step(
    model,
    optimizer: optax.GradientTransformation,
    num_slots: int,
    dim: int,
    lr: float,
    eps: float,
    g_square_momentum: float,
    loss_fn: Callable = bce_loss,
    weight_bound: float = 0.0,
    capacity: int = 0,
    mesh=None,
) -> Callable:
    """Multi-id (bag) variant of :func:`make_cached_train_step`.

    Every slot is a summed bag of variable length; the host flattens all
    (sample, slot) bags into one position list (length L, bucket-padded
    to Lpad) with a segment id per position. On device:

    - gather rows per position, segment-sum into per-(sample, slot)
      bags (matching the middleware's segment sum,
      worker/middleware.py postprocess_feature);
    - ``scale`` (B, S) applies sqrt_scaling (1/sqrt(bag size)) INSIDE
      the loss so autodiff routes the same scaling into the gradients
      (matching aggregate_gradients);
    - the backward re-gathers per-position grads through the segment
      map and dedup-sums them per distinct sign via ``inverse`` — a
      sign appearing twice in one bag contributes twice, exactly like
      the middleware's occurrence-level segment sum.

    step(state, cache_vals, cache_acc, non_id, flat_slot_idx (Lpad,),
    seg (Lpad,), scale (B, S), cold_idx, cold_vals, cold_acc,
    inverse (Lpad,), unique_slots (Lpad,), label) -> same outputs as
    the single-id step. Pad positions carry seg == B*S (a trash bag
    row) and flat_slot_idx == dummy, making them inert in both passes.
    """

    def step(state: TrainState, cache_vals, cache_acc, non_id_tensors,
             flat_slot_idx, seg, scale, cold_idx, cold_vals, cold_acc,
             inverse, unique_slots, label):
        cache_vals, cache_acc = _constrain_rows(mesh, cache_vals,
                                                cache_acc)
        cache_vals, cache_acc, evicted_vals, evicted_acc = _import_cold(
            cache_vals, cache_acc, cold_idx, cold_vals, cold_acc)

        batch = label.shape[0]
        rows = cache_vals[flat_slot_idx]                   # (Lpad, D)
        bags = jnp.zeros((batch * num_slots + 1, dim),
                         jnp.float32).at[seg].add(rows)
        gathered = bags[:batch * num_slots].reshape(batch, num_slots, dim)

        def emb_values_of(g):
            scaled = g * scale[:, :, None]
            return [scaled[:, i, :] for i in range(num_slots)]

        (loss, (pred, mutated)), (param_grads, bag_grad) = \
            _forward_backward(model, loss_fn, state, non_id_tensors,
                              label, gathered, emb_values_of)
        new_state = _dense_update(optimizer, state, param_grads, mutated)

        # per-position grads: pad positions (seg == B*S) read the zero
        # trash row, so their contribution to the dedup-sum is zero
        gpad = jnp.concatenate(
            [bag_grad.reshape(-1, dim), jnp.zeros((1, dim), jnp.float32)])
        pos_grad = gpad[seg]                               # (Lpad, D)
        dummy = capacity if capacity else cache_vals.shape[0] - 1
        cache_vals, cache_acc = _sparse_adagrad_update(
            cache_vals, cache_acc, unique_slots, inverse, pos_grad,
            dummy, dim, lr, eps, g_square_momentum, weight_bound)
        cache_vals, cache_acc = _constrain_rows(mesh, cache_vals,
                                                cache_acc)
        return (new_state, cache_vals, cache_acc, loss, pred,
                evicted_vals, evicted_acc)

    return jax.jit(step, donate_argnums=(1, 2))


def make_cached_eval_step(model, num_slots: int) -> Callable:
    """Pure gather + forward for signs fully resident in the cache."""

    def step(state: TrainState, cache_vals, non_id_tensors, slot_idx):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        gathered = cache_vals[slot_idx]
        emb_values = [gathered[:, i, :] for i in range(num_slots)]
        emb_inputs = _rebuild_embedding_inputs(emb_values, [None] * num_slots)
        return model.apply(variables, non_id_tensors, emb_inputs, train=False)

    return jax.jit(step)


def pad_to_bucket(n: int, buckets: Sequence[int]) -> int:
    """Pad a miss count to a fixed size so jit reuses a few compiled
    geometries instead of recompiling per distinct count."""
    for b in buckets:
        if n <= b:
            return b
    return int(np.ceil(n / buckets[-1]) * buckets[-1])
