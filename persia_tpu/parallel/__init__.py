"""Parallelism: device meshes, the jitted hybrid train step, and
device-resident sharded embeddings (see mesh.py / train.py /
device_embedding.py)."""

from persia_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch_pytree,
    table_sharding,
)
from persia_tpu.parallel.train import (
    TrainState,
    bce_loss,
    create_train_state,
    make_eval_step,
    make_train_step,
    split_embedding_inputs,
)
from persia_tpu.parallel.device_embedding import (
    DeviceEmbeddingBag,
    DeviceEmbeddingCollection,
)
from persia_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)
from persia_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_self_attention,
)

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "make_mesh", "batch_sharding", "replicated",
    "table_sharding", "shard_batch_pytree", "TrainState", "bce_loss",
    "create_train_state", "make_train_step", "make_eval_step",
    "split_embedding_inputs", "DeviceEmbeddingBag",
    "DeviceEmbeddingCollection", "ring_attention", "ring_self_attention",
    "ulysses_attention", "ulysses_self_attention",
]
