"""Fully device-resident training: dense tower + sharded HBM embeddings.

This mode composes :class:`DeviceEmbeddingCollection` (tables sharded over
the mesh's ``model`` axis) with any dense tower from the model zoo into a
single jitted train step — dense DP allreduce and embedding-shard
collectives are both XLA-inserted over ICI. It is the TPU-first
alternative to the CPU parameter-server path and the configuration the
multi-chip dry run exercises.
"""

from functools import partial
from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax.core import meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from persia_tpu.models.dlrm import DLRM
from persia_tpu.parallel.mesh import batch_sharding, make_mesh, replicated
from persia_tpu.parallel.train import bce_loss


class DeviceModeModel(nn.Module):
    """Dense tower + device embedding tables as one module.

    ``slot_specs``: sequence of (name, vocab_size, dim) for the hashed
    HBM tables; ``tower``: a model-zoo module instance.
    """

    slot_specs: Sequence[Any]
    tower: nn.Module

    @nn.compact
    def __call__(self, non_id_tensors, id_tensors: Dict[str, jnp.ndarray],
                 train: bool = False):
        from persia_tpu.parallel.device_embedding import (
            DeviceEmbeddingCollection,
        )

        embs = DeviceEmbeddingCollection(slot_specs=self.slot_specs)(id_tensors)
        return self.tower(non_id_tensors, embs, train=train)


def make_device_mode_trainer(
    model: nn.Module,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    sample_non_id,
    sample_ids: Dict[str, jnp.ndarray],
    loss_fn: Callable = bce_loss,
    seed: int = 0,
) -> Tuple[Any, Any, Callable]:
    """Initialize sharded params + opt state and build the jitted step.

    Returns (params, opt_state, step) where
    ``step(params, opt_state, non_id, ids, label) ->
    (params, opt_state, loss)``. Parameter shardings come from the
    modules' ``with_partitioning`` metadata; everything else replicates.
    """
    with mesh:
        variables = model.init(jax.random.key(seed), sample_non_id,
                               sample_ids, train=False)
    specs = nn.get_partition_spec(variables)["params"]
    params = meta.unbox(variables["params"])

    def shard_of(spec):
        if isinstance(spec, P):
            return NamedSharding(mesh, spec)
        return replicated(mesh)

    shardings = jax.tree_util.tree_map(
        shard_of, specs,
        is_leaf=lambda x: isinstance(x, P) or x is None,
    )
    params = jax.tree_util.tree_map(jax.device_put, params, shardings)
    opt_state = optimizer.init(params)

    def step(params, opt_state, non_id, ids, label):
        def compute_loss(params):
            pred = model.apply({"params": params}, non_id, ids, train=True)
            return loss_fn(pred, label)

        loss, grads = jax.value_and_grad(compute_loss)(params)
        updates, opt_state2 = optimizer.update(grads, opt_state, params)
        params2 = optax.apply_updates(params, updates)
        return params2, opt_state2, loss

    return params, opt_state, jax.jit(step, donate_argnums=(0, 1))


def criteo_like_specs(num_slots: int = 26, vocab: int = 1 << 16,
                      dim: int = 16):
    return [(f"slot_{i}", vocab, dim) for i in range(num_slots)]


def synthetic_device_batch(batch_size: int, num_dense: int,
                           slot_specs, sample_fixed_size: int = 1, seed=0):
    rng = np.random.default_rng(seed)
    non_id = [jnp.asarray(rng.normal(size=(batch_size, num_dense)),
                          jnp.float32)]
    ids = {
        name: jnp.asarray(
            rng.integers(1, 1 << 31, size=(batch_size, sample_fixed_size)),
            jnp.int32,
        )
        for name, _, _ in slot_specs
    }
    label = jnp.asarray(rng.integers(0, 2, size=(batch_size, 1)), jnp.float32)
    return non_id, ids, label
