"""The jitted hybrid train/eval step.

This is the TPU re-design of the reference's TrainCtx forward/backward
machinery (persia/ctx.py:893-1005): one compiled XLA program computes the
dense forward, the loss, the dense-parameter update, **and the gradients
w.r.t. the embedding inputs**, which exit the step as ordinary outputs and
are routed back to the parameter servers by the host (the async sparse
path). No GradScaler: bf16 compute has f32 exponent range, so the finite
check is a cheap debug hook rather than a correctness requirement.

Embedding inputs are split into differentiable values (float arrays) and
static index tensors (raw-slot int32 indices) so ``jax.grad`` sees only
float leaves.
"""

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct


@struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray


def bce_loss(pred: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Binary cross entropy on sigmoid outputs (adult-income parity)."""
    pred = jnp.clip(pred, 1e-7, 1.0 - 1e-7)
    return -jnp.mean(label * jnp.log(pred) + (1.0 - label) * jnp.log(1.0 - pred))


def _rebuild_embedding_inputs(
    emb_values: Sequence[jnp.ndarray], emb_indices: Sequence[Optional[jnp.ndarray]]
) -> List[Any]:
    return [
        v if idx is None else (v, idx)
        for v, idx in zip(emb_values, emb_indices)
    ]


def create_train_state(
    model, optimizer: optax.GradientTransformation, rng,
    non_id_tensors, embedding_inputs,
) -> TrainState:
    emb_values, emb_indices = split_embedding_inputs(embedding_inputs)
    variables = model.init(
        rng, non_id_tensors,
        _rebuild_embedding_inputs(emb_values, emb_indices), train=False,
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
        step=jnp.zeros((), jnp.int32),
    )


def split_embedding_inputs(embedding_inputs: Sequence[Any]):
    """Split mixed [array | (array, index)] inputs into float values and
    optional index tensors (None for summed slots)."""
    values, indices = [], []
    for e in embedding_inputs:
        if isinstance(e, (tuple, list)):
            values.append(e[0])
            indices.append(e[1])
        else:
            values.append(e)
            indices.append(None)
    return values, indices


def make_train_step(
    model,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable = bce_loss,
) -> Callable:
    """Build the jitted train step.

    step(state, non_id_tensors, emb_values, emb_indices, label)
      -> (state, loss, emb_grads, pred)

    ``emb_indices`` entries must be None or int32 arrays; they are part of
    the traced input pytree, not captured constants, so raw-slot index
    tensors change per batch without retracing.
    """

    def step(state: TrainState, non_id_tensors, emb_values, emb_indices, label):
        def compute_loss(params, emb_values):
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            emb_inputs = _rebuild_embedding_inputs(emb_values, emb_indices)
            out = model.apply(
                variables, non_id_tensors, emb_inputs, train=True,
                mutable=["batch_stats"] if state.batch_stats else [],
            )
            pred, mutated = out if isinstance(out, tuple) else (out, {})
            loss = loss_fn(pred, label)
            return loss, (pred, mutated)

        grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1), has_aux=True)
        (loss, (pred, mutated)), (param_grads, emb_grads) = grad_fn(
            state.params, emb_values
        )
        updates, new_opt_state = optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=mutated.get("batch_stats", state.batch_stats),
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        return new_state, loss, emb_grads, pred

    return jax.jit(step)


def make_packed_train_step(
    model,
    optimizer: optax.GradientTransformation,
    emb_shapes: Sequence[Tuple[int, ...]],
    loss_fn: Callable = bce_loss,
    wire_dtype=jnp.bfloat16,
) -> Callable:
    """Train step with **packed** embedding I/O for host-PS mode.

    All slots' embedding values enter as ONE flat ``wire_dtype`` array and
    all embedding gradients leave as ONE flat ``wire_dtype`` array — a
    single host->device and device->host transfer per step instead of one
    per slot. This is the TPU analogue of the reference's f16 wire format
    (persia-common/src/lib.rs:85-113) and matters enormously when the
    host<->device link has per-transfer latency.

    ``emb_shapes`` fixes each slot's (rows, dim); changing batch size
    retraces (shapes are static under XLA).

    step(state, non_id, flat_emb, emb_indices, label)
      -> (state, loss, flat_grads, pred)
    """
    sizes = [int(np.prod(s)) for s in emb_shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)]).tolist()

    def step(state: TrainState, non_id_tensors, flat_emb, emb_indices, label):
        emb_values = [
            flat_emb[offsets[i] : offsets[i + 1]]
            .reshape(emb_shapes[i])
            .astype(jnp.float32)
            for i in range(len(emb_shapes))
        ]

        def compute_loss(params, emb_values):
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            emb_inputs = _rebuild_embedding_inputs(emb_values, emb_indices)
            out = model.apply(
                variables, non_id_tensors, emb_inputs, train=True,
                mutable=["batch_stats"] if state.batch_stats else [],
            )
            pred, mutated = out if isinstance(out, tuple) else (out, {})
            loss = loss_fn(pred, label)
            return loss, (pred, mutated)

        grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1), has_aux=True)
        (loss, (pred, mutated)), (param_grads, emb_grads) = grad_fn(
            state.params, emb_values
        )
        updates, new_opt_state = optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=mutated.get("batch_stats", state.batch_stats),
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        flat_grads = jnp.concatenate(
            [g.ravel() for g in emb_grads]
        ).astype(wire_dtype)
        return new_state, loss, flat_grads, pred

    return jax.jit(step, donate_argnums=(0,))


# int8_ef quantization bucket: one f32 scale per this many elements.
# Scale overhead on the wire is 4B/1024B ≈ 0.4%; accuracy gain is large
# whenever parameter groups differ in gradient magnitude (one outlier
# layer no longer crushes every other layer's resolution).
_EF_BUCKET = 1024


def _ef_int8_mean(p: jnp.ndarray, axis_name: str, world: int):
    """Two-phase int8-compressed gradient mean over ``axis_name``.

    The TPU survivor of the reference's Bagua family (ByteGrad/QAdam,
    /root/reference/persia/distributed.py:204-410): on ICI a plain bf16
    pmean already wins, but on multi-host DCN meshes the wire is the
    bottleneck and 4x fewer bytes buys real throughput. Scheme:

    1. quantize the (error-compensated) local gradient to int8 with a
       per-replica scale per 1024-element bucket (``_EF_BUCKET``); the
       vector is zero-padded to a multiple of ``world * _EF_BUCKET`` so
       buckets never straddle shard boundaries;
    2. ``all_to_all`` the int8 shards AND their bucket scales (each
       device receives every replica's copy of ITS shard — int8 plus
       ~0.4% of scale floats on the wire), dequantize per bucket, sum
       in f32;
    3. requantize the mean shard per bucket and ``all_gather`` it back
       with its scales.

    Total wire bytes ~= 2 x size x 1B x 1.004 vs 2 x size x 4B for a
    ring f32 all-reduce. BOTH quantization stages feed back into ``err``
    (error-feedback SGD: the residual re-enters the next step's
    gradient, so the bias of deterministic rounding averages out and
    convergence tracks the uncompressed trajectory): stage 1 locally on
    every replica; stage 2 by the shard's owner, scaled by ``world``
    because a mean error times world is the aggregate error the owner
    must re-inject through its own (1/world-weighted) contribution.

    ``p``: f32 vector (grad + carried error). Returns (mean, new_err),
    both f32 of p's shape.
    """
    n = p.shape[0]
    pad = (-n) % (world * _EF_BUCKET)
    flat = jnp.pad(p, (0, pad))
    chunk = flat.shape[0] // world          # shard length, % _EF_BUCKET == 0
    nb_per = chunk // _EF_BUCKET            # buckets per shard
    buckets = flat.reshape(world * nb_per, _EF_BUCKET)
    scale = jnp.maximum(
        jnp.max(jnp.abs(buckets), axis=1) / 127.0, 1e-30)  # (world*nb_per,)
    q = jnp.clip(jnp.round(buckets / scale[:, None]),
                 -127, 127).astype(jnp.int8)
    err1 = (buckets - q.astype(jnp.float32) * scale[:, None]).reshape(-1)
    qs = q.reshape(world, chunk)
    # rows of recv are indexed by source replica: recv[s] = replica s's
    # int8 copy of THIS device's shard; srecv[s] = that copy's bucket
    # scales (all_to_all routes both identically)
    recv = jax.lax.all_to_all(qs, axis_name, split_axis=0, concat_axis=0)
    srecv = jax.lax.all_to_all(scale.reshape(world, nb_per), axis_name,
                               split_axis=0, concat_axis=0)
    deq = (recv.reshape(world, nb_per, _EF_BUCKET).astype(jnp.float32)
           * srecv[:, :, None])
    shard_mean = jnp.sum(deq, axis=0).reshape(chunk) / world
    mb = shard_mean.reshape(nb_per, _EF_BUCKET)
    s2 = jnp.maximum(jnp.max(jnp.abs(mb), axis=1) / 127.0, 1e-30)
    q2 = jnp.clip(jnp.round(mb / s2[:, None]), -127, 127).astype(jnp.int8)
    # stage-2 residual: this device owns shard `me` of the decoded mean
    err2 = (mb - q2.astype(jnp.float32) * s2[:, None]).reshape(chunk) * world
    me = jax.lax.axis_index(axis_name)
    own = jax.lax.dynamic_slice(err1, (me * chunk,), (chunk,))
    new_err = jax.lax.dynamic_update_slice(
        err1, own + err2, (me * chunk,))[:n]
    q2g = jax.lax.all_gather(q2, axis_name)   # (world, nb_per, _EF_BUCKET)
    s2g = jax.lax.all_gather(s2, axis_name)   # (world, nb_per)
    mean = (q2g.astype(jnp.float32) * s2g[:, :, None]).reshape(-1)[:n]
    return mean, new_err


def init_ef_state(params, mesh) -> jnp.ndarray:
    """Zero error-feedback residuals for ``grad_reduce_dtype="int8_ef"``:
    one flat f32 vector of the dense-param count per data-parallel
    replica, carried through the DDP step sharded over the data axis
    (each replica's residual is ITS OWN quantization error — it must
    not be replicated). Built under an explicit NamedSharding so a
    multi-host mesh (the mode's stated target) gets a global array, not
    a host-local one jit would refuse to reshard."""
    from jax.flatten_util import ravel_pytree

    from persia_tpu.parallel.mesh import DATA_AXIS, batch_sharding

    flat, _ = ravel_pytree(params)
    world = mesh.shape[DATA_AXIS]
    # computed UNDER the sharding (not device_put of a host-local
    # array, which would raise on a multi-process mesh's
    # non-addressable devices)
    return jax.jit(
        lambda: jnp.zeros((world, flat.shape[0]), jnp.float32),
        out_shardings=batch_sharding(mesh))()


def make_packed_train_step_ddp(
    model,
    optimizer: optax.GradientTransformation,
    slot_dims: Sequence[int],
    mesh,
    loss_fn: Callable = bce_loss,
    wire_dtype=jnp.bfloat16,
    grad_reduce_dtype=None,
) -> Callable:
    """Explicit data-parallel train step over a mesh via ``shard_map``.

    The reference offers DDP plus Bagua's communication algorithms
    (gradient_allreduce / low-precision variants,
    persia/distributed.py:204-410). The TPU equivalent is explicit
    collectives: each device computes gradients on its batch shard and
    the dense gradients cross ICI in ``jax.lax.pmean`` — optionally cast
    to ``grad_reduce_dtype`` (e.g. ``jnp.bfloat16``) first, halving
    all-reduce bytes the way Bagua's low-precision algorithms do.
    ``grad_reduce_dtype="int8_ef"`` goes further: an error-feedback
    int8 two-phase all-reduce (see :func:`_ef_int8_mean`) cutting wire
    bytes 4x — the Bagua ByteGrad analogue for multi-host DCN meshes.
    In that mode the step takes and returns an extra ``ef_state``
    residual (build with :func:`init_ef_state`). Decentralized/async
    peer algorithms have no XLA analogue and are deliberately absent:
    ICI all-reduce is already the fast path the reference's algorithms
    try to approximate.

    Requires every slot to be summed (pooled): embedding values enter
    batch-major as ONE ``(batch, sum(slot_dims))`` wire array so the
    batch axis shards cleanly. ``step(state, non_id, flat_emb,
    label) -> (state, loss, flat_grads, pred)`` with ``flat_grads``
    batch-major ``(batch, sum(slot_dims))`` in the wire dtype.
    """
    from jax.sharding import PartitionSpec as P

    from persia_tpu.parallel.ring_attention import _shard_map

    bounds = np.concatenate([[0], np.cumsum(slot_dims)]).tolist()
    data_spec = P("data")
    rep = P()
    ef_mode = grad_reduce_dtype == "int8_ef"
    from persia_tpu.parallel.mesh import DATA_AXIS

    world = mesh.shape[DATA_AXIS]

    def local_step(state: TrainState, non_id_tensors, flat_emb, label,
                   ef_state=None):
        emb_values = [
            flat_emb[:, bounds[i]:bounds[i + 1]].astype(jnp.float32)
            for i in range(len(slot_dims))
        ]

        def compute_loss(params, emb_values):
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            emb_inputs = _rebuild_embedding_inputs(
                emb_values, [None] * len(emb_values))
            out = model.apply(
                variables, non_id_tensors, emb_inputs, train=True,
                mutable=["batch_stats"] if state.batch_stats else [],
            )
            pred, mutated = out if isinstance(out, tuple) else (out, {})
            loss = loss_fn(pred, label)
            return loss, (pred, mutated)

        grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1),
                                     has_aux=True)
        (loss, (pred, mutated)), (param_grads, emb_grads) = grad_fn(
            state.params, emb_values
        )
        # the cross-replica exchange: dense grads ride ICI, optionally in
        # reduced precision (cast -> pmean -> f32, Bagua low-prec analogue)
        # or int8 with error feedback (ByteGrad analogue, 4x fewer bytes)
        if ef_mode:
            from jax.flatten_util import ravel_pytree

            flat_g, unravel = ravel_pytree(param_grads)
            mean_flat, new_err = _ef_int8_mean(
                flat_g + ef_state[0], "data", world)
            param_grads = unravel(mean_flat)
            new_ef_state = new_err[None, :]
        else:
            if grad_reduce_dtype is not None:
                param_grads = jax.tree_util.tree_map(
                    lambda g: g.astype(grad_reduce_dtype), param_grads)
            param_grads = jax.lax.pmean(param_grads, axis_name="data")
            if grad_reduce_dtype is not None:
                param_grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), param_grads)
        loss = jax.lax.pmean(loss, axis_name="data")
        if mutated:
            # BatchNorm running stats are computed per batch shard;
            # average them so every replica keeps identical buffers
            mutated = jax.lax.pmean(mutated, axis_name="data")
        # embedding grads are per-sample: they exit batch-sharded, no
        # collective needed (the async PS path owns their reduction)
        updates, new_opt_state = optimizer.update(
            param_grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params,
            batch_stats=mutated.get("batch_stats", state.batch_stats),
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        flat_grads = jnp.concatenate(emb_grads, axis=1).astype(wire_dtype)
        if ef_mode:
            return new_state, loss, flat_grads, pred, new_ef_state
        return new_state, loss, flat_grads, pred

    extra = (data_spec,) if ef_mode else ()
    sharded = _shard_map(
        local_step, mesh,
        in_specs=(rep, data_spec, data_spec, data_spec) + extra,
        out_specs=(rep, rep, data_spec, data_spec) + extra,
    )
    return jax.jit(sharded, donate_argnums=(0, 4) if ef_mode else (0,))


def pack_embedding_values_batch_major(
    emb_values: Sequence[np.ndarray], wire_dtype
) -> np.ndarray:
    """(batch, dim_i) summed-slot values -> one (batch, sum dims) array."""
    import ml_dtypes

    np_dtype = (
        ml_dtypes.bfloat16 if wire_dtype == jnp.bfloat16 else np.float32
    )
    flat = np.concatenate(
        [np.ascontiguousarray(v, dtype=np.float32) for v in emb_values],
        axis=1,
    )
    return flat.astype(np_dtype)


def unpack_embedding_grads_batch_major(
    flat: np.ndarray, slot_dims: Sequence[int]
) -> List[np.ndarray]:
    """(batch, sum dims) gradient blob -> per-slot (batch, dim_i) f32."""
    flat = np.asarray(flat)
    out = []
    pos = 0
    for d in slot_dims:
        out.append(flat[:, pos:pos + d].astype(np.float32))
        pos += d
    return out


def pack_embedding_values(emb_values: Sequence[np.ndarray], wire_dtype):
    """Host-side pack: concat + cast for the single upload."""
    import ml_dtypes  # ships with jax

    np_dtype = (
        ml_dtypes.bfloat16 if wire_dtype == jnp.bfloat16 else np.float32
    )
    flat = np.concatenate(
        [np.ascontiguousarray(v, dtype=np.float32).ravel() for v in emb_values]
    )
    return flat.astype(np_dtype)


def unpack_embedding_grads(
    flat: np.ndarray, emb_shapes: Sequence[Tuple[int, ...]]
) -> List[np.ndarray]:
    """Host-side unpack of the single gradient download (to f32)."""
    out = []
    pos = 0
    flat = np.asarray(flat)
    for shape in emb_shapes:
        n = int(np.prod(shape))
        out.append(flat[pos : pos + n].astype(np.float32).reshape(shape))
        pos += n
    return out


def make_eval_step(model) -> Callable:
    def step(state: TrainState, non_id_tensors, emb_values, emb_indices):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        emb_inputs = _rebuild_embedding_inputs(emb_values, emb_indices)
        return model.apply(variables, non_id_tensors, emb_inputs, train=False)

    return jax.jit(step)
