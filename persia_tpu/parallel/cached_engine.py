"""Orchestration for the device-resident embedding cache.

Ties together the host-side LRU sign->slot map + victim buffer
(persia_tpu/worker/device_cache.py) and the fused device step
(persia_tpu/parallel/cached_train.py), and owns the async write-back of
evicted rows to the parameter server. TrainCtx delegates here when
``device_cache_capacity`` is set.

Consistency model (documented trade, bounded like the reference's
staleness-based hybrid algorithm): cached rows train exclusively on
device; the PS copy of a cached sign is stale until the row is evicted
(write-back) or ``flush_all`` runs (eval/checkpoint entry points call
it). A cache miss reads the victim buffer first, so an evicted row
re-entering the cache never loses its in-flight update. Single-trainer
only: replicated per-trainer caches would fork hot rows' optimizer
state across trainers with no reconciliation. A device MESH is fine —
the cache is then ONE logical array row-sharded over the mesh by GSPMD
(see cached_train._row_sharding): still a single program, a single
writer, and per-device HBM that scales down with the device count.

Single-CONTROLLER only, enforced upstream: ``TrainCtx._ensure_cache``
raises NotImplementedError when ``jax.process_count() > 1``. On a
multi-process mesh the cache arrays' rows live on remote hosts this
process cannot address for miss imports / eviction write-backs, and
each process would run its own divergent sign->slot mapper. Lifting
this needs per-process row ownership (mapper sharded by
``jax.process_index``), not just GSPMD on the arrays.
"""

import itertools
import queue
import threading
from typing import List, Tuple

import numpy as np

from persia_tpu.logger import get_logger
from persia_tpu.parallel.cached_train import pad_to_bucket
from persia_tpu.worker.device_cache import VictimBuffer, make_sign_slot_map

logger = get_logger(__name__)

_BUCKETS = (64, 256, 1024, 4096, 16384, 65536)

# monotone id per DeviceCacheEngine in this process (metric label)
_ENGINE_SEQ = itertools.count()


class DeviceCacheEngine:
    def __init__(self, worker, capacity: int, num_slots: int, dim: int,
                 acc_init: float, mesh=None, sqrt_scaling=None,
                 admission: str = None):
        from persia_tpu import knobs

        self.worker = worker
        self.capacity = int(capacity)
        self.num_slots = int(num_slots)
        self.dim = int(dim)
        self.acc_init = float(acc_init)
        self.mesh = mesh
        # per-slot sqrt-scaling flags (bag mode only; see prepare_bags)
        self.sqrt_scaling = list(sqrt_scaling or [])
        # admission policy of the HBM tier: "lru" (legacy) or "hotness"
        # (frequency-gated TieredSignSlotMap; PERSIA_TIER_ADMIT)
        self.admission = admission or knobs.get("PERSIA_TIER_ADMIT")
        self.mapper = make_sign_slot_map(capacity, self.admission)
        self.victims = VictimBuffer()
        from persia_tpu.parallel.cached_train import init_cache_arrays

        self.cache_vals, self.cache_acc = init_cache_arrays(
            capacity, dim, acc_init, mesh=mesh)
        self._flush_q: "queue.Queue" = queue.Queue()
        self._flush_token = 0
        self._flush_err: List[BaseException] = []
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name="device-cache-flush")
        self._flush_thread.start()
        self.wire_bytes_saved = 0  # vs the packed upload+download path
        # registry twins of the mapper/write-back counters, so the
        # trainer sidecar (and the fleet federation scraping it) can
        # watch tier-ladder health; bumped by deltas once per batch —
        # the per-sign hot path never touches a locked counter
        from persia_tpu.metrics import default_registry

        reg = default_registry()
        # engine-identity label: two live engines in one process (A/B
        # benches, multi-ctx tests) must not share series — a blended
        # hit ratio and a last-writer-wins resident gauge would lie to
        # the hit-collapse SLO
        lbl = {"dim": str(dim), "engine": str(next(_ENGINE_SEQ))}
        self._m_probes = reg.counter(
            "device_cache_probes_total", lbl,
            help_text="sign positions probed against the device cache "
                      "(hits + misses) — the hit-rate denominator")
        self._m_hits = reg.counter(
            "device_cache_hits_total", lbl,
            help_text="device-cache hits (rows served from HBM, no "
                      "host<->device or PS traffic)")
        self._m_misses = reg.counter(
            "device_cache_misses_total", lbl,
            help_text="device-cache misses (rows imported from the PS "
                      "tier / victim buffer)")
        self._m_evictions = reg.counter(
            "device_cache_evictions_total", lbl,
            help_text="rows evicted from the device cache (each queues "
                      "one PS write-back)")
        self._m_promotions = reg.counter(
            "device_cache_promotions_total", lbl,
            help_text="window->protected promotions of the "
                      "hotness-admitted mapper (0 under LRU admission)")
        self._m_writebacks = reg.counter(
            "device_cache_writeback_rows_total", lbl,
            help_text="rows written back to the PS tier (eviction "
                      "flushes + flush_all)")
        self._m_resident = reg.gauge(
            "device_cache_resident_rows", lbl,
            help_text="signs currently resident in the device cache")
        self._counted = (0, 0, 0, 0)  # hits/misses/evictions/promotions

    def _publish_counters(self):
        """Delta the mapper's plain-int counters into their registry
        twins (once per batch, after assign)."""
        m = self.mapper
        h, mi, ev, pr = (m.hits, m.misses, m.evictions,
                         getattr(m, "promotions", 0))
        ph, pm, pe, pp = self._counted
        self._counted = (h, mi, ev, pr)
        if h - ph:
            self._m_hits.inc(h - ph)
        if mi - pm:
            self._m_misses.inc(mi - pm)
        if (h - ph) + (mi - pm):
            self._m_probes.inc((h - ph) + (mi - pm))
        if ev - pe:
            self._m_evictions.inc(ev - pe)
        if pr - pp:
            self._m_promotions.inc(pr - pp)
        self._m_resident.set(len(m))

    # --- per-batch host work --------------------------------------------

    def prepare(self, id_type_features) -> Tuple[
            np.ndarray, np.ndarray, np.ndarray, np.ndarray,
            np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Map this batch's signs and fetch its miss rows.

        Returns (slot_idx (B,S) i32, cold_idx (Mpad,) i32, cold_vals
        (Mpad, D) f32, cold_acc (Mpad, D) f32, evicted_signs (Mpad,)
        u64, evicted_mask (Mpad,) bool, inverse (B*S,) i32,
        unique_slots (B*S,) i32). Runs on the ordered training path —
        batch order IS the LRU order.
        """
        # single-id slots: f.signs is exactly one sign per sample (the
        # ctx-level guard verified this before building the engine)
        signs = np.stack([f.signs for f in id_type_features], axis=1)
        batch, num_slots = signs.shape
        flat_signs = signs.reshape(-1)
        res = self.mapper.assign(flat_signs)
        self._publish_counters()
        # tail past the distinct count is uninitialized: point it at the
        # dummy slot so the device update's pad rows are inert
        unique_slots = res.unique_slots
        unique_slots[res.n_unique:] = self.capacity
        slot_idx = res.slots.reshape(batch, num_slots)
        (cold_idx, cold_vals, cold_acc, evicted_signs, evicted_mask,
         mpad) = self._miss_import(flat_signs, res)
        # bookkeeping: what the packed path would have moved for this
        # batch (bf16 both ways) minus what the cached path moves
        packed = batch * num_slots * self.dim * 2 * 2
        moved = (slot_idx.nbytes + cold_idx.nbytes + cold_vals.nbytes
                 + cold_acc.nbytes + (2 * mpad * self.dim * 4))
        self.wire_bytes_saved += max(0, packed - moved)
        return (slot_idx, cold_idx, cold_vals, cold_acc, evicted_signs,
                evicted_mask, res.inverse, unique_slots)

    def prepare_bags(self, id_type_features) -> tuple:
        """Multi-id variant of :meth:`prepare` for summed bag slots.

        Flattens every (sample, slot) bag into one position list
        (slot-major), maps it through the same LRU assign, and returns
        (flat_slot_idx (Lpad,) i32, seg (Lpad,) i32, scale (B, S) f32,
        cold_idx, cold_vals, cold_acc, evicted_signs, evicted_mask,
        inverse (Lpad,) i32, unique_slots (Lpad,) i32) for
        ``make_cached_bag_train_step``. Pad positions carry
        seg == B*S (the trash bag row) and the dummy slot."""
        batch = id_type_features[0].batch_size
        num_slots = len(id_type_features)
        sign_parts, seg_parts, counts = [], [], []
        for s, f in enumerate(id_type_features):
            off = f.offsets.astype(np.int64)
            cnt = np.diff(off)
            counts.append(cnt)
            sign_parts.append(f.signs)
            seg_parts.append(
                np.repeat(np.arange(batch, dtype=np.int64) * num_slots + s,
                          cnt))
        flat_signs = np.concatenate(sign_parts).astype(np.uint64)
        seg = np.concatenate(seg_parts)
        n = len(flat_signs)
        res = self.mapper.assign(flat_signs)
        self._publish_counters()
        lpad = pad_to_bucket(max(n, 1), _BUCKETS)
        flat_slot_idx = np.full(lpad, self.capacity, np.int32)
        flat_slot_idx[:n] = res.slots
        seg_pad = np.full(lpad, batch * num_slots, np.int32)
        seg_pad[:n] = seg
        # pad inverse entries add the (zero) trash-row grad to distinct
        # index 0 — adding zeros is inert
        inverse = np.zeros(lpad, np.int32)
        inverse[:n] = res.inverse
        unique_slots = np.full(lpad, self.capacity, np.int32)
        unique_slots[:res.n_unique] = res.unique_slots[:res.n_unique]
        # per-(sample, slot) sqrt scaling, matching the middleware's
        # 1/sqrt(max(bag size, 1)) (worker/middleware.py)
        scale = np.ones((batch, num_slots), np.float32)
        for s in range(num_slots):
            if self.sqrt_scaling and self.sqrt_scaling[s]:
                scale[:, s] = 1.0 / np.sqrt(
                    np.maximum(counts[s], 1).astype(np.float32))
        (cold_idx, cold_vals, cold_acc, evicted_signs, evicted_mask,
         mpad) = self._miss_import(flat_signs, res)
        packed = batch * num_slots * self.dim * 2 * 2
        moved = (flat_slot_idx.nbytes + seg_pad.nbytes + scale.nbytes
                 + cold_idx.nbytes + cold_vals.nbytes + cold_acc.nbytes
                 + (2 * mpad * self.dim * 4))
        self.wire_bytes_saved += max(0, packed - moved)
        return (flat_slot_idx, seg_pad, scale, cold_idx, cold_vals,
                cold_acc, evicted_signs, evicted_mask, inverse,
                unique_slots)

    def _miss_import(self, flat_signs, res):
        """Fetch this batch's miss rows (victim buffer first, then PS),
        bucket-padded. Returns (cold_idx, cold_vals, cold_acc,
        evicted_signs, evicted_mask, mpad)."""
        slots, miss_pos, evicted, emask = (res.slots, res.miss_pos,
                                           res.evicted_signs,
                                           res.evicted_mask)
        miss_signs = flat_signs[miss_pos]
        m = len(miss_signs)
        mpad = pad_to_bucket(max(m, 1), _BUCKETS)
        cold_idx = np.full(mpad, self.capacity, np.int32)  # pad -> dummy
        cold_vals = np.zeros((mpad, self.dim), np.float32)
        cold_acc = np.full((mpad, self.dim), self.acc_init, np.float32)
        evicted_signs = np.zeros(mpad, np.uint64)
        evicted_mask = np.zeros(mpad, bool)
        if m:
            cold_idx[:m] = slots[miss_pos]
            evicted_signs[:m] = evicted
            evicted_mask[:m] = emask
            # victim buffer first: an evicted row still in flight is the
            # authoritative copy (the PS write-back may not have landed).
            # Entries are (ev_vals, ev_acc, row) with possibly-device
            # arrays; np.asarray blocks until the step that produced
            # them finished, so the value read here is never stale.
            need_ps = []
            for i, s in enumerate(miss_signs):
                v = self.victims.take(int(s))
                if v is not None:
                    vvals, vacc, row = v
                    cold_vals[i] = np.asarray(vvals)[row]
                    cold_acc[i] = np.asarray(vacc)[row]
                else:
                    need_ps.append(i)
            if need_ps:
                idx = np.asarray(need_ps)
                vals, state = self.worker.lookup_rows_with_state(
                    miss_signs[idx], self.dim,
                    default_state=self.acc_init)
                cold_vals[idx] = vals
                if state.shape[1] == self.dim:
                    cold_acc[idx] = state
                # (space != dim would mean a non-matching optimizer; the
                # ctx-level guard rejects that before the engine exists)
        return (cold_idx, cold_vals, cold_acc, evicted_signs,
                evicted_mask, mpad)

    def finish(self, evicted_signs: np.ndarray, evicted_mask: np.ndarray,
               ev_vals, ev_acc) -> None:
        """Queue evicted rows for async PS write-back. ``ev_vals`` /
        ``ev_acc`` may be jax device arrays; the d2h materialization
        happens on the flush thread. The mask (not sign truthiness)
        selects real evictions — sign 0 is a legal sign."""
        if self._flush_err:
            raise self._flush_err[0]
        real = list(np.nonzero(evicted_mask)[0])
        if not real:
            return
        self._flush_token += 1
        token = self._flush_token
        for i in real:
            # the buffered entry holds the device arrays themselves: a
            # miss racing the write-back materializes its row directly,
            # so there is no window where the PS copy (stale) is the only
            # readable one
            self.victims.put(int(evicted_signs[i]),
                             (ev_vals, ev_acc, i), token=token)
        self._flush_q.put((token, evicted_signs, real, ev_vals, ev_acc))

    # --- write-back -------------------------------------------------------

    def _flush_loop(self):
        while True:
            job = self._flush_q.get()
            if job is None:
                self._flush_q.task_done()
                return
            try:
                self._flush_job(*job)
            except BaseException as e:  # surfaced on the next finish()
                self._flush_err.append(e)
            finally:
                self._flush_q.task_done()

    def _flush_job(self, token, evicted_signs, real, ev_vals, ev_acc):
        vals = np.asarray(ev_vals)  # d2h here, off the training thread
        acc = np.asarray(ev_acc)
        todo_signs, todo_vecs = [], []
        for i in real:
            sign = int(evicted_signs[i])
            # token-matched PEEK (no removal yet): absent or different
            # token => the miss path reclaimed the row (the cache copy is
            # authoritative again) or a newer eviction owns the sign —
            # either way writing our older value would clobber fresher
            # state, so skip.
            if self.victims.peek_if(sign, token) is None:
                continue
            todo_signs.append(sign)
            todo_vecs.append(np.concatenate([vals[i], acc[i]]))
        if todo_signs:
            self.worker.set_rows(
                np.asarray(todo_signs, np.uint64),
                np.stack(todo_vecs), self.dim)
            self._m_writebacks.inc(len(todo_signs))
        # remove only AFTER the PS write landed: a miss racing the write
        # must keep finding the pending entry, otherwise it would read
        # the stale pre-write PS row. A miss that took the entry mid-
        # write is also fine — the PS got the same value, and the cache
        # copy stays authoritative.
        for sign in todo_signs:
            self.victims.take_if(sign, token)

    def flush_all(self) -> int:
        """Write every cached row (+ the victim buffer) back to the PS.
        Called before eval/checkpoint so the PS is authoritative. The
        cache stays valid for continued training. Returns rows written."""
        self._drain_flush_queue()
        signs, slots = self.mapper.signs_and_slots()
        n = len(signs)
        if n:
            vals = np.asarray(self.cache_vals)[slots]
            acc = np.asarray(self.cache_acc)[slots]
            vecs = np.concatenate([vals, acc], axis=1)
            self.worker.set_rows(signs, vecs, self.dim)
            self._m_writebacks.inc(n)
        while True:
            item = self.victims.pop_any()
            if item is None:
                break
            # payloads are always (ev_vals, ev_acc, row) triples; after
            # the queue drain this loop is normally empty, but a row left
            # behind (e.g. flush after close()) must still write back
            sign, (vvals, vacc, row) = item
            vec = np.concatenate(
                [np.asarray(vvals)[row], np.asarray(vacc)[row]])
            self.worker.set_rows(
                np.asarray([sign], np.uint64), vec[None, :], self.dim)
            self._m_writebacks.inc()
            n += 1
        return n

    def invalidate(self) -> None:
        """Drop every cached row WITHOUT writing back — checkpoint
        restore: the cache predates the loaded values, so both serving
        further hits from it and flushing it would clobber the restore.
        Queued write-backs are drained first and their PS writes land
        BEFORE the restore overwrites them (load happens after this
        returns), which is the correct order."""
        self._drain_flush_queue()
        while self.victims.pop_any() is not None:
            pass
        self.mapper = make_sign_slot_map(self.capacity, self.admission)
        self._counted = (0, 0, 0, 0)
        self._m_resident.set(0)
        from persia_tpu.parallel.cached_train import init_cache_arrays

        self.cache_vals, self.cache_acc = init_cache_arrays(
            self.capacity, self.dim, self.acc_init, mesh=self.mesh)

    def _drain_flush_queue(self):
        """Block until queued write-backs complete (order matters: a
        flush_all snapshot must not be overwritten by an older queued
        eviction landing later). task_done bookkeeping in _flush_loop
        makes join() cover the in-progress job too."""
        self._flush_q.join()
        if self._flush_err:
            raise self._flush_err[0]

    def close(self):
        """Stop the flush thread (TrainCtx.__exit__). The engine's state
        (cache arrays, mapper) stays valid; ensure_open() restarts the
        thread if the ctx is re-entered."""
        if self._flush_thread.is_alive():
            self._flush_q.put(None)
            self._flush_thread.join(timeout=30)

    def ensure_open(self):
        if not self._flush_thread.is_alive():
            # a recorded flush error belongs to the previous life of the
            # ctx (it was raised at — or superseded by — exit); keeping
            # it would make every finish()/flush of the re-entered ctx
            # re-raise a stale, already-surfaced exception forever.
            # But if the ctx exited on an UNRELATED exception, the exit
            # path skipped flush_device_cache and nothing ever raised
            # this — write-backs were lost silently. Leave a trace.
            if self._flush_err:
                logger.warning(
                    "device-cache: discarding %d unraised write-back "
                    "error(s) from the previous ctx life (first: %r) — "
                    "PS updates queued before the abnormal exit were "
                    "lost", len(self._flush_err), self._flush_err[0])
            self._flush_err.clear()
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="device-cache-flush")
            self._flush_thread.start()

    @property
    def hit_rate(self) -> float:
        return self.mapper.hit_rate
