"""Device-mesh helpers: the TPU-native replacement for the reference's
DDP/NCCL process groups (persia/distributed.py:74-201).

A PERSIA-style job maps onto a 2-D mesh:

- ``data`` axis — synchronous data parallelism of the dense tower (the
  reference's DDP allreduce becomes an XLA psum over ICI)
- ``model`` axis — sharding of device-resident embedding tables (the
  TPU-first alternative to CPU parameter servers; CPU-PS mode uses a
  1-D data mesh)
"""

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    axis_names: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    Default shape puts every device on the data axis — pure DP, the
    reference's topology. Pass e.g. ``shape=(4, 2)`` for hybrid
    DP x embedding-sharding.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    if shape[0] * shape[1] != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Shard embedding-table rows over the model axis."""
    return NamedSharding(mesh, P(MODEL_AXIS, None))


def shard_batch_pytree(tree, mesh: Mesh):
    """device_put every array leaf with its batch dim over the data axis.

    Leaves whose leading dim does not divide the data-axis size are
    replicated instead — notably raw-slot distinct-embedding tensors of
    capacity batch*sample_fixed_size+1, which are indexed globally and
    must be visible to every data shard. Scalars are replicated.
    """
    bsh = batch_sharding(mesh)
    rep = replicated(mesh)
    data_size = mesh.shape[DATA_AXIS]

    def place(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] % data_size == 0:
            return jax.device_put(x, bsh)
        return jax.device_put(x, rep)

    return jax.tree_util.tree_map(place, tree)
