"""Ulysses-style all-to-all sequence parallelism.

The complement to :mod:`persia_tpu.parallel.ring_attention` (the
reference has neither — SURVEY.md §5 — but long-context machinery is
first-class here): instead of rotating K/V blocks around a ring, one
``all_to_all`` re-partitions the sharding from *sequence* to *heads*, so
every device runs ordinary full attention over the complete sequence for
its head subset, and a second ``all_to_all`` restores sequence sharding
(the DeepSpeed-Ulysses formulation). Communication is O(T·D/P) per
device — the same volume as ring attention but in two bulk collectives
that XLA schedules over ICI, which wins when heads are plentiful and the
per-step latency of P ppermutes would dominate.

Trade-off vs ring: Ulysses needs ``heads % axis_size == 0`` and holds
the full-sequence K/V per device for 1/P of the heads (activations
O(T·H/P·Dh) vs ring's O(T/P·H·Dh) — same total, different shape); ring
never holds the full sequence but pays P permute steps. The per-head
attention itself runs through :func:`local_flash_attention` (chunked
online-softmax), so score memory stays O(T·chunk), not O(T²). Pick per
topology; both share the reference_attention semantics exactly.
"""

from jax import lax
from jax.sharding import Mesh

from persia_tpu.parallel.ring_attention import (
    local_flash_attention,
    seq_sharded,
)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      chunk_size: int = 512, kv_mask=None,
                      impl: str = "xla"):
    """Inside shard_map: q/k/v (B, H, T_local, Dh) with the sequence
    sharded over ``axis_name``; H must divide by the axis size; kv_mask
    optional (B, T_local) of valid keys on this shard.

    all_to_all to (B, H_local, T, Dh), full attention per head subset,
    all_to_all back to (B, H, T_local, Dh)."""
    import jax.numpy as jnp

    axis_size = lax.psum(1, axis_name)
    heads = q.shape[1]
    if heads % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by the sequence "
            f"axis size ({axis_size}); use ring attention otherwise")

    def seq_to_heads(x):
        # (B, H, T/P, Dh) -> (B, H/P, T, Dh): split the head axis across
        # devices, gather the sequence axis
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    if kv_mask is None:
        kv_mask = jnp.ones((q.shape[0], k.shape[2]), bool)
    # the key mask has no head axis: gather the full sequence mask
    full_mask = lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # chunked flash: O(T·chunk) score memory, not the O(T²) matrix a
    # naive softmax(qkᵀ)v would materialize at long context.
    # impl="pallas" keeps the o/m/l running statistics in VMEM across
    # k-blocks (the XLA scan round-trips them through HBM per chunk)
    if impl == "pallas":
        from persia_tpu.ops.flash_attention import flash_attention_masked

        out = flash_attention_masked(q, k, v, kv_mask=full_mask,
                                     causal=causal, block_q=chunk_size,
                                     block_k=chunk_size)
    else:
        out = local_flash_attention(q, k, v, causal=causal,
                                    chunk_size=chunk_size,
                                    kv_mask=full_mask)
    return heads_to_seq(out)


def ulysses_self_attention(q, k, v, mesh: Mesh, seq_axis: str = "model",
                           causal: bool = False, chunk_size: int = 512,
                           kv_mask=None, impl: str = "xla"):
    """shard_map wrapper: q/k/v (B, H, T, Dh) with T sharded on
    ``seq_axis``; returns attention output with the same sharding
    (drop-in for :func:`ring_self_attention`). ``impl``: "xla" | "pallas"
    picks the per-device flash kernel."""
    import jax.numpy as jnp

    if kv_mask is None:
        kv_mask = jnp.ones((q.shape[0], k.shape[2]), bool)

    def inner(q, k, v, m):
        return ulysses_attention(q, k, v, axis_name=seq_axis, causal=causal,
                                 chunk_size=chunk_size, kv_mask=m,
                                 impl=impl)

    return seq_sharded(inner, mesh, seq_axis)(q, k, v, kv_mask)
