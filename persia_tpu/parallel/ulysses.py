"""Ulysses-style all-to-all sequence parallelism.

The complement to :mod:`persia_tpu.parallel.ring_attention` (the
reference has neither — SURVEY.md §5 — but long-context machinery is
first-class here): instead of rotating K/V blocks around a ring, one
``all_to_all`` re-partitions the sharding from *sequence* to *heads*, so
every device runs ordinary full attention over the complete sequence for
its head subset, and a second ``all_to_all`` restores sequence sharding
(the DeepSpeed-Ulysses formulation). Communication is O(T·D/P) per
device — the same volume as ring attention but in two bulk collectives
that XLA schedules over ICI, which wins when heads are plentiful and the
per-step latency of P ppermutes would dominate.

Trade-off vs ring: Ulysses needs ``heads % axis_size == 0`` and holds
the full-sequence K/V per device for 1/P of the heads (activations
O(T·H/P·Dh) vs ring's O(T/P·H·Dh) — same total, different shape); ring
never holds the full sequence but pays P permute steps. The per-head
attention itself runs through the blockwise online-softmax kernel
(``ring_attention`` with no axis = single-block flash attention), so
score memory stays O(T·block), not O(T²). Pick per topology; both share
the reference_attention semantics exactly.
"""

import functools

from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from persia_tpu.parallel.ring_attention import ring_attention


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False):
    """Inside shard_map: q/k/v (B, H, T_local, Dh) with the sequence
    sharded over ``axis_name``; H must divide by the axis size.

    all_to_all to (B, H_local, T, Dh), full attention per head subset,
    all_to_all back to (B, H, T_local, Dh)."""
    axis_size = lax.psum(1, axis_name)
    heads = q.shape[1]
    if heads % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by the sequence "
            f"axis size ({axis_size}); use ring attention otherwise")

    def seq_to_heads(x):
        # (B, H, T/P, Dh) -> (B, H/P, T, Dh): split the head axis across
        # devices, gather the sequence axis
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # single-block flash kernel: O(T·block) score memory, not the O(T²)
    # matrix a naive softmax(qkᵀ)v would materialize at long context
    out = ring_attention(q, k, v, axis_name=None, causal=causal)
    return heads_to_seq(out)


def ulysses_self_attention(q, k, v, mesh: Mesh, seq_axis: str = "model",
                           causal: bool = False):
    """shard_map wrapper: q/k/v (B, H, T, Dh) with T sharded on
    ``seq_axis``; returns attention output with the same sharding
    (drop-in for :func:`ring_self_attention`)."""
    from jax.experimental.shard_map import shard_map

    spec = P(None, None, seq_axis, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)
