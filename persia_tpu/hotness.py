"""Workload hotness telemetry: bounded-memory, mergeable per-table
access sketches over the embedding lookup stream.

PERSIA's hybrid split is justified by two workload facts this stack
could not, until now, measure about itself: recommendation id traffic
is zipfian (a few percent of rows serve most lookups — the premise of
the HBM<->host tier ladder, ROADMAP item 2), and async updates ride a
*bounded* staleness (item 3). This module is the measurement layer for
the first fact; the staleness/freshness half lives in
:mod:`persia_tpu.pipeline`, :mod:`persia_tpu.service.ps_service`, and
:mod:`persia_tpu.inc_update`.

Three classic streaming summaries, composed per (table, internal
shard):

- **Space-Saving** (Metwally et al. '05) keeps the top-K heavy hitters
  with per-item count and error bound: ``count - err <= true <= count``
  and every sign with true frequency > total/K is guaranteed present.
- **Count-Min** (Cormode & Muthukrishnan '05) answers a frequency
  upper bound for *any* sign in O(depth); here it doubles as the
  admission filter that keeps the Space-Saving update off the hot
  path for provably-cold signs (the vectorized estimate gates the
  per-sign Python work, so a steady cold stream costs a few numpy ops
  per batch, not K heap operations).
- **HyperLogLog** (reused from :mod:`persia_tpu.worker.monitor`, fed
  the same FarmHash64 values) estimates the distinct-row count — the
  denominator of every "top p% of rows" statement.

All three are *mergeable*: CM cells and Space-Saving counts add,
HLL registers max. :func:`merge_snapshots` is exact-commutative and
exact-associative (counts are integers, and integer sums in float64
are exact), which is what lets one PS replica's per-shard summaries
roll up into a table view, and the fleet monitor roll N replicas into
one cross-shard coverage curve whose totals equal the sum of the
parts (``bench.py --mode telemetry`` pins this).

**Lock discipline** (persialint-enforced): :class:`HotnessTracker`
owns one lock per internal shard and is the only writer of its cells;
the holder calls :meth:`HotnessTracker.observe` *outside* its own
shard locks, so the tracker's locks are leaves — no nesting, no
ordering hazard. Methods suffixed ``_locked`` follow the repo
convention: the caller holds the shard's lock.

The disabled path is free: an unarmed holder carries ``hotness =
None`` and pays one ``is not None`` test per lookup call.
"""

import base64
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from persia_tpu import knobs
from persia_tpu.hashing import farmhash64_np
from persia_tpu.worker.monitor import HyperLogLog

SNAPSHOT_VERSION = 1

# coverage-curve evaluation grid: fraction of (estimated) unique rows
DEFAULT_COVERAGE_FRACS = (0.0001, 0.0005, 0.001, 0.002, 0.005, 0.01,
                          0.02, 0.05, 0.1, 0.2, 0.5, 1.0)


class SpaceSaving:
    """Space-Saving heavy-hitter summary of at most ``k`` items,
    array-backed and batch-updated.

    The summary lives in three aligned numpy arrays (signs sorted
    ascending, counts, inherited errors), so one lookup batch costs a
    handful of vectorized ops instead of per-item heap work — the
    difference between telemetry that fits a 3% cycle budget and
    telemetry that doesn't. Admissions at capacity evict the batch's
    worth of current minima in one ``argpartition``; each admitted
    sign inherits one evicted count as its error, largest newcomer
    paired with smallest evictee. That batched eviction is the one
    deviation from the sequential textbook algorithm (which re-reads
    the min after every eviction), and it preserves both invariants
    the property tests pin: ``count >= true`` (a newcomer's unseen
    prior occurrences are <= the summary min <= every evicted count)
    and ``count - err <= true``.

    Not thread-safe on purpose: one instance lives under one shard
    lock of :class:`HotnessTracker` (or in single-threaded test code).
    """

    def __init__(self, k: int):
        if k <= 0:
            raise ValueError("k must be positive")
        self.k = k
        self._signs = np.empty(0, dtype=np.uint64)
        self._counts = np.empty(0, dtype=np.float64)  # integer-valued
        self._errs = np.empty(0, dtype=np.float64)

    def __len__(self) -> int:
        return len(self._signs)

    def min_count(self) -> int:
        """Smallest tracked count (0 while below capacity)."""
        if len(self._signs) < self.k:
            return 0
        return int(self._counts.min())

    def offer(self, sign: int, inc: int = 1):
        """Single-item offer — exactly the sequential reference
        algorithm (a 1-item batch has nothing to batch)."""
        self.offer_many(np.array([sign], dtype=np.uint64),
                        np.array([inc], dtype=np.float64))

    def count_of(self, sign: int) -> int:
        """Tracked count of one sign (0 when untracked) — the point
        query the device-cache admission ladder gates on."""
        n = len(self._signs)
        if n == 0:
            return 0
        pos = min(int(np.searchsorted(self._signs, np.uint64(sign))),
                  n - 1)
        if int(self._signs[pos]) != int(sign):
            return 0
        return int(self._counts[pos])

    def counts_of(self, signs: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`count_of` (0 for untracked signs) — the
        admission mapper bulk-queries its whole victim queue once per
        batch instead of point-probing the summary per miss."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        out = np.zeros(len(signs), dtype=np.int64)
        if len(signs) == 0:
            return out
        mask, pos = self.member_mask(signs)
        if mask.any():
            out[mask] = self._counts[pos[mask]].astype(np.int64)
        return out

    def decay(self, factor: float = 0.5):
        """Age every tracked count (and its error bound) by ``factor``
        — W-TinyLFU-style periodic halving. Without aging, a
        formerly-hot row's lifetime count blocks admission of newly
        hot rows forever after a hot-set shift; halving preserves the
        relative order of counts while letting recent traffic win in
        bounded time. Admission-side use only (the telemetry trackers
        never decay — their merge algebra needs raw additive counts)."""
        np.floor(self._counts * factor, out=self._counts)
        np.floor(self._errs * factor, out=self._errs)

    def member_mask(self, signs: np.ndarray) -> np.ndarray:
        """Vectorized membership test against the sorted sign array.
        Returns (mask, positions-into-the-summary)."""
        if len(self._signs) == 0:
            return (np.zeros(len(signs), dtype=bool),
                    np.zeros(len(signs), dtype=np.int64))
        pos = np.searchsorted(self._signs, signs).clip(
            max=len(self._signs) - 1)
        return self._signs[pos] == signs, pos

    def offer_many(self, signs: np.ndarray, counts: np.ndarray,
                   estimates: Optional[np.ndarray] = None):
        """Batch offer of DISTINCT signs with the Count-Min admission
        filter: when the summary is full, an untracked sign is worth
        admission work only if its CM frequency upper bound reaches
        the current minimum (below it, the sequential algorithm would
        admit and immediately lose it to the next cold sign — skipping
        it only forgoes churn). Tracked members always take their
        increments."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        counts = np.asarray(counts, dtype=np.float64)
        member, pos = self.member_mask(signs)
        if member.any():
            # distinct signs -> distinct positions, plain fancy add
            self._counts[pos[member]] += counts[member]
        new_s, new_c = signs[~member], counts[~member]
        if len(new_s) == 0:
            return
        if estimates is not None and len(self._signs) >= self.k:
            keep = estimates[~member] >= self._counts.min()
            new_s, new_c = new_s[keep], new_c[keep]
            if len(new_s) == 0:
                return
        # largest newcomers first: the order a zipfian batch's hot
        # signs would reach a sequential summary in anyway, and it
        # keeps a flood of cold singletons from inflating the errors
        # the hot admissions inherit
        order = np.argsort(new_c, kind="stable")[::-1]
        new_s, new_c = new_s[order], new_c[order]
        room = self.k - len(self._signs)
        if room > 0:
            take = min(room, len(new_s))
            self._signs = np.concatenate([self._signs, new_s[:take]])
            self._counts = np.concatenate([self._counts, new_c[:take]])
            self._errs = np.concatenate([self._errs, np.zeros(take)])
            new_s, new_c = new_s[take:], new_c[take:]
        if len(new_s):
            # at capacity: textbook sequential admissions (each evicts
            # the CURRENT minimum and inherits it as error), driven by
            # a per-batch heap of (count, slot). Entries go stale when
            # their slot's count moves on; a stale top is discarded on
            # sight. Only filter-passing newcomers reach this loop, so
            # steady-state cold traffic never pays it.
            import heapq

            counts = self._counts
            heap = [(c, i) for i, c in enumerate(counts.tolist())]
            heapq.heapify(heap)
            for s, c in zip(new_s.tolist(), new_c.tolist()):
                while counts[heap[0][1]] != heap[0][0]:
                    heapq.heappop(heap)
                mc, slot = heapq.heappop(heap)
                self._signs[slot] = s
                counts[slot] = mc + c
                self._errs[slot] = mc
                heapq.heappush(heap, (mc + c, slot))
        self._resort()

    def _resort(self):
        order = np.argsort(self._signs, kind="stable")
        self._signs = self._signs[order]
        self._counts = self._counts[order]
        self._errs = self._errs[order]

    @property
    def counts(self) -> Dict[int, int]:
        """Dict view (tests and small summaries; the hot path never
        builds it)."""
        return {int(s): int(c)
                for s, c in zip(self._signs, self._counts)}

    def snapshot(self) -> Dict[int, Tuple[int, int]]:
        return {int(s): (int(c), int(e)) for s, c, e in
                zip(self._signs, self._counts, self._errs)}


class CountMinSketch:
    """Count-Min over pre-hashed uint64 keys.

    ``depth`` rows of ``width`` cells; row i's index is the classic
    double-hash ``(h + i * h2) % width`` with ``h2`` odd, derived from
    the one FarmHash64 the caller already computed. Cells are float64
    holding integer values (exact to 2**53 — far beyond any lookup
    count this stores), so a batch update is one ``bincount`` per row
    and merged sketches stay exactly associative."""

    def __init__(self, width: int, depth: int):
        if width <= 0 or depth <= 0:
            raise ValueError("width and depth must be positive")
        self.width = width
        self.depth = depth
        self.rows = np.zeros((depth, width), dtype=np.float64)

    def _indices(self, hashes: np.ndarray) -> np.ndarray:
        """(depth, n) row indices in one broadcast (one errstate, one
        astype — the per-row version's fixed costs dominated the
        lookup path)."""
        h = hashes.astype(np.uint64, copy=False)
        h2 = (h >> np.uint64(32)) | np.uint64(1)
        d = np.arange(self.depth, dtype=np.uint64)[:, None]
        with np.errstate(over="ignore"):
            return ((h[None, :] + d * h2[None, :])
                    % np.uint64(self.width)).astype(np.int64)

    def add(self, hashes: np.ndarray, counts: np.ndarray):
        self.add_and_estimate(hashes, counts)

    def add_and_estimate(self, hashes: np.ndarray,
                         counts: np.ndarray) -> np.ndarray:
        """One pass: fold the batch in and return each hash's
        post-update frequency upper bound (hashed once — the admission
        filter wants the estimate right after the add anyway).
        bincount + row add beats np.add.at by an order of magnitude:
        ufunc.at pays per-element interpreter cost, the bincount pass
        and the full-width add are single C loops."""
        w = np.asarray(counts, dtype=np.float64)
        idx = self._indices(hashes)
        est = None
        for i in range(self.depth):
            self.rows[i] += np.bincount(idx[i], weights=w,
                                        minlength=self.width)
            row_est = self.rows[i][idx[i]]
            if est is None:
                est = row_est
            else:
                np.minimum(est, row_est, out=est)
        return est

    def estimate(self, hashes: np.ndarray) -> np.ndarray:
        """Frequency upper bound per hash (min over rows)."""
        idx = self._indices(hashes)
        est = self.rows[0][idx[0]]
        for i in range(1, self.depth):
            np.minimum(est, self.rows[i][idx[i]], out=est)
        return est


class _TableGlobal:
    """One table's whole-replica sketches (count-min + HLL + total).
    Frequency estimation and distinct counting don't care about the
    shard split — one vectorized pass over the flush batch beats
    num_shards small ones by the fixed numpy per-call costs — so these
    live at table level under the tracker's table lock, while the
    Space-Saving summaries stay per internal shard."""

    __slots__ = ("cm", "hll", "total")

    def __init__(self, cm_width: int, cm_depth: int, hll_p: int):
        self.cm = CountMinSketch(cm_width, cm_depth)
        self.hll = HyperLogLog(hll_p)
        self.total = 0

    def fold_locked(self, counts: np.ndarray,
                    hashes: np.ndarray) -> np.ndarray:
        self.total += int(counts.sum())
        est = self.cm.add_and_estimate(hashes, counts)
        self.hll.add_hashed(hashes)
        return est


class HotnessTracker:
    """Per-internal-shard hotness cells behind one lock per shard,
    fed through a small per-table staging buffer.

    The holder calls :meth:`observe` once per lookup batch, outside
    its own shard locks. The batch is *staged* (one array append under
    the buffer lock — a memcpy, no sketch math) and the sketches are
    folded in once ~``FLUSH_SIGNS`` signs accumulate: that amortizes
    the fixed numpy per-call costs across several batches AND dedups
    across them before any per-shard work (zipfian traffic repeats
    its hot signs batch to batch). At flush, signs are deduped and
    hashed once (vectorized), then bucketed by the same
    ``internal_shard_of`` hash the store uses, so each shard's cell is
    touched by exactly the traffic that shard serves and a
    per-replica snapshot is a disjoint union. :meth:`snapshot`
    flushes first, so readers never see the staging lag."""

    FLUSH_SIGNS = 65_536

    def __init__(self, num_shards: int, topk: Optional[int] = None,
                 cm_width: Optional[int] = None,
                 cm_depth: Optional[int] = None, hll_p: int = 12):
        if num_shards <= 0:
            raise ValueError("num_shards must be positive")
        self.num_shards = num_shards
        self.topk = int(topk if topk is not None
                        else knobs.get("PERSIA_HOTNESS_TOPK"))
        self.cm_width = int(cm_width if cm_width is not None
                            else knobs.get("PERSIA_HOTNESS_CM_WIDTH"))
        self.cm_depth = int(cm_depth if cm_depth is not None
                            else knobs.get("PERSIA_HOTNESS_CM_DEPTH"))
        self.hll_p = hll_p
        self._locks = [threading.Lock() for _ in range(num_shards)]
        # shard index -> {table(dim) -> SpaceSaving}
        self._cells: List[Dict[int, SpaceSaving]] = [
            {} for _ in range(num_shards)]
        # table(dim) -> _TableGlobal (cm + hll + total), own leaf lock
        self._table_lock = threading.Lock()
        self._tables: Dict[int, _TableGlobal] = {}
        # table -> list of staged sign arrays (buffer lock only guards
        # the staging lists; sketch math runs under the sketch locks)
        self._buf_lock = threading.Lock()
        self._buf: Dict[int, List[np.ndarray]] = {}
        self._buf_n: Dict[int, int] = {}

    def _cell_locked(self, shard: int, table: int) -> SpaceSaving:
        cell = self._cells[shard].get(table)
        if cell is None:
            cell = self._cells[shard][table] = SpaceSaving(self.topk)
        return cell

    def observe(self, table: int, signs: np.ndarray):
        """Record one lookup batch against ``table`` (the slot dim —
        the per-dim grouping the whole PS wire already routes by)."""
        if len(signs) == 0:
            return
        table = int(table)
        staged = None
        with self._buf_lock:
            self._buf.setdefault(table, []).append(
                np.ascontiguousarray(signs, dtype=np.uint64))
            n = self._buf_n[table] = self._buf_n.get(table, 0) + len(signs)
            if n >= self.FLUSH_SIGNS:
                staged = self._buf.pop(table)
                self._buf_n[table] = 0
        if staged is not None:
            self._fold(table, np.concatenate(staged))

    def _fold(self, table: int, signs: np.ndarray):
        """Dedup + hash once, fold the table-level CM/HLL in one
        vectorized pass (its estimate doubles as the Space-Saving
        admission filter), then update each touched shard's summary
        under that shard's lock. All locks here are leaves — no
        nesting, no ordering hazard."""
        from persia_tpu.ps.rng import internal_shard_of

        uniq, counts = np.unique(signs, return_counts=True)
        hashes = farmhash64_np(uniq)
        with self._table_lock:
            g = self._tables.get(table)
            if g is None:
                g = self._tables[table] = _TableGlobal(
                    self.cm_width, self.cm_depth, self.hll_p)
            est = g.fold_locked(counts, hashes)
        shard_ids = internal_shard_of(uniq, self.num_shards)
        for shard in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard)[0]
            with self._locks[shard]:
                self._cell_locked(int(shard), table).offer_many(
                    uniq[sel], counts[sel], est[sel])

    def flush(self):
        """Fold every staged batch in (snapshot readers and tests call
        this; the hot path flushes on its own cadence)."""
        with self._buf_lock:
            staged = [(t, arrs) for t, arrs in self._buf.items() if arrs]
            self._buf = {}
            self._buf_n = {}
        for table, arrs in staged:
            self._fold(table, np.concatenate(arrs))

    def snapshot(self) -> Dict:
        """Serializable roll-up: per-table CM/HLL/total read under the
        table lock, every shard's summary under its lock (shards
        partition the sign space, so the top-K union is disjoint).
        Like the holder's resident-bytes counters, the cross-lock
        union is a consistent-enough cut for telemetry, not a
        transactional one."""
        self.flush()
        agg: Dict[int, Dict] = {}
        with self._table_lock:
            for table, g in self._tables.items():
                agg[table] = {
                    "total": g.total,
                    "topk": {},
                    "cm": g.cm.rows.copy(),
                    "hll": g.hll.registers.copy(),
                    "unique_est": float(g.hll.estimate()),
                }
        for shard in range(self.num_shards):
            with self._locks[shard]:
                for table, cell in self._cells[shard].items():
                    a = agg.get(table)
                    if a is None:
                        continue  # racing first fold; next snapshot
                    for s, (c, e) in cell.snapshot().items():
                        oc, oe = a["topk"].get(s, (0, 0))
                        a["topk"][s] = (oc + c, oe + e)
        tables = {}
        for table, a in agg.items():
            tables[str(table)] = {
                "total": a["total"],
                "unique_est": a["unique_est"],
                "topk": sorted(
                    ([int(s), int(c), int(e)]
                     for s, (c, e) in a["topk"].items()),
                    key=lambda t: (-t[1], t[0])),
                "cm": _b64(a["cm"].tobytes()),
                "hll": _b64(a["hll"].tobytes()),
            }
        return {
            "enabled": True,
            "v": SNAPSHOT_VERSION,
            "k": self.topk,
            "num_shards": self.num_shards,
            "cm_width": self.cm_width,
            "cm_depth": self.cm_depth,
            "hll_p": self.hll_p,
            "total": sum(t["total"] for t in tables.values()),
            "tables": tables,
        }


def make_tracker(num_shards: int,
                 enabled: Optional[bool] = None) -> Optional[HotnessTracker]:
    """The one holder-side construction convention: ``None`` consults
    the ``PERSIA_HOTNESS`` knob at call time; disabled returns None so
    the lookup path's guard is a plain ``is not None``."""
    if enabled is None:
        enabled = knobs.get("PERSIA_HOTNESS")
    return HotnessTracker(num_shards) if enabled else None


def disabled_snapshot() -> Dict:
    return {"enabled": False, "v": SNAPSHOT_VERSION, "total": 0,
            "tables": {}}


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode("ascii")


def _unb64(s) -> bytes:
    return base64.b64decode(s)


# --- merging ---------------------------------------------------------------


def merge_snapshots(snaps: Sequence[Dict]) -> Dict:
    """Merge any number of snapshots into one. Exactly commutative and
    associative: top-K entries are summed pointwise over the sign
    union (the render-time truncation happens in :func:`top_rows`, not
    here), CM cells add, HLL registers max, totals add. Disabled or
    empty snapshots contribute nothing; mixed sketch geometries raise
    (replicas of one fleet share one knob config)."""
    merged = disabled_snapshot()
    geom = None
    for snap in snaps:
        if not snap or not snap.get("enabled"):
            continue
        sg = (snap.get("k"), snap.get("cm_width"), snap.get("cm_depth"),
              snap.get("hll_p"))
        if geom is None:
            geom = sg
            merged.update({"enabled": True, "k": snap.get("k"),
                           "cm_width": snap.get("cm_width"),
                           "cm_depth": snap.get("cm_depth"),
                           "hll_p": snap.get("hll_p")})
        elif geom != sg:
            raise ValueError(
                f"cannot merge hotness snapshots of different sketch "
                f"geometry: {geom} vs {sg}")
        merged["total"] += int(snap.get("total", 0))
        for table, t in snap.get("tables", {}).items():
            m = merged["tables"].get(table)
            if m is None:
                merged["tables"][table] = {
                    "total": int(t["total"]),
                    "topk": [list(row) for row in t["topk"]],
                    "cm": t["cm"],
                    "hll": t["hll"],
                }
                if t.get("row_bytes"):
                    merged["tables"][table]["row_bytes"] = int(
                        t["row_bytes"])
                continue
            m["total"] += int(t["total"])
            if t.get("row_bytes"):
                # replicas of one fleet share one storage policy; a
                # mid-rollout mix keeps the WIDER row so budget math
                # stays conservative
                m["row_bytes"] = max(int(m.get("row_bytes") or 0),
                                     int(t["row_bytes"]))
            by_sign = {s: [c, e] for s, c, e in m["topk"]}
            for s, c, e in t["topk"]:
                cur = by_sign.get(s)
                if cur is None:
                    by_sign[s] = [c, e]
                else:
                    cur[0] += c
                    cur[1] += e
            m["topk"] = sorted(
                ([s, ce[0], ce[1]] for s, ce in by_sign.items()),
                key=lambda r: (-r[1], r[0]))
            a = np.frombuffer(_unb64(m["cm"]), dtype=np.float64)
            b = np.frombuffer(_unb64(t["cm"]), dtype=np.float64)
            m["cm"] = _b64((a + b).tobytes())
            ha = np.frombuffer(_unb64(m["hll"]), dtype=np.uint8)
            hb = np.frombuffer(_unb64(t["hll"]), dtype=np.uint8)
            m["hll"] = _b64(np.maximum(ha, hb).tobytes())
    # recompute per-table uniques from the merged HLLs (a sum of the
    # inputs' estimates would double-count signs seen by >1 replica)
    hll_p = merged.get("hll_p")
    if hll_p:
        for t in merged["tables"].values():
            hll = HyperLogLog(hll_p)
            hll.registers = np.frombuffer(
                _unb64(t["hll"]), dtype=np.uint8).copy()
            t["unique_est"] = float(hll.estimate())
    return merged


def top_rows(table_snap: Dict, n: int) -> List[List[int]]:
    """The ``n`` hottest ``[sign, count, err]`` rows of one table."""
    return table_snap["topk"][:n]


# --- analysis: zipf fit, coverage, planning --------------------------------


def fit_zipf_alpha(counts: Sequence[float],
                   skip_head: int = 8) -> Optional[float]:
    """Least-squares slope of log(count) vs log(rank) over the top-K
    counts (descending). The first few ranks are skipped: zipfian heads
    routinely deviate from the tail power law, and the tail slope is
    what extrapolation beyond K needs. Returns None when there is not
    enough signal to fit."""
    counts = [c for c in counts if c > 0]
    if len(counts) < max(skip_head + 8, 16):
        return None
    lo = max(1, skip_head)
    ranks = np.arange(lo, len(counts) + 1, dtype=np.float64)
    vals = np.asarray(counts[lo - 1:], dtype=np.float64)
    slope, _ = np.polyfit(np.log(ranks), np.log(vals), 1)
    alpha = -float(slope)
    return alpha if math.isfinite(alpha) and alpha > 0 else None


def _zipf_partial_sum(alpha: float, lo: float, hi: float) -> float:
    """Approximate sum of r^-alpha for r in (lo, hi] via the integral
    (the extrapolation tail only — head mass comes from real counts)."""
    if hi <= lo:
        return 0.0
    if abs(alpha - 1.0) < 1e-9:
        return math.log(hi / lo)
    return (hi ** (1.0 - alpha) - lo ** (1.0 - alpha)) / (1.0 - alpha)


def _tail_model(c_k: float, k: float, uniq: float, remaining: float):
    """Mass-conserving model of the untracked tail: counts decay as
    ``c_k * (r/k)^-a`` down to the floor of 1 (a finite sample's deep
    tail is singletons), with the decay ``a`` solved so the tail's
    total mass equals the ``remaining`` lookups the head did not
    cover. Anchoring on conservation instead of a fitted slope means
    coverage hits exactly 1.0 at the last unique row and a noisy
    log-log fit cannot claim mass the stream never had. Returns
    ``tail_mass(n)``: lookups covered by tail ranks (k, n]."""
    m_rows = max(uniq - k, 1.0)

    def uniform(n):
        return remaining * (min(n, uniq) - k) / m_rows

    if remaining <= m_rows or c_k <= 1.0:
        # averages below one count per row: sketch noise territory,
        # spread the mass evenly
        return uniform

    log_ck = math.log(c_k)

    def mass(a, upto=None):
        # r_star solves c_k * (r/k)^-a == 1; computed in log space so
        # a tiny decay exponent cannot overflow the power
        if log_ck / a > math.log(uniq / k):
            r_star = uniq
        else:
            r_star = min(k * math.exp(log_ck / a), uniq)
        hi = min(upto, uniq) if upto is not None else uniq
        power = c_k * (k ** a) * _zipf_partial_sum(a, k, min(hi, r_star))
        floor = max(hi - max(r_star, k), 0.0)
        return power + floor

    if mass(1e-6) <= remaining:
        # even a flat tail at c_k cannot carry the remaining mass
        # (head overcounting ate it) — degrade to uniform
        return uniform
    lo_a, hi_a = 1e-6, 64.0
    for _ in range(60):
        mid = (lo_a + hi_a) / 2.0
        if mass(mid) > remaining:
            lo_a = mid
        else:
            hi_a = mid
    a = (lo_a + hi_a) / 2.0
    scale = remaining / max(mass(a), 1e-12)  # close the bisection gap

    def tail(n):
        return scale * mass(a, upto=float(n))

    return tail


def _stable_counts(rows: Sequence) -> np.ndarray:
    """Bias-corrected count estimates from ``[sign, count, err]``
    summary rows, sorted descending. Space-Saving counts straddle the
    truth: ``count`` overestimates by up to ``err``, ``count - err``
    underestimates; the midpoint halves the systematic bias, but only
    for *stable* cells (count >= 2*err) — a cell dominated by the
    inherited eviction floor is churn, not signal, and keeping churned
    cells drags any statistic over the summary (coverage prefix sums,
    the log-log zipf slope) toward the flat eviction floor. When every
    cell is churning (a near-uniform stream), fall back to midpoints of
    everything rather than returning nothing."""
    stable = [c - e / 2.0 for _s, c, e in rows if c >= 2 * e]
    return np.sort(np.asarray(stable or
                              [c - e / 2.0 for _s, c, e in rows],
                              dtype=np.float64))[::-1]


def coverage_curve(table_snap: Dict,
                   fracs: Sequence[float] = DEFAULT_COVERAGE_FRACS
                   ) -> List[Dict]:
    """"Top p% of rows serve q% of lookups" points for one table.

    Ranks inside the top-K summary read straight off the (slightly
    over-counted) Space-Saving counts; ranks beyond K extrapolate the
    fitted zipf tail anchored at the summary's own tail counts, capped
    so coverage is monotone and <= 1."""
    total = float(table_snap.get("total") or 0)
    rows = table_snap.get("topk", ())
    uniq = max(float(table_snap.get("unique_est") or 0.0),
               float(len(rows)), 1.0)
    out = []
    if total <= 0 or not rows:
        return [{"frac": f, "rows": 0, "coverage": 0.0} for f in fracs]
    # Churned cells are dropped from the trusted head (_stable_counts)
    # and their mass handed to the conservation-anchored tail model
    # (measured worst coverage error on zipf(1.05): raw 3.4 pts,
    # midpoint-everywhere 0.6/2.2 pts stable/churning summary,
    # stability-cut 0.2/0.9), re-sorted since the correction reorders
    # mid-rank rows.
    counts = _stable_counts(rows)
    prefix = np.cumsum(counts, dtype=np.float64)
    k = len(counts)
    head = float(prefix[-1])
    remaining = max(total - head, 0.0)
    tail_mass = _tail_model(max(float(counts[-1]), 0.0), float(k), uniq,
                            remaining)
    for f in fracs:
        n = max(1, int(round(f * uniq)))
        n = min(n, int(uniq))
        if n <= k:
            # inside the summary: straight off the (slightly
            # over-counted) Space-Saving prefix sums
            cov = prefix[n - 1] / total
        else:
            # evaluate the tail at the fractional rank: int truncation
            # of `n` would undershoot the conserved mass at frac=1.0
            cov = (head + tail_mass(min(f * uniq, uniq))) / total
        out.append({"frac": f, "rows": n,
                    "coverage": round(min(max(cov, 0.0), 1.0), 6)})
    # enforce monotonicity across the grid (extrapolation joins the
    # exact prefix at rank K; tiny seams must not read as regressions)
    for i in range(1, len(out)):
        if out[i]["coverage"] < out[i - 1]["coverage"]:
            out[i]["coverage"] = out[i - 1]["coverage"]
    return out


def table_report(table_snap: Dict,
                 fracs: Sequence[float] = DEFAULT_COVERAGE_FRACS,
                 top_n: int = 16) -> Dict:
    """Human/SLO-facing summary of one table: totals, distinct
    estimate, fitted skew, coverage curve, hottest rows."""
    rows = table_snap.get("topk", ())
    # fit on the stability-cut corrected counts: raw Space-Saving
    # counts carry the eviction floor in every churned tail cell, which
    # flattens the log-log slope and reads genuinely skewed traffic
    # (alpha ~1.0) as near-uniform (~0.5) — the number DEPLOY.md tells
    # operators to size the device-cache tier by
    counts = _stable_counts(rows) if rows else []
    return {
        "total": int(table_snap.get("total") or 0),
        "row_bytes": int(table_snap.get("row_bytes") or 0) or None,
        "unique_est": round(float(table_snap.get("unique_est") or 0.0), 1),
        "tracked_topk": len(rows),
        "zipf_alpha": fit_zipf_alpha(counts),
        "coverage": coverage_curve(table_snap, fracs),
        "top_rows": top_rows(table_snap, top_n),
    }


def planner_report(snapshot: Dict, hbm_bytes: int,
                   row_bytes: Optional[Dict[str, int]] = None,
                   fracs: Sequence[float] = DEFAULT_COVERAGE_FRACS,
                   num_replicas: Optional[int] = None,
                   measured_hit_rate: Optional[float] = None) -> Dict:
    """HBM-capacity plan for the frequency-admitted device cache
    (ROADMAP item 2): split ``hbm_bytes`` across tables in proportion
    to their lookup traffic, size each table's hot set, and read the
    expected hit rate off its coverage curve. Bytes/row resolve in
    order: the caller's ``row_bytes`` map (table -> resident bytes/row
    in HBM) wins outright; otherwise the snapshot's per-table
    ``row_bytes`` (the LIVE holder's storage precision, stamped by
    ``hotness_snapshot`` and carried by the merge) FLOORED at the fp32
    width ``dim * 4`` — the device cache imports rows as f32 values
    whatever the PS stores (cached_train.init_cache_arrays), so an
    fp16 PS tier must not seduce the plan into budgeting 2x the rows
    that actually fit in HBM. A wider-than-f32 stamp (future) is
    honored; optimizer state is excluded by convention.

    ``measured_hit_rate`` closes the prediction loop: when a caller has
    MEASURED the device-cache hit rate under the planned budget (the
    e2e bench's steady window, or an operator reading the cache
    counters), the report carries it next to the prediction plus their
    signed delta (``predicted - measured``) — the number the e2e gate
    bounds and the first thing to look at when a capacity plan
    disagrees with production."""
    tables = snapshot.get("tables", {})
    total = float(snapshot.get("total") or 0) or float(
        sum(t.get("total", 0) for t in tables.values())) or 1.0
    plan = []
    overall = 0.0
    for table, t in sorted(tables.items(), key=lambda kv: kv[0]):
        share = float(t.get("total", 0)) / total
        rb = (int((row_bytes or {}).get(table, 0))
              or max(int(t.get("row_bytes") or 0), int(table) * 4))
        budget = int(share * hbm_bytes)
        uniq = max(float(t.get("unique_est") or 0.0), 1.0)
        hot_rows = min(int(budget // rb) if rb else 0, int(uniq))
        curve = coverage_curve(t, fracs=[min(hot_rows / uniq, 1.0)])
        hit = curve[0]["coverage"] if hot_rows else 0.0
        overall += share * hit
        plan.append({
            "table": table,
            "row_bytes": rb,
            "traffic_share": round(share, 6),
            "unique_rows_est": round(uniq, 1),
            "budget_bytes": budget,
            "hot_rows": hot_rows,
            "hot_row_frac": round(hot_rows / uniq, 6),
            "expected_hit_rate": hit,
        })
    doc = {
        "hbm_bytes": int(hbm_bytes),
        "total_lookups": int(total),
        "expected_overall_hit_rate": round(overall, 6),
        "tables": plan,
    }
    if measured_hit_rate is not None:
        doc["measured_overall_hit_rate"] = round(
            float(measured_hit_rate), 6)
        doc["hit_rate_delta"] = round(
            overall - float(measured_hit_rate), 6)
    if num_replicas:
        # elastic-tier placement: per-slot traffic shares -> replica
        # assignment, consumed by the reshard controller
        doc["placement_plan"] = placement_plan(snapshot, num_replicas)
    return doc


def slot_weights(snapshot: Dict, num_slots: int) -> np.ndarray:
    """Per-routing-slot traffic weights from a (merged) hotness
    snapshot, for the elastic tier's hotness-balanced placement.

    The tracked top-K heads (bias-corrected midpoint counts, summed
    across tables — routing is global, not per-table) land on their
    exact slot via the same ``farmhash % num_slots`` the
    :class:`~persia_tpu.routing.RoutingTable` routes by; the untracked
    tail mass (total - head) spreads uniformly across slots, which is
    exactly what an un-skewed remainder does to load. Returns raw
    lookup-count weights (length ``num_slots``); normalize if you need
    shares."""
    w = np.zeros(int(num_slots), dtype=np.float64)
    tail_total = 0.0
    for t in snapshot.get("tables", {}).values():
        rows = t.get("topk", ())
        head = 0.0
        if rows:
            signs = np.array([r[0] for r in rows], dtype=np.uint64)
            counts = np.array([max(c - e / 2.0, 0.0)
                               for _s, c, e in rows], dtype=np.float64)
            slots = (farmhash64_np(signs)
                     % np.uint64(num_slots)).astype(np.int64)
            np.add.at(w, slots, counts)
            head = float(counts.sum())
        tail_total += max(float(t.get("total", 0)) - head, 0.0)
    w += tail_total / float(num_slots)
    return w


def placement_plan(snapshot: Dict, num_replicas: int,
                   num_slots: Optional[int] = None,
                   current_table=None) -> Dict:
    """Hotness-balanced slot→replica placement for ``num_replicas``
    (the reshard controller's planning input): per-slot traffic shares
    from :func:`slot_weights`, assigned by the move-minimizing greedy
    LPT in :func:`persia_tpu.reshard.plan_assignment`. The report pairs
    the plan's per-replica load shares with what uniform hash-even
    (``slot % R``) would have carried, so "how much did balancing buy"
    is a read-off, not a rerun — under zipf traffic the head slot no
    longer pins max-replica load to head + 1/R."""
    from persia_tpu import knobs
    from persia_tpu.reshard import plan_assignment
    from persia_tpu.routing import RoutingTable

    if current_table is not None:
        num_slots = current_table.num_slots
    elif num_slots is None:
        num_slots = num_replicas * int(
            knobs.get("PERSIA_ROUTING_SLOTS_PER_REPLICA"))
    if current_table is None:
        current_table = RoutingTable(
            1, np.arange(num_slots, dtype=np.int32)
            % np.int32(num_replicas), num_replicas)
    w = slot_weights(snapshot, num_slots)
    total = float(w.sum()) or 1.0
    assignment = plan_assignment(current_table, num_replicas, w)
    loads = np.bincount(assignment, weights=w, minlength=num_replicas)
    even = np.bincount(
        np.arange(num_slots, dtype=np.int64) % num_replicas,
        weights=w, minlength=num_replicas)
    moved = int(np.count_nonzero(
        assignment != current_table.replica_of_slot))
    return {
        "num_replicas": int(num_replicas),
        "num_slots": int(num_slots),
        "assignment": [int(r) for r in assignment],
        "slot_weights": [round(float(x), 3) for x in w],
        "replica_shares": [round(float(x) / total, 6) for x in loads],
        "max_replica_share": round(float(loads.max()) / total, 6),
        "hash_even_shares": [round(float(x) / total, 6) for x in even],
        "hash_even_max_share": round(float(even.max()) / total, 6),
        "moved_slots": moved,
    }


def fleet_report(snapshot: Dict, hbm_bytes: Optional[int] = None,
                 fracs: Sequence[float] = DEFAULT_COVERAGE_FRACS,
                 num_replicas: Optional[int] = None,
                 measured_hit_rate: Optional[float] = None) -> Dict:
    """The /fleet/hotness document: merged totals, per-table analysis,
    (when an HBM budget is named) the capacity plan, and (when a
    replica count is named) the elastic tier's hotness-balanced
    placement plan."""
    if measured_hit_rate is not None and not (
            hbm_bytes and snapshot.get("enabled")):
        # a measured rate needs a prediction to delta against — that
        # takes both a budget AND armed telemetry; silently dropping
        # it would read as "no drift data"
        raise ValueError(
            "measured_hit_rate requires an HBM budget (hbm_bytes / "
            "?hbm_gb=) and armed hotness telemetry — there is no "
            "predicted hit rate to compare against without them")
    doc = {
        "enabled": bool(snapshot.get("enabled")),
        "total": int(snapshot.get("total") or 0),
        "tables": {t: table_report(ts, fracs=fracs)
                   for t, ts in snapshot.get("tables", {}).items()},
    }
    if hbm_bytes and snapshot.get("enabled"):
        doc["planner"] = planner_report(snapshot, hbm_bytes, fracs=fracs,
                                        num_replicas=num_replicas,
                                        measured_hit_rate=measured_hit_rate)
    elif num_replicas and snapshot.get("enabled"):
        doc["placement_plan"] = placement_plan(snapshot, num_replicas)
    return doc


def summary_view(snapshot: Dict, top_n: int = 16) -> Dict:
    """The default /hotness body: everything human-sized, the bulky
    b64 sketch payloads stripped (``?full=1`` serves the mergeable
    form)."""
    if not snapshot.get("enabled"):
        return snapshot
    return {
        "enabled": True,
        "v": snapshot.get("v"),
        "k": snapshot.get("k"),
        "total": snapshot.get("total"),
        "tables": {t: table_report(ts, top_n=top_n)
                   for t, ts in snapshot.get("tables", {}).items()},
    }
