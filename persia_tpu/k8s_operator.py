"""Kubernetes reconcile loop for persia_tpu jobs.

The reference runs a Rust kube-runtime Controller that creates the
job's pods, restarts failures, and tears everything down on delete
(k8s/src/bin/operator.rs:25-123, reconcile interval 10 s, with
PersiaJobResources apply/delete in k8s/src/lib.rs). This is the same
control loop over the declarative manifests from
:mod:`persia_tpu.k8s_utils`:

- **desired state** = ``gen_manifests(job_spec)`` for every tracked job
- **observed state** = pods/services labeled ``persia-job=<name>``
- reconcile: create missing objects, delete+recreate pods in a terminal
  phase (Failed, or Succeeded for long-running roles), delete objects
  that are no longer desired, and tear down all objects of untracked
  (deleted) jobs.

The API surface is pluggable: :class:`KubectlApi` shells out to
``kubectl`` (no client library dependency, works against any cluster),
and :class:`FakeKubeApi` is an in-memory twin for tests (the reference's
operator is e2e-tested against a real cluster, k8s/src/bin/e2e.rs; the
fake gives the same coverage in-process).

CLI: ``python -m persia_tpu.k8s_operator job1.yml job2.yml
[--interval 10] [--once]``
"""

import argparse
import json
import os
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from persia_tpu.k8s_utils import gen_manifests
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import load_yaml

_logger = get_default_logger(__name__)

# Service roles run forever — any terminal phase (even Succeeded) means
# the process exited and must be replaced. Entry-script roles (trainer,
# data-loader) legitimately finish: only Failed/Unknown restarts them.
_SERVICE_ROLES = frozenset({
    "coordinator", "embeddingParameterServer", "embeddingWorker",
    "metricsGateway",
})
_FAILED_PHASES = ("Failed", "Unknown")
_SERVICE_TERMINAL_PHASES = ("Failed", "Succeeded", "Unknown")


def _pod_needs_restart(manifest: dict, observed: dict) -> bool:
    phase = observed.get("status", {}).get("phase")
    role = manifest["metadata"].get("labels", {}).get("persia-role", "")
    terminal = (_SERVICE_TERMINAL_PHASES if role in _SERVICE_ROLES
                else _FAILED_PHASES)
    return phase in terminal


class KubectlApi:
    """Real-cluster access through the kubectl CLI."""

    def __init__(self, namespace: str = "default", kubectl: str = "kubectl"):
        self.namespace = namespace
        self.kubectl = kubectl

    def _run(self, args: List[str], stdin: Optional[str] = None) -> str:
        proc = subprocess.run(
            [self.kubectl, "-n", self.namespace, *args],
            input=stdin, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"kubectl {' '.join(args)} failed: {proc.stderr.strip()}")
        return proc.stdout

    def apply(self, manifest: dict):
        self._run(["apply", "-f", "-"], stdin=json.dumps(manifest))

    def delete(self, kind: str, name: str):
        self._run(["delete", kind.lower(), name, "--ignore-not-found",
                   "--wait=false"])

    def list_objects(self, label_selector: str) -> List[dict]:
        out = []
        for kind in ("pods", "services"):
            data = json.loads(
                self._run(["get", kind, "-l", label_selector, "-o", "json"]))
            out.extend(data.get("items", []))
        return out

    def list_custom(self, plural: str = "persiajobs") -> List[dict]:
        """PersiaJob custom resources (requires the CRD from
        ``persia_tpu.k8s_utils gencrd`` to be installed)."""
        data = json.loads(self._run(["get", plural, "-o", "json"]))
        return data.get("items", [])


class FakeKubeApi:
    """In-memory twin of KubectlApi for unit tests.

    Tests mutate observed state directly (``kill_pod``) to simulate
    crashes; new pods come up ``Running``.
    """

    def __init__(self):
        # (kind, name) -> manifest (with .status.phase for pods)
        self.objects: Dict[Tuple[str, str], dict] = {}
        self.apply_log: List[str] = []
        self.delete_log: List[str] = []
        self.custom_resources: List[dict] = []  # PersiaJob CRs

    def apply(self, manifest: dict):
        kind = manifest["kind"]
        name = manifest["metadata"]["name"]
        manifest = dict(manifest)
        if kind == "Pod":
            manifest["status"] = {"phase": "Running"}
        self.objects[(kind, name)] = manifest
        self.apply_log.append(f"{kind}/{name}")

    def delete(self, kind: str, name: str):
        self.objects.pop((kind.capitalize(), name), None)
        # kubectl's kind argument is lowercase; normalize both spellings
        self.objects.pop((kind, name), None)
        self.delete_log.append(f"{kind}/{name}")

    def list_objects(self, label_selector: str) -> List[dict]:
        want = dict(kv.split("=", 1) for kv in label_selector.split(","))
        out = []
        for obj in self.objects.values():
            labels = obj.get("metadata", {}).get("labels", {})
            if all(labels.get(k) == v for k, v in want.items()):
                out.append(obj)
        return out

    def kill_pod(self, name: str, phase: str = "Failed"):
        self.objects[("Pod", name)]["status"] = {"phase": phase}

    def list_custom(self, plural: str = "persiajobs") -> List[dict]:
        return list(self.custom_resources)


class Operator:
    """The reconcile loop (reference operator.rs:25-123)."""

    def __init__(self, api, job_specs: Optional[List[dict]] = None,
                 interval: float = 10.0, reshard_driver=None,
                 reshard_journal_dir: Optional[str] = None,
                 variant_driver=None):
        self.api = api
        self.interval = interval
        # elastic-tier hook: ``reshard_driver(job_name, old, new,
        # phase, spec)`` runs the live slot migration around PS pod
        # reconciliation (phase "scale_out": pods already created,
        # migrate onto them; phase "scale_in": migrate OFF the dying
        # replicas BEFORE their pods are removed; phase "resume": a
        # restarted operator found the job's migration journal showing
        # an in-flight migration — the driver must
        # ReshardController.resume() it before any new scale runs).
        # Without a driver, scale intents are recorded for an external
        # controller.
        self._reshard_driver = reshard_driver
        # per-job durable migration journals live under
        # <reshard_journal_dir>/<job_name> (the driver passes the same
        # path to its ReshardController); on operator start the first
        # reconcile pass scans them and resumes/flags any migration a
        # previous operator incarnation left in flight
        self._reshard_journal_dir = reshard_journal_dir
        # (job, mig_id, attempt) triples already resumed/surfaced — the
        # scan runs every reconcile pass (a job tracked AFTER startup
        # still gets its wedged migration found), but each in-flight
        # attempt is handled once
        self._resumed_migs: set = set()
        self._reshard_events: List[dict] = []
        # multi-variant serving hook: ``variant_driver(job_name, op,
        # payload, spec)`` forwards a variant operation (add / remove /
        # promote / weight / drain / resume) to the job's serving
        # replicas — typically a variant_admin RPC broadcast. Without a
        # driver the intent is recorded for an external controller,
        # mirroring the reshard_driver convention.
        self._variant_driver = variant_driver
        self._variant_events: List[dict] = []
        self._jobs: Dict[str, dict] = {}
        # serializes reconcile passes against track/untrack (the REST
        # API mutates job state while the loop runs; without this a
        # delete could race an in-flight reconcile, which would recreate
        # the torn-down pods of a no-longer-tracked job — orphans)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._from_cr: set = set()  # jobs sourced from PersiaJob CRs
        for spec in job_specs or []:
            self.track(spec)

    # --- job tracking (the CRD add/delete events) -----------------------

    def track(self, spec: dict, source: str = "api"):
        """Track a job. ``source="cr"`` marks it as governed by its
        PersiaJob custom resource; any other source (YAML argv, REST)
        claims the job away from CR governance so a later CR sweep
        cannot tear down a job the user explicitly re-applied."""
        with self._lock:
            self._jobs[spec["jobName"]] = spec
            if source == "cr":
                self._from_cr.add(spec["jobName"])
            else:
                self._from_cr.discard(spec["jobName"])

    def untrack(self, job_name: str):
        """Stop managing a job; its objects are torn down immediately
        (the reference's delete finalizer)."""
        with self._lock:
            self._jobs.pop(job_name, None)
            self.teardown(job_name)

    def teardown(self, job_name: str):
        for obj in self.api.list_objects(f"persia-job={job_name}"):
            self.api.delete(obj["kind"], obj["metadata"]["name"])

    # locked snapshots for concurrent readers (the REST handlers run on
    # their own threads; iterating shared dicts unlocked would race the
    # reconcile loop)
    def job_names(self) -> List[str]:
        with self._lock:
            return sorted(self._jobs)

    def objects_of(self, job_name: str) -> List[dict]:
        with self._lock:
            return list(self.api.list_objects(f"persia-job={job_name}"))

    # --- reconcile ------------------------------------------------------

    def reconcile_job(self, spec: dict, manifests=None) -> Dict[str, int]:
        """Drive one job toward its desired manifest set. Returns action
        counts (created/restarted/removed) for observability. Callers
        that already rendered the spec (e.g. /apply's validation pass)
        hand the manifests in to avoid a second gen_manifests()."""
        with self._lock:
            return self._reconcile_job_locked(spec, manifests)

    def _reconcile_job_locked(self, spec: dict, manifests=None) -> Dict[str, int]:
        job = spec["jobName"]
        stats = {"created": 0, "restarted": 0, "removed": 0}
        desired = {
            (m["kind"], m["metadata"]["name"]): m
            for m in (manifests if manifests is not None
                      else gen_manifests(spec))
        }
        observed = {
            (o["kind"], o["metadata"]["name"]): o
            for o in self.api.list_objects(f"persia-job={job}")
        }
        for key, manifest in desired.items():
            obj = observed.get(key)
            if obj is None:
                self.api.apply(manifest)
                stats["created"] += 1
            elif key[0] == "Pod" and _pod_needs_restart(manifest, obj):
                # dead pod: delete now; the NEXT pass's missing-object
                # branch recreates it. Re-applying the same name in the
                # same pass races the apiserver's termination grace
                # period (the object still exists with a
                # deletionTimestamp) and would abort the reconcile.
                self.api.delete(key[0], key[1])
                stats["restarted"] += 1
        for key in observed.keys() - desired.keys():
            self.api.delete(key[0], key[1])
            stats["removed"] += 1
        if any(stats.values()):
            _logger.info("reconciled %s: %s", job, stats)
        return stats

    # --- elastic PS tier (scale-out / scale-in / drain) -----------------

    @staticmethod
    def _ps_replicas_of(spec: dict) -> int:
        conf = spec.get("roles", {}).get("embeddingParameterServer")
        return int(conf.get("replicas", 1)) if conf is not None else 0

    def ps_replicas(self, job_name: str) -> int:
        """The job's CURRENT desired PS replica count — the autopilot
        reads the world it acts on from here (observed state, not its
        own action history, so an operator-side manual scale between
        ticks is seen, not fought)."""
        with self._lock:
            spec = self._jobs.get(job_name)
            if spec is None:
                raise KeyError(f"job {job_name!r} is not tracked")
            return self._ps_replicas_of(spec)

    def reshard_events(self) -> List[dict]:
        with self._lock:
            return list(self._reshard_events)

    def rebalance_ps(self, job_name: str) -> dict:
        """Re-place slots across the CURRENT replica set by workload
        hotness (replica count unchanged): the driver runs a
        ``reshard_to`` at the same count with a hotness
        ``placement_plan``'s slot weights. Without a driver the intent
        is recorded (status ``pending``) for an external controller,
        same convention as :meth:`scale_ps`."""
        import time as _time

        with self._lock:
            spec = self._jobs.get(job_name)
            if spec is None:
                raise KeyError(f"job {job_name!r} is not tracked")
            old = self._ps_replicas_of(spec)
            if old == 0:
                raise ValueError(f"job {job_name!r} has no PS role")
        event = {"job": job_name, "from": old, "to": old,
                 "phase": "rebalance",
                 "time": _time.strftime("%Y-%m-%dT%H:%M:%S"),
                 "status": "pending"}
        if self._reshard_driver is not None:
            self._reshard_driver(job_name, old, old, "rebalance", spec)
            event["status"] = "done"
        with self._lock:
            self._reshard_events.append(event)
        _logger.info("rebalance_ps %s: %d replicas (%s)", job_name, old,
                     event["status"])
        return event

    # --- autopilot hookup -------------------------------------------

    def attach_autopilot(self, pilot):
        """Expose a running :class:`persia_tpu.autopilot.Autopilot` on
        the REST surface (``GET /autopilot``). The operator never
        drives the pilot — the pilot calls INTO the operator; this
        hook only makes its decisions inspectable next to the
        reshard/variant audit trails."""
        self._autopilot = pilot

    def autopilot_doc(self) -> dict:
        pilot = getattr(self, "_autopilot", None)
        if pilot is None:
            return {"enabled": False}
        doc = pilot.describe()
        doc["enabled"] = True
        return doc

    def scale_ps(self, job_name: str, replicas: int) -> dict:
        """Reconcile a job's PS tier to ``replicas`` with the live
        reshard sequenced safely around pod churn:

        - **scale-out**: new PS pods are created FIRST (reconcile),
          then the driver migrates hotness-balanced slot plans onto
          them and publishes the successor routing epoch;
        - **scale-in / drain**: the driver migrates every slot OFF the
          dying replicas and cuts over BEFORE their pods are removed —
          a drained replica serves stale-epoch double-reads until the
          window closes, then reconcile deletes it.

        Without a driver the intent is recorded (status "pending") so
        an external reshard controller — or an operator following
        docs/DEPLOY.md's runbook — can pick it up; the pod set is only
        changed for scale-out in that case (never delete a PS that
        still owns slots)."""
        import time as _time

        with self._lock:
            spec = self._jobs.get(job_name)
            if spec is None:
                raise KeyError(f"job {job_name!r} is not tracked")
            old = self._ps_replicas_of(spec)
            if old == 0:
                raise ValueError(f"job {job_name!r} has no PS role")
        replicas = int(replicas)
        event = {"job": job_name, "from": old, "to": replicas,
                 "time": _time.strftime("%Y-%m-%dT%H:%M:%S"),
                 "status": "noop" if replicas == old else "pending"}
        if replicas == old:
            with self._lock:
                self._reshard_events.append(event)
            return event

        def _apply_spec_and_reconcile():
            with self._lock:
                spec["roles"]["embeddingParameterServer"]["replicas"] = \
                    replicas
                self._jobs[job_name] = spec
                self._reconcile_job_locked(spec)

        if replicas > old:
            # grow the pod set, then migrate onto it
            _apply_spec_and_reconcile()
            if self._reshard_driver is not None:
                self._reshard_driver(job_name, old, replicas,
                                     "scale_out", spec)
                event["status"] = "done"
        else:
            # drain slots off the dying replicas BEFORE removing pods
            if self._reshard_driver is not None:
                self._reshard_driver(job_name, old, replicas,
                                     "scale_in", spec)
                event["status"] = "done"
                _apply_spec_and_reconcile()
            else:
                # no driver: record the intent but leave the pods —
                # deleting a PS that still owns slots loses rows
                event["status"] = "pending_drain"
        with self._lock:
            self._reshard_events.append(event)
        _logger.info("scale_ps %s: %d -> %d (%s)", job_name, old,
                     replicas, event["status"])
        return event

    # --- multi-variant serving (promote / rollback a variant) -----------

    def variant_events(self) -> List[dict]:
        with self._lock:
            return list(self._variant_events)

    def variant_op(self, job_name: str, op: str, payload: dict) -> dict:
        """Forward a live variant operation to a job's serving tier
        through the variant driver (``POST /variants`` lands here).
        ``payload`` carries at least ``name`` (except for ``list``);
        ``add`` additionally the model/dense-checkpoint fields the
        serving ``variant_admin`` RPC expects. The event log is the
        operator's audit trail — the promote/rollback runbook
        (docs/DEPLOY.md) reads it back via ``GET /variants``."""
        import time as _time

        with self._lock:
            spec = self._jobs.get(job_name)
            if spec is None:
                raise KeyError(f"job {job_name!r} is not tracked")
        if op not in ("add", "remove", "promote", "weight", "drain",
                      "resume", "list"):
            raise ValueError(f"unknown variant op {op!r}")
        event = {"job": job_name, "op": op,
                 "variant": payload.get("name"),
                 "time": _time.strftime("%Y-%m-%dT%H:%M:%S"),
                 "status": "pending"}
        if self._variant_driver is not None:
            result = self._variant_driver(job_name, op, dict(payload),
                                          spec)
            event["status"] = "done"
            if result is not None:
                event["result"] = result
        with self._lock:
            self._variant_events.append(event)
        _logger.info("variant_op %s: %s %s (%s)", job_name, op,
                     payload.get("name"), event["status"])
        return event

    def resume_pending_reshards(self) -> List[dict]:
        """Operator-crash recovery: scan each tracked job's migration
        journal (``<reshard_journal_dir>/<job>``) for a migration a
        previous operator incarnation left in flight. With a driver,
        hand it the job under phase ``"resume"`` (it runs
        ``ReshardController.resume()`` against the live fleet — roll
        forward post-publish, fence-and-retry pre-publish); without
        one, record a ``resume_pending`` event so the runbook operator
        sees the wedged migration instead of a silently frozen donor.
        Returns the events recorded (one per in-flight journal)."""
        if self._reshard_journal_dir is None:
            return []
        import time as _time

        from persia_tpu.reshard import MigrationJournal

        events = []
        for job in self.job_names():
            root = os.path.join(self._reshard_journal_dir, job)
            if not os.path.isdir(root):
                continue
            try:
                st = MigrationJournal(root).state()
            except Exception as e:
                _logger.error("unreadable reshard journal %s: %s",
                              root, e)
                continue
            if st is None or st["phase"] in MigrationJournal.TERMINAL:
                continue
            key = (job, st["mig_id"], st["attempt"])
            with self._lock:
                if key in self._resumed_migs:
                    continue
                spec = self._jobs.get(job)
            old = self._ps_replicas_of(spec) if spec else None
            new = int(st["new_table"]["num_replicas"])
            event = {"job": job, "from": old, "to": new,
                     "mig_id": st["mig_id"], "phase": st["phase"],
                     "time": _time.strftime("%Y-%m-%dT%H:%M:%S"),
                     "status": "resume_pending"}
            if self._reshard_driver is not None and spec is not None:
                try:
                    self._reshard_driver(job, old, new, "resume", spec)
                    event["status"] = "resumed"
                except Exception as e:
                    # a failed resume must RETRY next pass, not be
                    # silently marked handled (the PS fleet is often
                    # briefly unreachable right after an operator
                    # restart — exactly when this scan runs); other
                    # jobs' scans proceed regardless
                    _logger.error("reshard resume driver for %s "
                                  "failed (will retry): %s", job, e)
                    event["status"] = "resume_failed"
                    event["error"] = str(e)
                    with self._lock:
                        self._reshard_events.append(event)
                    events.append(event)
                    continue
            # handled (resumed, or surfaced as pending for a
            # driverless operator) — don't re-fire for this attempt
            with self._lock:
                self._resumed_migs.add(key)
            _logger.warning(
                "reshard journal for %s shows migration %s in flight "
                "(phase %s) -> %s", job, st["mig_id"], st["phase"],
                event["status"])
            with self._lock:
                self._reshard_events.append(event)
            events.append(event)
        return events

    def reconcile_all(self, specs: Optional[List[dict]] = None):
        """One pass over every tracked job. ``specs`` overrides the
        snapshot (tests use it to inject a stale one and prove the
        deleted-while-iterating guard below). Every pass also scans
        the tracked jobs' migration journals (each in-flight attempt
        handled once) — a reshard a previous operator incarnation died
        driving is resumed (or surfaced) before any pod churn can race
        it, including for jobs tracked after startup."""
        try:
            self.resume_pending_reshards()
        except Exception as e:
            _logger.error("reshard resume scan failed: %s", e)
        if specs is None:
            with self._lock:
                specs = list(self._jobs.values())
        for spec in specs:
            with self._lock:
                if spec["jobName"] not in self._jobs:
                    continue  # deleted since the snapshot — do not
                    # resurrect a torn-down job's pods
                try:
                    self._reconcile_job_locked(spec)
                except Exception as e:  # keep the loop alive (operator.rs
                    # requeues on error rather than crashing)
                    _logger.error("reconcile %s failed: %s",
                                  spec.get("jobName"), e)

    def sync_custom_resources(self):
        """Poll PersiaJob custom resources and converge the tracked-job
        set on them (the reference Controller watches the CRD stream,
        operator.rs:25-123; a poll every reconcile interval gives the
        same convergence without a watch API). CR spec = the job spec;
        removed CRs untrack (and tear down) their jobs."""
        crs = self.api.list_custom()
        seen = set()
        for cr in crs:
            spec = cr.get("spec", cr)
            name = spec.get("jobName") or cr.get("metadata", {}).get("name")
            if not name:
                continue
            spec = dict(spec, jobName=name)
            seen.add(name)
            with self._lock:
                # a job the user re-applied via REST/YAML is owned by
                # them — the CR must not reclaim it (or overwrite their
                # spec) on the next poll
                if name in self._jobs and name not in self._from_cr:
                    continue
            self.track(spec, source="cr")
        # only CR-sourced jobs are governed by CR deletion; jobs tracked
        # from YAML argv or the REST API are untouched. Stale detection
        # and the untrack run under ONE lock hold — releasing in between
        # would let a concurrent REST /apply re-track the job only to
        # have it silently torn down here.
        with self._lock:
            for j in list(self._from_cr - seen):
                _logger.info("PersiaJob %s deleted; tearing down", j)
                self._from_cr.discard(j)
                self.untrack(j)

    def run(self, from_crd: bool = False):
        while not self._stop.is_set():
            if from_crd:
                try:
                    self.sync_custom_resources()
                except Exception as e:
                    _logger.error("CR sync failed: %s", e)
            self.reconcile_all()
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()


class SchedulingServer:
    """REST surface over the operator (reference: the actix-web
    scheduling server, k8s/src/bin/server.rs — /apply /delete /listjobs
    /listpods /podstatus). Submitting a job spec tracks + reconciles it;
    deleting untracks + tears it down."""

    def __init__(self, operator: Operator, host: str = "127.0.0.1",
                 port: int = 0):
        import http.server

        op = operator

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # route through our logger
                _logger.debug("rest: " + a[0], *a[1:])

            def _send(self, code: int, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _query(self) -> dict:
                from urllib.parse import parse_qsl, urlparse

                return dict(parse_qsl(urlparse(self.path).query))

            def do_GET(self):
                from urllib.parse import urlparse

                route = urlparse(self.path).path
                q = self._query()
                try:
                    if route == "/listjobs":
                        self._send(200, {"jobs": op.job_names()})
                    elif route == "/listpods":
                        job = q.get("job", "")
                        pods = [
                            {"name": o["metadata"]["name"],
                             "phase": o.get("status", {}).get("phase")}
                            for o in op.objects_of(job)
                            if o["kind"] == "Pod"
                        ]
                        self._send(200, {"pods": pods})
                    elif route == "/podstatus":
                        job, pod = q.get("job", ""), q.get("pod", "")
                        for o in op.objects_of(job):
                            if (o["kind"] == "Pod"
                                    and o["metadata"]["name"] == pod):
                                self._send(200, {
                                    "phase": o.get("status", {}).get("phase")
                                })
                                return
                        self._send(404, {"error": f"pod {pod!r} not found"})
                    elif route == "/reshards":
                        self._send(200, {"events": op.reshard_events()})
                    elif route == "/variants":
                        self._send(200, {"events": op.variant_events()})
                    elif route == "/autopilot":
                        # the attached autopilot's posture + recent
                        # decisions (enabled: false when none attached)
                        self._send(200, op.autopilot_doc())
                    else:
                        self._send(404, {"error": f"no route {route!r}"})
                except Exception as e:  # surface as HTTP, keep serving
                    self._send(500, {"error": repr(e)})

            def do_POST(self):
                from urllib.parse import urlparse

                route = urlparse(self.path).path
                try:
                    if route == "/apply":
                        n = int(self.headers.get("Content-Length", 0))
                        spec = json.loads(self.rfile.read(n))
                        # validate BEFORE track: an invalid spec must not
                        # stay tracked, or the reconcile loop re-raises on
                        # every interval until a manual /delete
                        from persia_tpu.k8s_utils import validate_spec

                        try:
                            manifests = validate_spec(spec)
                        except Exception as e:
                            self._send(400, {"error": repr(e)})
                            return
                        op.track(spec)
                        stats = op.reconcile_job(spec, manifests)
                        self._send(200, {"job": spec["jobName"],
                                         "reconcile": stats})
                    elif route == "/delete":
                        job = self._query().get("job", "")
                        op.untrack(job)
                        self._send(200, {"deleted": job})
                    elif route == "/scale":
                        # elastic PS tier: reconcile the replica count
                        # with the live reshard sequenced around pod
                        # churn (see Operator.scale_ps)
                        n = int(self.headers.get("Content-Length", 0))
                        req = json.loads(self.rfile.read(n))
                        try:
                            event = op.scale_ps(req["jobName"],
                                                int(req["psReplicas"]))
                        except KeyError as e:
                            self._send(404, {"error": repr(e)})
                            return
                        except ValueError as e:
                            self._send(400, {"error": repr(e)})
                            return
                        self._send(200, event)
                    elif route == "/variants":
                        # multi-variant serving control: forward a live
                        # add/remove/promote/weight/drain to the job's
                        # serving replicas (see Operator.variant_op)
                        n = int(self.headers.get("Content-Length", 0))
                        req = json.loads(self.rfile.read(n))
                        try:
                            event = op.variant_op(
                                req["jobName"], req["op"],
                                {k: v for k, v in req.items()
                                 if k not in ("jobName", "op")})
                        except KeyError as e:
                            self._send(404, {"error": repr(e)})
                            return
                        except ValueError as e:
                            self._send(400, {"error": repr(e)})
                            return
                        self._send(200, event)
                    else:
                        self._send(404, {"error": f"no route {route!r}"})
                except Exception as e:
                    self._send(500, {"error": repr(e)})

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.addr = f"{host}:{self._httpd.server_address[1]}"

    def serve_background(self):
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name=f"k8s-rest-{self.addr}").start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None):
    p = argparse.ArgumentParser(prog="persia-tpu-operator")
    p.add_argument("job_yamls", nargs="*", help="job spec YAML files")
    p.add_argument("--namespace", default="default")
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("--once", action="store_true",
                   help="single reconcile pass, then exit")
    p.add_argument("--serve", default=None, metavar="HOST:PORT",
                   help="also expose the REST scheduling API")
    p.add_argument("--from-crd", action="store_true",
                   help="watch PersiaJob custom resources (install the "
                        "CRD via `python -m persia_tpu.k8s_utils gencrd`)")
    args = p.parse_args(argv)
    if not args.job_yamls and not args.serve and not args.from_crd:
        p.error("give job YAML files, --serve HOST:PORT, --from-crd, "
                "or a combination")
    if args.once and args.serve:
        p.error("--once exits immediately and would kill the REST server; "
                "use one or the other")
    specs = [load_yaml(f) for f in args.job_yamls]
    op = Operator(KubectlApi(args.namespace), specs, interval=args.interval)
    if args.serve:
        if ":" not in args.serve:
            p.error(f"--serve expects HOST:PORT, got {args.serve!r}")
        host, port = args.serve.rsplit(":", 1)
        server = SchedulingServer(op, host, int(port))
        server.serve_background()
        _logger.info("scheduling REST API on %s", server.addr)
    if args.once:
        if args.from_crd:
            op.sync_custom_resources()
        op.reconcile_all()
    else:
        op.run(from_crd=args.from_crd)


if __name__ == "__main__":
    main()
