"""Kubernetes reconcile loop for persia_tpu jobs.

The reference runs a Rust kube-runtime Controller that creates the
job's pods, restarts failures, and tears everything down on delete
(k8s/src/bin/operator.rs:25-123, reconcile interval 10 s, with
PersiaJobResources apply/delete in k8s/src/lib.rs). This is the same
control loop over the declarative manifests from
:mod:`persia_tpu.k8s_utils`:

- **desired state** = ``gen_manifests(job_spec)`` for every tracked job
- **observed state** = pods/services labeled ``persia-job=<name>``
- reconcile: create missing objects, delete+recreate pods in a terminal
  phase (Failed, or Succeeded for long-running roles), delete objects
  that are no longer desired, and tear down all objects of untracked
  (deleted) jobs.

The API surface is pluggable: :class:`KubectlApi` shells out to
``kubectl`` (no client library dependency, works against any cluster),
and :class:`FakeKubeApi` is an in-memory twin for tests (the reference's
operator is e2e-tested against a real cluster, k8s/src/bin/e2e.rs; the
fake gives the same coverage in-process).

CLI: ``python -m persia_tpu.k8s_operator job1.yml job2.yml
[--interval 10] [--once]``
"""

import argparse
import json
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from persia_tpu.k8s_utils import gen_manifests
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import load_yaml

_logger = get_default_logger(__name__)

# Service roles run forever — any terminal phase (even Succeeded) means
# the process exited and must be replaced. Entry-script roles (trainer,
# data-loader) legitimately finish: only Failed/Unknown restarts them.
_SERVICE_ROLES = frozenset({
    "coordinator", "embeddingParameterServer", "embeddingWorker",
    "metricsGateway",
})
_FAILED_PHASES = ("Failed", "Unknown")
_SERVICE_TERMINAL_PHASES = ("Failed", "Succeeded", "Unknown")


def _pod_needs_restart(manifest: dict, observed: dict) -> bool:
    phase = observed.get("status", {}).get("phase")
    role = manifest["metadata"].get("labels", {}).get("persia-role", "")
    terminal = (_SERVICE_TERMINAL_PHASES if role in _SERVICE_ROLES
                else _FAILED_PHASES)
    return phase in terminal


class KubectlApi:
    """Real-cluster access through the kubectl CLI."""

    def __init__(self, namespace: str = "default", kubectl: str = "kubectl"):
        self.namespace = namespace
        self.kubectl = kubectl

    def _run(self, args: List[str], stdin: Optional[str] = None) -> str:
        proc = subprocess.run(
            [self.kubectl, "-n", self.namespace, *args],
            input=stdin, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"kubectl {' '.join(args)} failed: {proc.stderr.strip()}")
        return proc.stdout

    def apply(self, manifest: dict):
        self._run(["apply", "-f", "-"], stdin=json.dumps(manifest))

    def delete(self, kind: str, name: str):
        self._run(["delete", kind.lower(), name, "--ignore-not-found",
                   "--wait=false"])

    def list_objects(self, label_selector: str) -> List[dict]:
        out = []
        for kind in ("pods", "services"):
            data = json.loads(
                self._run(["get", kind, "-l", label_selector, "-o", "json"]))
            out.extend(data.get("items", []))
        return out


class FakeKubeApi:
    """In-memory twin of KubectlApi for unit tests.

    Tests mutate observed state directly (``kill_pod``) to simulate
    crashes; new pods come up ``Running``.
    """

    def __init__(self):
        # (kind, name) -> manifest (with .status.phase for pods)
        self.objects: Dict[Tuple[str, str], dict] = {}
        self.apply_log: List[str] = []
        self.delete_log: List[str] = []

    def apply(self, manifest: dict):
        kind = manifest["kind"]
        name = manifest["metadata"]["name"]
        manifest = dict(manifest)
        if kind == "Pod":
            manifest["status"] = {"phase": "Running"}
        self.objects[(kind, name)] = manifest
        self.apply_log.append(f"{kind}/{name}")

    def delete(self, kind: str, name: str):
        self.objects.pop((kind.capitalize(), name), None)
        # kubectl's kind argument is lowercase; normalize both spellings
        self.objects.pop((kind, name), None)
        self.delete_log.append(f"{kind}/{name}")

    def list_objects(self, label_selector: str) -> List[dict]:
        want = dict(kv.split("=", 1) for kv in label_selector.split(","))
        out = []
        for obj in self.objects.values():
            labels = obj.get("metadata", {}).get("labels", {})
            if all(labels.get(k) == v for k, v in want.items()):
                out.append(obj)
        return out

    def kill_pod(self, name: str, phase: str = "Failed"):
        self.objects[("Pod", name)]["status"] = {"phase": phase}


class Operator:
    """The reconcile loop (reference operator.rs:25-123)."""

    def __init__(self, api, job_specs: Optional[List[dict]] = None,
                 interval: float = 10.0):
        self.api = api
        self.interval = interval
        self._jobs: Dict[str, dict] = {}
        self._stop = threading.Event()
        for spec in job_specs or []:
            self.track(spec)

    # --- job tracking (the CRD add/delete events) -----------------------

    def track(self, spec: dict):
        self._jobs[spec["jobName"]] = spec

    def untrack(self, job_name: str):
        """Stop managing a job; its objects are torn down on the next
        reconcile (the reference's delete finalizer)."""
        self._jobs.pop(job_name, None)
        self.teardown(job_name)

    def teardown(self, job_name: str):
        for obj in self.api.list_objects(f"persia-job={job_name}"):
            self.api.delete(obj["kind"], obj["metadata"]["name"])

    # --- reconcile ------------------------------------------------------

    def reconcile_job(self, spec: dict) -> Dict[str, int]:
        """Drive one job toward its desired manifest set. Returns action
        counts (created/restarted/removed) for observability."""
        job = spec["jobName"]
        desired = {
            (m["kind"], m["metadata"]["name"]): m
            for m in gen_manifests(spec)
        }
        observed = {
            (o["kind"], o["metadata"]["name"]): o
            for o in self.api.list_objects(f"persia-job={job}")
        }
        stats = {"created": 0, "restarted": 0, "removed": 0}
        for key, manifest in desired.items():
            obj = observed.get(key)
            if obj is None:
                self.api.apply(manifest)
                stats["created"] += 1
            elif key[0] == "Pod" and _pod_needs_restart(manifest, obj):
                # dead pod: delete now; the NEXT pass's missing-object
                # branch recreates it. Re-applying the same name in the
                # same pass races the apiserver's termination grace
                # period (the object still exists with a
                # deletionTimestamp) and would abort the reconcile.
                self.api.delete(key[0], key[1])
                stats["restarted"] += 1
        for key in observed.keys() - desired.keys():
            self.api.delete(key[0], key[1])
            stats["removed"] += 1
        if any(stats.values()):
            _logger.info("reconciled %s: %s", job, stats)
        return stats

    def reconcile_all(self):
        for spec in list(self._jobs.values()):
            try:
                self.reconcile_job(spec)
            except Exception as e:  # keep the loop alive (operator.rs
                # requeues on error rather than crashing)
                _logger.error("reconcile %s failed: %s",
                              spec.get("jobName"), e)

    def run(self):
        while not self._stop.is_set():
            self.reconcile_all()
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()


def main(argv=None):
    p = argparse.ArgumentParser(prog="persia-tpu-operator")
    p.add_argument("job_yamls", nargs="+", help="job spec YAML files")
    p.add_argument("--namespace", default="default")
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("--once", action="store_true",
                   help="single reconcile pass, then exit")
    args = p.parse_args(argv)
    specs = [load_yaml(f) for f in args.job_yamls]
    op = Operator(KubectlApi(args.namespace), specs, interval=args.interval)
    if args.once:
        op.reconcile_all()
    else:
        op.run()


if __name__ == "__main__":
    main()
