"""Datasets + DataLoader (reference: persia/data.py).

The reference's ``DataLoader`` owns a native ``Forward`` pipeline engine
(rust/persia-core/src/forward.rs) that prefetches embedding lookups and
yields GPU-resident ``PersiaTrainingBatch``es. Here the engine is
:class:`persia_tpu.pipeline.ForwardEngine`; it overlaps embedding-worker
RPC, host staging, and TPU transfer, bounded by the embedding-staleness
semaphore, and yields :class:`TrainingBatch` of JAX arrays.
"""

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional

from persia_tpu.data.batch import PersiaBatch
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)


# The batch type yielded by DataLoader: embeddings fetched, gradient
# handle attached (reference: PersiaTrainingBatch, forward.rs:101-117).
from persia_tpu.pipeline import LookedUpBatch as TrainingBatch  # noqa: E402


class IterableDatasetBase(Iterable[PersiaBatch]):
    """Anything that yields :class:`PersiaBatch` (reference: data.py:29-94)."""

    def __init__(self, buffer_size: int = 128):
        self.buffer_size = buffer_size

    def __iter__(self) -> Iterator[PersiaBatch]:
        raise NotImplementedError


class IterableDataset(IterableDatasetBase):
    """Wraps a local python iterable producing PersiaBatch, decoupled
    through a background thread + bounded queue (reference: data.py:141-199)."""

    def __init__(self, source: Iterable[PersiaBatch], buffer_size: int = 128):
        super().__init__(buffer_size)
        self.source = source

    def __iter__(self) -> Iterator[PersiaBatch]:
        q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        _SENTINEL = object()
        error: List[BaseException] = []

        def _producer():
            try:
                for item in self.source:
                    q.put(item)
            except BaseException as e:  # surface producer failures to consumer
                error.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=_producer, daemon=True, name="dataset-producer")
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                if error:
                    raise error[0]
                return
            yield item


class ResumableDataset(IterableDatasetBase):
    """Deterministic, cursor-tracked dataset — the data leg of the
    whole-job snapshot protocol (persia_tpu/snapshot.py).

    ``factory(seed)`` must return a FRESH batch iterator that is a pure
    function of the seed (the workload-zoo generators are: same seed →
    byte-identical stream). The dataset skips the first ``start``
    batches — batches a previous incarnation of the job already
    trained — and counts every batch it hands out, so
    :meth:`cursor` names an exact position in the stream that a
    restarted process reproduces from nothing but ``{seed, consumed}``.

    The cursor is keyed to TRAINED batches, not produced ones: the
    prefetch pipeline runs ahead of the optimizer, so at snapshot time
    the trainer passes the number of batches it has fully stepped
    (``cursor(trained=...)``); resume re-yields everything past that
    point, including batches that were sitting in the pipeline when
    the process died.

    **Multi-process sharding**: ``process_index``/``process_count``
    round-robin-partition the ONE global deterministic stream across a
    trainer group — process ``p`` of ``N`` yields exactly the global
    batches whose stream position ``i`` satisfies ``i % N == p``, so
    the union of the per-process shard streams IS the 1-process stream
    (no batch trained twice, none skipped, order within a shard
    preserved). ``start`` and the cursor stay in PER-PROCESS trained
    batches; the cursor additionally records the shard coordinates so
    a resumed process refuses a cursor cut for a different shard.
    Defaults (0, 1) are the historic single-process stream, positions
    and cursor dict byte-identical.
    """

    def __init__(self, factory, seed: int = 0, start: int = 0,
                 buffer_size: int = 128, process_index: int = 0,
                 process_count: int = 1):
        super().__init__(buffer_size)
        self.factory = factory
        self.seed = int(seed)
        self.start = int(start)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        if not 0 <= self.process_index < self.process_count:
            raise ValueError(
                f"process_index {self.process_index} outside group of "
                f"{self.process_count}")
        self.produced = 0  # batches handed out by THIS incarnation

    def cursor(self, trained: Optional[int] = None) -> Dict[str, int]:
        """Snapshot cursor. ``trained`` = batches fully stepped this
        incarnation; defaults to every batch handed out (exact only
        when nothing runs ahead of the consumer)."""
        n = self.produced if trained is None else int(trained)
        cur = {"seed": self.seed, "consumed": self.start + n}
        if self.process_count != 1:
            # shard coordinates ride the cursor ONLY for sharded
            # streams: the 1-process cursor dict (and with it the
            # snapshot manifest) stays byte-identical to the historic
            # format
            cur["process_index"] = self.process_index
            cur["process_count"] = self.process_count
        return cur

    @classmethod
    def from_cursor(cls, factory, cursor: Dict[str, int],
                    buffer_size: int = 128, process_index: int = 0,
                    process_count: int = 1) -> "ResumableDataset":
        cur_count = int(cursor.get("process_count", 1))
        cur_index = int(cursor.get("process_index", 0))
        if process_count == 1 and cur_count != 1:
            # a sharded cursor restored without explicit coordinates
            # resumes ITS shard (the cursor names the stream cut)
            process_index, process_count = cur_index, cur_count
        elif (cur_count, cur_index) != (1, 0) and (
                (process_index, process_count) != (cur_index, cur_count)):
            raise ValueError(
                f"cursor names shard {cur_index}/{cur_count} but resume "
                f"asked for {process_index}/{process_count} — a "
                f"per-process cursor only positions its own shard")
        return cls(factory, seed=cursor["seed"], start=cursor["consumed"],
                   buffer_size=buffer_size, process_index=process_index,
                   process_count=process_count)

    def __iter__(self) -> Iterator[PersiaBatch]:
        import itertools

        # global stream position of this shard's next batch: shard
        # batches sit at global positions p, p+N, p+2N, ...; ``start``
        # per-process trained batches == start*N global batches behind
        it = itertools.islice(iter(self.factory(self.seed)),
                              self.process_index
                              + self.start * self.process_count,
                              None, self.process_count)
        for batch in it:
            self.produced += 1
            yield batch


class StreamingDataset(IterableDatasetBase):
    """Binds the dataflow receiver: batches pushed by remote data-loader
    processes over the message queue (reference: data.py:97-138).

    The receiver is registered by ``TrainCtx``/``DataCtx`` wiring; iteration
    blocks on the network queue forever (training-stream semantics).
    """

    def __init__(self, receiver=None, buffer_size: int = 128):
        super().__init__(buffer_size)
        self._receiver = receiver  # persia_tpu.service.dataflow.DataflowReceiver

    def bind_receiver(self, receiver):
        self._receiver = receiver

    def __iter__(self) -> Iterator[PersiaBatch]:
        if self._receiver is None:
            raise RuntimeError(
                "StreamingDataset not bound to a dataflow receiver; "
                "construct it with a persia_tpu.service.dataflow."
                "DataflowReceiver (or call bind_receiver)"
            )
        while True:
            batch = self._receiver.get()
            if batch is None:
                return
            yield batch


class DataLoader:
    """Drives the forward engine over a dataset
    (reference: persia/data.py:202-271).

    Arguments mirror the reference: ``forward_buffer_size`` bounds the
    prefetch pipeline, ``embedding_staleness`` bounds how many batches may
    have in-flight (unreturned) embedding gradients, ``reproducible``
    enables the batch-id reorder buffer so iteration order is deterministic.
    """

    def __init__(
        self,
        dataset: IterableDatasetBase,
        forward_buffer_size: int = 10,
        timeout_ms: int = 1000 * 60 * 10,
        num_workers: int = 8,
        reproducible: bool = False,
        embedding_staleness: Optional[int] = None,
    ):
        self.dataset = dataset
        self.timeout_ms = timeout_ms
        self.forward_buffer_size = forward_buffer_size
        self.num_workers = num_workers
        self.reproducible = reproducible
        self.embedding_staleness = embedding_staleness
        self._engine = None

    def _ensure_engine(self):
        if self._engine is None:
            try:
                from persia_tpu.ctx import current_ctx
                from persia_tpu.pipeline import ForwardEngine
            except ImportError as e:
                raise RuntimeError(
                    f"DataLoader requires persia_tpu.ctx and "
                    f"persia_tpu.pipeline (import failed: {e})"
                ) from e

            ctx = current_ctx()
            if ctx is None:
                raise RuntimeError(
                    "DataLoader requires an active EmbeddingCtx/TrainCtx"
                )
            self._engine = ForwardEngine(
                ctx=ctx,
                num_workers=self.num_workers,
                buffer_size=self.forward_buffer_size,
                reproducible=self.reproducible,
                embedding_staleness=self.embedding_staleness,
            )
        return self._engine

    def __iter__(self) -> Iterator[TrainingBatch]:
        from persia_tpu.ctx import current_ctx

        ctx = current_ctx()
        if ctx is not None and getattr(ctx, "device_cache_capacity", 0):
            # Device-cache path: the worker-lookup prefetch pipeline is
            # skipped entirely — the cached step does its own (cheaper)
            # miss imports, and JAX's async dispatch already overlaps
            # batch i+1's host work (mapper assign + PS miss fetch) with
            # batch i's device step. The dataset's background producer
            # still decouples the data source. Ordered iteration is
            # REQUIRED here: batch order is the cache's LRU order.
            yield from iter(self.dataset)
            return
        engine = self._ensure_engine()
        try:
            yield from engine.run(iter(self.dataset), timeout_ms=self.timeout_ms)
        finally:
            # drain in-flight gradient updates so a finished epoch leaves
            # no pending PS writes (reference: backward.rs release path)
            engine.flush(timeout=self.timeout_ms / 1000.0)
