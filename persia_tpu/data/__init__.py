from persia_tpu.data.batch import (
    MAX_BATCH_SIZE,
    IDTypeFeature,
    IDTypeFeatureWithSingleID,
    Label,
    NonIDTypeFeature,
    PersiaBatch,
)
from persia_tpu.data.dataloader import (
    DataLoader,
    IterableDataset,
    StreamingDataset,
    TrainingBatch,
)

__all__ = [
    "MAX_BATCH_SIZE",
    "IDTypeFeature",
    "IDTypeFeatureWithSingleID",
    "NonIDTypeFeature",
    "Label",
    "PersiaBatch",
    "DataLoader",
    "IterableDataset",
    "StreamingDataset",
    "TrainingBatch",
]
