"""Batch construction + the persia_tpu wire format.

Reference surface: persia/embedding/data.py (IDTypeFeature LIL matrices,
NdarrayDataBase, PersiaBatch marshalling into the native _PersiaBatch).

TPU-first design differences:

- ID features are stored **CSR** (offsets + flat signs) instead of LIL —
  one contiguous uint64 buffer per feature serializes with zero copies
  and is what the C++ worker consumes directly.
- Serialization is a simple length-prefixed little-endian binary layout
  (`PTB2`) replacing the reference's speedy format. This Python
  implementation is the format's source of truth.
"""

import struct
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from persia_tpu.env import skip_check_data

# Maximum supported batch size: sample indices travel as u16 pairs in the
# worker's dedup maps (reference: persia/embedding/data.py:14).
MAX_BATCH_SIZE = 65535

MAGIC = b"PTB2"

# Header flag bits (PTB2): presence flags instead of in-band sentinels so
# batch_id=-1 and meta=b"" round-trip losslessly.
_FLAG_REQUIRES_GRAD = 1
_FLAG_HAS_BATCH_ID = 2
_FLAG_HAS_META = 4

_ND_SUPPORTED_DTYPES = (
    np.bool_,
    np.int8,
    np.int16,
    np.int32,
    np.int64,
    np.float32,
    np.float64,
    np.uint8,
)

# Stable dtype codes for the wire format.
_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int8): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.uint8): 6,
    np.dtype(np.bool_): 7,
    np.dtype(np.uint64): 8,
    np.dtype(np.uint16): 9,  # bf16 raw bits travel as uint16
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


class IDTypeFeature:
    """One sparse categorical feature for a batch, as a list of per-sample
    uint64 ID arrays (LIL). Stored internally as CSR."""

    def __init__(self, name: str, data: List[np.ndarray]):
        if not skip_check_data():
            for x in data:
                if not isinstance(x, np.ndarray) or x.ndim != 1 or x.dtype != np.uint64:
                    raise TypeError(
                        f"id_type_feature {name!r}: every sample must be a 1-D "
                        f"np.uint64 ndarray, got {type(x)} "
                        f"{getattr(x, 'dtype', None)} ndim={getattr(x, 'ndim', None)}"
                    )
        self.name = name
        self.offsets = np.zeros(len(data) + 1, dtype=np.uint32)
        if data:
            np.cumsum([len(x) for x in data], out=self.offsets[1:])
            self.signs = (
                np.concatenate(data) if self.offsets[-1] > 0
                else np.empty(0, dtype=np.uint64)
            ).astype(np.uint64, copy=False)
        else:
            self.signs = np.empty(0, dtype=np.uint64)

    @classmethod
    def from_csr(cls, name: str, offsets: np.ndarray, signs: np.ndarray):
        obj = cls.__new__(cls)
        obj.name = name
        obj.offsets = offsets.astype(np.uint32, copy=False)
        obj.signs = signs.astype(np.uint64, copy=False)
        return obj

    @property
    def batch_size(self) -> int:
        return len(self.offsets) - 1

    @property
    def data(self) -> List[np.ndarray]:
        """LIL view (reference-compatible accessor)."""
        return [
            self.signs[self.offsets[i] : self.offsets[i + 1]]
            for i in range(self.batch_size)
        ]


class IDTypeFeatureWithSingleID(IDTypeFeature):
    """Exactly one ID per sample; single vectorized type check
    (reference: embedding/data.py:116-157)."""

    def __init__(self, name: str, data: np.ndarray):
        if not skip_check_data():
            if (
                not isinstance(data, np.ndarray)
                or data.ndim != 1
                or data.dtype != np.uint64
            ):
                raise TypeError(
                    f"id_type_feature {name!r} must be a 1-D np.uint64 ndarray"
                )
        self.name = name
        self.offsets = np.arange(len(data) + 1, dtype=np.uint32)
        self.signs = data


class NdarrayBase:
    DEFAULT_NAME = "ndarray_base"

    def __init__(self, data: np.ndarray, name: Optional[str] = None):
        if not skip_check_data():
            if not isinstance(data, np.ndarray):
                raise TypeError(f"{name or self.DEFAULT_NAME} must be np.ndarray")
            if data.dtype.type not in _ND_SUPPORTED_DTYPES:
                raise TypeError(
                    f"{name or self.DEFAULT_NAME} unsupported dtype {data.dtype}; "
                    f"supported: {_ND_SUPPORTED_DTYPES}"
                )
            if data.ndim < 1:
                raise ValueError(f"{name or self.DEFAULT_NAME} must have ndim >= 1")
        self.data = np.ascontiguousarray(data)
        self._name = name

    @property
    def name(self) -> str:
        return self._name if self._name is not None else self.DEFAULT_NAME

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


class NonIDTypeFeature(NdarrayBase):
    DEFAULT_NAME = "non_id_type_feature"


class Label(NdarrayBase):
    DEFAULT_NAME = "label"


def _check_batch_size(batch_size: int, target: Optional[int], kind: str, name: str):
    if target is not None and batch_size != target:
        raise ValueError(
            f"{kind} {name!r}: batch_size {batch_size} != expected {target}"
        )
    if batch_size > MAX_BATCH_SIZE:
        raise ValueError(
            f"{kind} {name!r}: batch_size {batch_size} > MAX_BATCH_SIZE {MAX_BATCH_SIZE}"
        )


class PersiaBatch:
    """One training/inference batch: ID features + dense features + labels.

    Reference surface: persia/embedding/data.py:279-411. ``to_bytes`` /
    ``from_bytes`` implement the PTB2 wire layout consumed by the
    dataflow message queue between data-loader and trainer processes.
    """

    def __init__(
        self,
        id_type_features: Sequence[IDTypeFeature],
        non_id_type_features: Optional[Sequence[NonIDTypeFeature]] = None,
        labels: Optional[Sequence[Label]] = None,
        batch_id: Optional[int] = None,
        requires_grad: bool = True,
        meta: Optional[bytes] = None,
    ):
        if len(id_type_features) == 0:
            raise ValueError("id_type_features must be non-empty")
        batch_size = id_type_features[0].batch_size
        for f in id_type_features:
            _check_batch_size(f.batch_size, batch_size, "id_type_feature", f.name)
        for group in (non_id_type_features or []), (labels or []):
            for x in group:
                _check_batch_size(x.batch_size, batch_size, type(x).__name__, x.name)

        self.id_type_features = list(id_type_features)
        self.non_id_type_features = list(non_id_type_features or [])
        self.labels = list(labels or [])
        self.batch_id = batch_id
        self.requires_grad = requires_grad
        self.meta = meta
        self.batch_size = batch_size
        # (worker_addr, ref_id) when this batch's ID features were already
        # ingested into a remote embedding worker by a data-loader
        # (reference: IDTypeFeatureRemoteRef, persia-common/src/lib.rs:115-155)
        self.remote_ref = None

    # --- wire format -----------------------------------------------------

    def to_bytes(self) -> bytes:
        out = [MAGIC]
        flags = 0
        if self.requires_grad:
            flags |= _FLAG_REQUIRES_GRAD
        if self.batch_id is not None:
            flags |= _FLAG_HAS_BATCH_ID
        if self.meta is not None:
            flags |= _FLAG_HAS_META
        out.append(
            struct.pack(
                "<qBH",
                self.batch_id if self.batch_id is not None else 0,
                flags,
                self.batch_size,
            )
        )
        meta = self.meta if self.meta is not None else b""
        out.append(struct.pack("<I", len(meta)))
        out.append(meta)

        out.append(struct.pack("<H", len(self.id_type_features)))
        for f in self.id_type_features:
            name_b = f.name.encode()
            out.append(struct.pack("<H", len(name_b)))
            out.append(name_b)
            out.append(struct.pack("<IQ", f.batch_size, len(f.signs)))
            out.append(np.ascontiguousarray(f.offsets).tobytes())
            out.append(np.ascontiguousarray(f.signs).tobytes())

        for group in (self.non_id_type_features, self.labels):
            out.append(struct.pack("<H", len(group)))
            for x in group:
                name_b = x.name.encode()
                out.append(struct.pack("<H", len(name_b)))
                out.append(name_b)
                arr = x.data
                out.append(struct.pack("<BB", _DTYPE_CODES[arr.dtype], arr.ndim))
                out.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
                out.append(arr.tobytes())
        return b"".join(out)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "PersiaBatch":
        view = memoryview(buf)
        if bytes(view[:4]) != MAGIC:
            raise ValueError("bad PersiaBatch magic")
        pos = 4
        batch_id, flags, batch_size = struct.unpack_from("<qBH", view, pos)
        pos += struct.calcsize("<qBH")
        (meta_len,) = struct.unpack_from("<I", view, pos)
        pos += 4
        meta = (
            bytes(view[pos : pos + meta_len]) if flags & _FLAG_HAS_META else None
        )
        pos += meta_len

        (n_id,) = struct.unpack_from("<H", view, pos)
        pos += 2
        id_feats = []
        for _ in range(n_id):
            (name_len,) = struct.unpack_from("<H", view, pos)
            pos += 2
            name = bytes(view[pos : pos + name_len]).decode()
            pos += name_len
            bs, nnz = struct.unpack_from("<IQ", view, pos)
            pos += struct.calcsize("<IQ")
            offsets = np.frombuffer(view, dtype=np.uint32, count=bs + 1, offset=pos)
            pos += 4 * (bs + 1)
            signs = np.frombuffer(view, dtype=np.uint64, count=nnz, offset=pos)
            pos += 8 * nnz
            id_feats.append(IDTypeFeature.from_csr(name, offsets.copy(), signs.copy()))

        groups = []
        for klass in (NonIDTypeFeature, Label):
            (n,) = struct.unpack_from("<H", view, pos)
            pos += 2
            items = []
            for _ in range(n):
                (name_len,) = struct.unpack_from("<H", view, pos)
                pos += 2
                name = bytes(view[pos : pos + name_len]).decode()
                pos += name_len
                dtype_code, ndim = struct.unpack_from("<BB", view, pos)
                pos += 2
                shape = struct.unpack_from(f"<{ndim}I", view, pos)
                pos += 4 * ndim
                dtype = _CODE_DTYPES[dtype_code]
                count = int(np.prod(shape)) if ndim else 0
                arr = np.frombuffer(view, dtype=dtype, count=count, offset=pos).reshape(
                    shape
                )
                pos += arr.nbytes
                items.append(klass(arr.copy(), name=name))
            groups.append(items)

        return cls(
            id_type_features=id_feats,
            non_id_type_features=groups[0],
            labels=groups[1],
            batch_id=batch_id if flags & _FLAG_HAS_BATCH_ID else None,
            requires_grad=bool(flags & _FLAG_REQUIRES_GRAD),
            meta=meta,
        )
