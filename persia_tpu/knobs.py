"""Central typed registry of every ``PERSIA_*`` environment knob.

Before this module existed the stack had ~35 ``PERSIA_*`` reads
scattered over 15 modules, each with its own parse convention
(``== "1"`` vs ``!= "0"`` vs ``in ("1", "true", "yes")``), no single
place to look up what exists, and one real footgun: a module-level
``os.environ.get`` freezes the knob at import time, silently ignoring
anything a test or launcher sets later (the old ``env.py``
``PERSIA_SKIP_CHECK_DATA`` bug). ``tools/persialint``'s knob-registry
pass now rejects any direct ``os.environ`` read of a ``PERSIA_*`` name
outside this file, any ``knobs.get`` of an unregistered name (typo
guard), and any import-time read of a knob not explicitly marked
``import_time_safe`` — and ``docs/KNOBS.md`` is rendered from this
registry, so the docs cannot drift.

Parse conventions (kept bit-compatible with the historical call sites):

- ``bool`` knobs whose default is **False** are enabled by
  ``1``/``true``/``yes`` (case-insensitive) — the ``== "1"`` family;
- ``bool`` knobs whose default is **True** are disabled only by the
  literal ``0`` — the ``!= "0"`` family (any other value keeps them on);
- ``int`` knobs parse with ``int()``; unset -> the registered default;
- ``str`` knobs return the raw value; unset -> the registered default.

``get`` applies the registered default; ``get_raw`` returns the
environment string (or the caller's fallback) for sites whose local
default differs from the canonical one (argparse ``default=None``
"was it set at all?" probes). Both read ``os.environ`` at CALL time —
never cache the result at import unless the knob is registered
``import_time_safe`` (in which case the freeze is a documented,
deliberate perf choice, e.g. the tracing gate's zero-overhead
disabled path).
"""

import os
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = ["Knob", "REGISTRY", "get", "get_raw", "all_knobs",
           "render_markdown"]

_TRUTHY = ("1", "true", "yes")


@dataclass(frozen=True)
class Knob:
    name: str
    type: str  # "bool" | "int" | "float" | "str"
    default: object
    doc: str
    # True == reading this knob at module import time is a deliberate,
    # documented freeze (zero-overhead gates, subprocess inheritance).
    # Everything else must be read lazily, at call time.
    import_time_safe: bool = False


def _k(name, type_, default, doc, **kw) -> Knob:
    return Knob(name, type_, default, doc, **kw)


# One entry per knob, alphabetical. The doc string is what
# docs/KNOBS.md renders, so write it for an operator, not for the code.
REGISTRY: Dict[str, Knob] = {k.name: k for k in [
    _k("PERSIA_ARENA_INDEX_SLOTS", "int", 1024,
       "Initial open-addressing sign-index size per internal shard of "
       "the arena holder (rounded up to a power of two; the index "
       "grows itself past 3/4 fill). Pre-size it near 2x the expected "
       "per-shard rows to skip rehash churn during the first fill."),
    _k("PERSIA_ARENA_SLAB_ROWS", "int", 65536,
       "Arena growth quantum: rows added per slab extension of a "
       "(shard, record-class) arena in the Python holder (amortized-"
       "doubling, so large stores reallocate O(log n) times). The "
       "native store's slab size is fixed at 4096 rows/slab."),
    _k("PERSIA_AUTOPILOT_COOLDOWN_SEC", "float", 300.0,
       "Default per-policy cooldown between executed autopilot actions "
       "of the same kind. A policy may override it; raising it is the "
       "first stabilizer when the action journal shows oscillation "
       "(scale_out closely followed by scale_in)."),
    _k("PERSIA_AUTOPILOT_JOURNAL_DIR", "str", None,
       "Directory for the autopilot's durable action journal "
       "(decision/executed/outcome records, atomic JSON files — same "
       "discipline as the reshard journal). None keeps the journal "
       "in-memory only: decisions are still queryable over HTTP but do "
       "not survive the process."),
    _k("PERSIA_AUTOPILOT_MAX_ACTIONS_PER_HOUR", "int", 12,
       "Global autopilot action-rate limiter across ALL policies: "
       "further actions (and recommendations) are deferred once this "
       "many fired in the trailing hour. The blast-radius backstop "
       "when a bad signal makes every policy want to act at once."),
    _k("PERSIA_AUTOPILOT_MODE", "str", "recommend",
       "Autopilot posture: `recommend` (default) journals every "
       "decision it WOULD take without touching the fleet; `enforce` "
       "executes decisions through the operator. Graduate only after "
       "a recommend soak matches operator intent (DEPLOY.md runbook)."),
    _k("PERSIA_COORDINATOR_ADDR", "str", "127.0.0.1:23333",
       "Address of the persia-coordinator control-plane service (the "
       "NATS analogue). Service binaries take it as their argparse "
       "default; client helpers fall back to the canonical default."),
    _k("PERSIA_DATALOADER_ENTRY", "str", None,
       "Script the `persia_tpu.launcher data-loader` role runs when no "
       "script argument is given (declarative k8s manifests)."),
    _k("PERSIA_DEADLOCK_DETECTION", "bool", False,
       "Arm the stall watchdog thread: logs an error when in-flight "
       "work stops heartbeating (reference env gate)."),
    _k("PERSIA_ENABLE_MONITOR", "bool", False,
       "Embedding worker: estimate distinct ids per feature with an "
       "HLL gauge (extra per-batch hashing cost)."),
    _k("PERSIA_FAULTS", "str", None,
       "Fault-injection spec armed at import (e.g. "
       "`ps.lookup:delay:0.2:0.5`); subprocess service replicas "
       "inherit it through the environment. See faults.py.",
       import_time_safe=True),
    _k("PERSIA_FAULTS_RPC", "bool", False,
       "Expose the `__faults__` RPC control method so a live process "
       "can be re-armed remotely (chaos bench). Never on by default."),
    _k("PERSIA_FAULTS_SEED", "int", None,
       "Deterministic seed for the fault injector's RNG.",
       import_time_safe=True),
    _k("PERSIA_FLEET_HISTORY_POINTS", "int", 512,
       "Per-series point cap of the fleet monitor's in-memory history "
       "ring (oldest points drop first). Bounds memory per scraped "
       "series independently of the time window."),
    _k("PERSIA_FLEET_HISTORY_SEC", "float", 600.0,
       "Time-window retention of the fleet monitor's history ring: "
       "every scraped series keeps this many seconds of (t, value) "
       "points for /fleet/history, sustained()/trend() context, and "
       "autopilot evidence excerpts."),
    _k("PERSIA_FLEET_TARGETS", "str", "",
       "Static fleet-monitor scrape targets: comma-joined "
       "`name=host:port` pairs, merged with coordinator discovery."),
    _k("PERSIA_FORCE_JAX_PLATFORM", "str", None,
       "Serving binary: re-pin jax.config's platform (the axon plugin "
       "overrides JAX_PLATFORMS via sitecustomize)."),
    _k("PERSIA_FORCE_PYTHON_MW", "bool", False,
       "Skip the native middleware kernels and use the numpy twins."),
    _k("PERSIA_FSYNC", "bool", True,
       "Durability of storage.PersiaPath.write_bytes_atomic on local "
       "paths: fsync the tmp file before the rename and the parent "
       "directory after it, so a machine crash cannot lose a record "
       "the caller was told is durable (migration journals, snapshot "
       "manifests, inc-packet markers). `0` trades that guarantee for "
       "write latency — process crashes are still safe, host/power "
       "crashes are not."),
    _k("PERSIA_HOTNESS", "bool", False,
       "Workload telemetry: arm per-table hotness sketches "
       "(Space-Saving top-K + count-min + HLL, per internal shard) on "
       "the PS lookup path, the `hotness` RPC / `/hotness` sidecar "
       "endpoint, and the negotiated gradient-staleness meta rider on "
       "the PS wire. Off (the default) keeps the wire byte-identical "
       "and the lookup path at one pointer test of overhead."),
    _k("PERSIA_HOTNESS_CM_DEPTH", "int", 4,
       "Count-min sketch depth (hash rows) per (table, shard) hotness "
       "cell."),
    _k("PERSIA_HOTNESS_CM_WIDTH", "int", 8192,
       "Count-min sketch width (cells per row) per (table, shard) "
       "hotness cell; the frequency upper-bound error scales as "
       "~total/width."),
    _k("PERSIA_HOTNESS_TOPK", "int", 512,
       "Space-Saving summary size per (table, internal shard); a "
       "replica's merged per-table top-K holds up to "
       "num_internal_shards * this many rows."),
    _k("PERSIA_HTTP_PORT", "int", 0,
       "Default observability sidecar port for the service binaries "
       "(0 = ephemeral, -1 = disabled)."),
    _k("PERSIA_NATIVE_LIB", "str", None,
       "Explicit path to libpersia_native.so, tried before the normal "
       "candidates. The ASan parity hook points it at the "
       "`make -C native sanitize` build (native/build/asan/)."),
    _k("PERSIA_NATIVE_SIMD", "str", "auto",
       "Kernel path of the native store's narrow/widen and in-slab "
       "optimizer updates: `auto` probes the CPU (AVX2 on x86, NEON on "
       "aarch64, scalar otherwise), `avx2`/`neon`/`scalar` force a "
       "path — clamped to what the host can execute, never a crash. "
       "All paths are bit-exact; the selected one is logged at holder "
       "init and exported via /healthz (\"simd\") and the fleet "
       "gauges. Read by the C++ library at first use (set it before "
       "the process loads the .so)."),
    _k("PERSIA_METRICS_GATEWAY_ADDR", "str", None,
       "Prometheus push-gateway address for metrics.push_loop. Unset "
       "= pull-only via the /metrics sidecar."),
    _k("PERSIA_MULTIHOST_CACHE", "str", "off",
       "What a multi-process trainer (`jax.process_count() > 1`) does "
       "when the device-resident embedding cache is requested: `off` "
       "(default) negotiates down LOUDLY — the cache is disabled and "
       "the run continues on the PS-only hybrid path, because a pod "
       "job must not die on a cache knob; `refuse` keeps the historic "
       "hard error (the cache's sign->slot mapper and miss/evict host "
       "transfers are single-controller state)."),
    _k("PERSIA_NN_WORKER_ENTRY", "str", None,
       "Script the `persia_tpu.launcher nn-worker` role runs when no "
       "script argument is given."),
    _k("PERSIA_NUM_DATALOADERS", "int", 1,
       "Data-loader replica count (k8s manifests, examples' EOS "
       "accounting)."),
    _k("PERSIA_NUM_PS", "int", 1,
       "Parameter-server replica count the worker binary expects."),
    _k("PERSIA_NUM_WORKERS", "int", 1,
       "Embedding-worker replica count (k8s manifests, examples)."),
    _k("PERSIA_ONLINE_APPLY_BATCH_ROWS", "int", 8192,
       "Rows per hot-row-cache delta-apply batch of the serving "
       "online subscriber: each batch takes the cache lock once and "
       "checks the write-rate governor once. Smaller batches bound "
       "the per-apply predict stall; larger ones amortize the lock."),
    _k("PERSIA_ONLINE_APPLY_ROWS_PER_SEC", "int", 500_000,
       "Write-rate governor of the serving delta subscriber: a token "
       "bucket (1s burst) over rows upserted into the hot-row cache, "
       "so a training-tier flush burst spreads its applies instead of "
       "convoying the predict path (the --mode online bench gates "
       "serving p99 inflation at <= 3% with this armed). 0 = "
       "unthrottled."),
    _k("PERSIA_ONLINE_SCAN_SEC", "float", 2.0,
       "Scan interval of the serving delta subscriber over the "
       "incremental-update packet directory. Together with the "
       "trainer's flush cadence this bounds sign-to-servable lag; "
       "scans of an unchanged directory cost one listdir."),
    _k("PERSIA_POSTMORTEM_DIR", "str", None,
       "Where the fleet monitor / PS supervisor write breach and crash "
       "flight-recorder bundles. Unset = recorder disabled."),
    _k("PERSIA_PROFILE_DIR", "str", None,
       "Enables the step-windowed jax.profiler capture; traces land "
       "here."),
    _k("PERSIA_PROFILE_NUM_STEPS", "int", 5,
       "How many steps the profiler window captures."),
    _k("PERSIA_PROFILE_START_STEP", "int", 10,
       "First step of the profiler capture window."),
    _k("PERSIA_PS_BACKEND", "str", "auto",
       "Embedding-store backend: `auto` picks the native C++ arena "
       "store when the built library supports the configured storage "
       "policy (negotiating down to the Python arena holder LOUDLY "
       "when an older .so lacks a capability), `native` requires it, "
       "`arena` forces the Python arena holder, `python-legacy` forces "
       "the per-entry OrderedDict holder (A/B lever for bench.py "
       "--mode mem). Replaces the retired PERSIA_FORCE_PYTHON_PS."),
    _k("PERSIA_PS_CIRCUIT_BREAKER", "bool", True,
       "Per-replica circuit breaker on every PsClient RPC (fail fast "
       "while a background TCP probe watches the address). `0` "
       "disables."),
    _k("PERSIA_PS_CONCURRENT_STREAMS", "int", 8,
       "PS per-connection dispatch-pool depth (1 = the legacy "
       "strictly-serial per-connection loop)."),
    _k("PERSIA_PS_GC_TUNE", "bool", True,
       "PS replica: freeze boot state and make full GC ~100x rarer "
       "(a multi-million-entry store makes gen2 walks multi-hundred-ms "
       "stalls). `0` restores interpreter defaults."),
    _k("PERSIA_PS_LEGACY_FRAMES", "bool", False,
       "Revert PS request framing to the concatenating pack_arrays "
       "(pre-zero-copy A/B lever for the worker-cycle bench)."),
    _k("PERSIA_PS_ROW_DTYPE", "str", None,
       "Storage precision of the embedding slice of every PS row "
       "(fp32|fp16|bf16; optimizer state stays fp32). Served by every "
       "backend; an old pre-arena native .so negotiates down to the "
       "Python arena holder loudly."),
    _k("PERSIA_PS_SHARD_PARALLEL", "bool", True,
       "PS shard-parallel dispatch (per-internal-shard buckets). `0` "
       "forces single-threaded dispatch regardless of core count."),
    _k("PERSIA_PS_WIRE_CODEC", "str", "",
       "Embedding-row wire precision policy: ``fp16`` ships lookup "
       "responses as fp16 rows, ``fp16+int8`` additionally ships "
       "update gradients as int8 + per-row scales (error feedback "
       "client-side). Unset/off keeps the fp32 wire byte-identical to "
       "the legacy protocol."),
    _k("PERSIA_RESHARD_BATCH_ROWS", "int", 65536,
       "Rows per extract/install chunk while the reshard controller "
       "streams a donor's slot snapshot to its target replica. Smaller "
       "chunks bound the per-RPC copy stall a migrating replica "
       "imposes on live traffic; larger chunks finish the copy phase "
       "sooner."),
    _k("PERSIA_RESHARD_FREEZE_LEASE_SEC", "float", 30.0,
       "Donor self-healing lease on reshard state: every controller "
       "RPC (begin/extract/drain/freeze/status) renews it; when it "
       "expires — the controller died or was partitioned away — the "
       "donor auto-thaws, discarding capture state and unfreezing the "
       "moving slots, so bounced writers recover under the OLD epoch "
       "instead of facing a frozen-forever shard. Keep it well above "
       "the longest expected extract/install gap; a resumed controller "
       "fences out the dead attempt either way. 0 disables the lease "
       "(frozen state persists until reshard_finish)."),
    _k("PERSIA_RESHARD_JOURNAL_DIR", "str", None,
       "Arm the reshard controller's durable migration journal: "
       "append-only, atomically-written protocol records (plan, "
       "per-donor copy/freeze/drain, publish bracket, finalize/abort) "
       "land under this directory (storage.PersiaPath — local or "
       "hdfs://), so a controller killed mid-migration can resume() "
       "or abort the same migration after restart. Unset = in-memory "
       "only (a controller crash relies on the freeze lease for donor "
       "recovery)."),
    _k("PERSIA_RESHARD_RPC_TIMEOUT_SEC", "float", 120.0,
       "Per-RPC deadline the reshard controller stamps on every "
       "reshard_* call (negotiated __deadline__ envelope slot, armed "
       "on its clients at migration start): a wedged donor sheds the "
       "expired extract/install instead of hanging the migration "
       "unboundedly. Idle fleets never negotiate it — the "
       "no-migration wire stays byte-identical. 0 disables."),
    _k("PERSIA_PROCESS_COUNT", "int", 1,
       "Trainer-group size this process belongs to. Set by "
       "`persia_tpu.launcher nn-worker` on every spawned trainer copy "
       "(alongside PERSIA_PROCESS_INDEX); the trainer driver shards "
       "the deterministic batch stream by (index, count). 1 = the "
       "historic single-process stream."),
    _k("PERSIA_PROCESS_INDEX", "int", 0,
       "This trainer process's rank within the trainer group "
       "(0-based, < PERSIA_PROCESS_COUNT). Owns every global batch "
       "whose stream position i satisfies "
       "i % PERSIA_PROCESS_COUNT == index."),
    _k("PERSIA_RESHARD_DRAIN_SEC", "float", 5.0,
       "Double-read window after a reshard cutover: donors keep the "
       "moved rows readable (for in-flight lookups routed by the "
       "previous epoch) this long before finalize deletes them. "
       "Raise it when trainers run deep async staleness windows."),
    _k("PERSIA_RESHARD_STALE_RETRY_SEC", "float", 10.0,
       "How long a worker retries a shard group bounced with "
       "routing_stale (the reshard freeze window) while waiting for "
       "the new routing epoch to arrive before giving up. The freeze "
       "window is normally milliseconds; this bound only catches a "
       "wedged cutover."),
    _k("PERSIA_ROUTING_SLOTS_PER_REPLICA", "int", 64,
       "Routing slots per PS replica when a uniform table is born "
       "(num_slots = replicas * this). Slots are the migration unit: "
       "more slots = finer-grained hotness balancing and smaller "
       "migration chunks, at a few bytes of table per slot. The "
       "uniform table routes bit-exactly like the legacy "
       "farmhash % R whatever this value is."),
    _k("PERSIA_ROUTING_WIRE", "bool", False,
       "PsClient probes the __routing__ envelope extension at dial "
       "and stamps its routing epoch on lookup/update meta, letting a "
       "resharding PS fast-reject stale-epoch writes before the "
       "per-sign slot check. Off (default) keeps the wire "
       "byte-identical; legacy servers negotiate down."),
    _k("PERSIA_RPC_FORCE_BLOCK", "bool", False,
       "Force negotiated block compression even on loopback (tests and "
       "benches exercise the codec path without a real DCN link).",
       import_time_safe=True),
    _k("PERSIA_SKIP_CHECK_DATA", "bool", False,
       "Skip PersiaBatch input validation (shape/dtype checks) on the "
       "data-loader hot path. Read at call time — setting it after "
       "import works (the old import-time freeze was a bug)."),
    _k("PERSIA_SNAPSHOT_INTERVAL_STEPS", "int", 50,
       "Default cadence (train steps) between coordinated job "
       "snapshots taken by the supervised trainer driver "
       "(persia_tpu.service.trainer_service). The interval is the "
       "recovery budget: a trainer SIGKILL loses at most this many "
       "steps of dense+sparse progress, all of which the resume path "
       "replays deterministically from the snapshotted data cursor."),
    _k("PERSIA_SNAPSHOT_KEEP", "int", 3,
       "Retention of the job-snapshot GC (persia_tpu/snapshot.py): "
       "the newest K COMPLETE snapshots survive; older completes and "
       "any torn/manifest-less debris older than the newest complete "
       "are removed after each successful snapshot. Keep >= 2 so a "
       "torn newest snapshot always has a fallback."),
    _k("PERSIA_TEST_TPU", "bool", False,
       "Run the TPU-gated hardware-validation tests (pytest conftest "
       "arms a per-test watchdog instead of skipping them)."),
    _k("PERSIA_TIER_ADMIT", "str", "lru",
       "Device-cache admission policy for the HBM tier of the embedding "
       "ladder: `lru` (the legacy recency-only mapper) or `hotness` "
       "(frequency-gated admission — a Space-Saving sketch over the "
       "training id stream keeps one-touch cold traffic in a small "
       "probationary window so it cannot thrash the resident hot set). "
       "The default keeps the wire and the mapper behavior identical "
       "to the pre-ladder stack."),
    _k("PERSIA_TIER_SKETCH_TOPK", "int", 0,
       "Space-Saving summary size of the hotness-admitted device-cache "
       "mapper (0 = auto: 4x the cache capacity, capped at 1Mi). Only "
       "read when PERSIA_TIER_ADMIT=hotness."),
    _k("PERSIA_TIER_SPILL_BYTES", "int", 0,
       "Disk budget for the PS cold-row spill tier (0 = unbounded). "
       "When the budget overflows, whole oldest spill packets are "
       "dropped (cold-cold rows die last-tier)."),
    _k("PERSIA_TIER_SPILL_DIR", "str", None,
       "Arm the PS disk spill tier: byte/row-budget evictions write "
       "cold rows to spill packets under this directory "
       "(storage.PersiaPath — local or hdfs://) instead of dropping "
       "them, and lookups fault spilled rows back in transparently. "
       "Works on every backend (the native store drains evictions to "
       "the shared Python SpillStore)."),
    _k("PERSIA_TIER_WINDOW_FRAC", "float", 0.125,
       "Fraction of the device-cache capacity reserved as the "
       "probationary admission window under PERSIA_TIER_ADMIT=hotness "
       "(cold newcomers churn there; rows earn protected residency by "
       "out-counting the protected LRU victim)."),
    _k("PERSIA_TRACING", "bool", False,
       "Cross-tier span capture. Frozen at import ON PURPOSE: the "
       "disabled path must cost nothing, so the gate is a module "
       "constant; tests toggle via subprocess env.",
       import_time_safe=True),
    _k("PERSIA_TRAINER_PROCESSES", "int", 1,
       "Trainer (nn-worker) processes per job: `persia_tpu.launcher "
       "nn-worker` spawns this many copies of the entry script with "
       "PERSIA_PROCESS_INDEX/PERSIA_PROCESS_COUNT set, and "
       "ServiceCtx's trainer supervisor sizes its group from the same "
       "number. 1 = the historic single-process trainer."),
    _k("PERSIA_TRAINER_RENDEZVOUS_KEY", "str", "trainer/jax_coordinator",
       "Coordinator KV key the trainer group rendezvouses through: "
       "process 0 binds the jax.distributed coordination port and "
       "kv_put's `host:port` under this key; every other process "
       "wait_kv's it before jax.distributed.initialize."),
    _k("PERSIA_TRAINER_RENDEZVOUS_TIMEOUT_SEC", "float", 120.0,
       "How long a non-zero trainer process waits for process 0 to "
       "publish the jax.distributed coordinator address before giving "
       "up (coordinator KV wait_kv timeout)."),
    _k("PERSIA_VARIANT_ROUTE_FEATURE", "str", None,
       "Field-based A/B routing for the serving tier: when set, a "
       "plain predict derives its variant route key from this id "
       "feature's first sign (e.g. the user-id slot — per-user-sticky "
       "assignment with no client change). Unset keeps plain predicts "
       "on the default variant. Read once at server construction."),
    _k("PERSIA_VARIANT_SPLIT_BUCKETS", "int", 10000,
       "Resolution of the deterministic weighted variant split: route "
       "keys hash into this many buckets and variants own contiguous "
       "weight-proportional ranges. 10000 buckets = 0.01% split "
       "granularity; every serving replica computes the same "
       "assignment for the same key."),
    _k("PERSIA_WORKLOAD_ALPHA", "float", 1.05,
       "Default zipf skew of the workload-zoo scenario generators "
       "(persia_tpu/workloads): every categorical table's sign draw "
       "uses this alpha unless the scenario spec overrides it. The "
       "e2e bench fits the hotness telemetry against traffic generated "
       "at this skew."),
    _k("PERSIA_WORKLOAD_SEED", "int", 0,
       "Base seed of the workload-zoo generators. Scenario streams are "
       "deterministic per seed (identical batches), and the hidden "
       "label structure is seed-INDEPENDENT — train on one seed, "
       "evaluate on another, same task."),
    _k("PERSIA_WORKER_STREAMING", "bool", True,
       "Embedding worker streaming data plane (scatter-per-completion "
       "lookups, ship-as-aggregated updates). `0` restores the "
       "serialized gather-then-scatter plane."),
]}


def _parse(knob: Knob, raw: str):
    if knob.type == "bool":
        # default-True knobs are the `!= "0"` family, default-False
        # knobs the `== "1"/true/yes` family — bit-compatible with
        # every historical call site.
        if knob.default:
            return raw != "0"
        return raw.lower() in _TRUTHY
    if knob.type in ("int", "float"):
        # an EMPTY numeric knob means unset (shell blocks interpolate
        # unset variables as ""); the historical sites treated it that
        # way (`if os.environ.get(X)` is falsy on ""), and int("")
        # raising here would silently disarm e.g. PERSIA_FAULTS_SEED
        if raw == "":
            return knob.default
        return int(raw) if knob.type == "int" else float(raw)
    return raw


def get(name: str):
    """Typed value of knob ``name`` from the CURRENT environment,
    falling back to the registered default. Unknown names raise — the
    runtime twin of the lint pass's typo guard."""
    knob = REGISTRY.get(name)
    if knob is None:
        raise KeyError(f"unregistered PERSIA knob {name!r}; add it to "
                       "persia_tpu/knobs.py (persialint enforces this)")
    raw = os.environ.get(name)
    if raw is None:
        return knob.default
    return _parse(knob, raw)


def get_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw environment string for knob ``name`` (or ``default`` when
    unset). For call sites whose local fallback differs from the
    canonical default — argparse "was it set?" probes and the like.
    Still registry-checked, so typos fail loudly."""
    if name not in REGISTRY:
        raise KeyError(f"unregistered PERSIA knob {name!r}; add it to "
                       "persia_tpu/knobs.py (persialint enforces this)")
    return os.environ.get(name, default)


def all_knobs():
    return [REGISTRY[k] for k in sorted(REGISTRY)]


def render_markdown() -> str:
    """The full knob reference, rendered for docs/KNOBS.md. persialint
    --check-knob-docs fails when the checked-in file drifts from this."""
    lines = [
        "# PERSIA_* environment knobs",
        "",
        "Generated from `persia_tpu/knobs.py` — do not edit by hand.",
        "Regenerate with `python -m tools.persialint --render-knobs`.",
        "",
        "Boolean knobs whose default is **on** are disabled only by the",
        "literal `0`; boolean knobs whose default is **off** are enabled",
        "by `1`/`true`/`yes`. All knobs are read at call time unless",
        "marked *frozen at import*.",
        "",
        "| Knob | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for knob in all_knobs():
        default = ("*(unset)*" if knob.default is None
                   else f"`{knob.default}`")
        doc = " ".join(knob.doc.split())
        if knob.import_time_safe:
            doc += " *(frozen at import)*"
        lines.append(f"| `{knob.name}` | {knob.type} | {default} | {doc} |")
    lines.append("")
    return "\n".join(lines)
