"""Incremental update manager: train -> serve online delta sync.

Re-design of rust/persia-incremental-update-manager/src/lib.rs:

- **Train side** (lib.rs:178-312): updated signs accumulate in a dedup
  buffer; when it exceeds ``incremental_buffer_size`` the current entry
  values are dumped as a timestamped packet directory
  ``inc_<ts>_<seq>/<replica>.inc`` (PSD1 layout) with an
  ``inc_update_done`` marker.
- **Infer side** (lib.rs:314-364): a scanner thread polls the directory,
  loads packets newer than the last applied one into the store, and
  tracks the sync delay.

The packet-discovery conventions (done-marker visibility, name-sorted
order, per-replica ``.inc`` files) live in :func:`ready_packets` /
:func:`packet_files`, shared with the serving tier's online delta
subscriber (:mod:`persia_tpu.online`) — one stream, two consumers:
the infer PS hot-loads whole rows, the serving cache upserts resident
hot rows directly.
"""

import json
import os
import threading
import time
from typing import List, Optional, Set

import numpy as np

from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

DONE_MARKER = "inc_update_done"


def ready_packets(inc_dir: str, applied: Set[str]):
    """Yield ``(name, pkt_dir, marker_info)`` for every COMPLETE packet
    under ``inc_dir`` not already in ``applied``, in name order (names
    sort by dump timestamp). The one packet-discovery convention shared
    by the PS-side :class:`IncrementalUpdateLoader` and the serving-side
    delta subscriber (:mod:`persia_tpu.online`) — a packet is visible
    only once its done-marker exists (the dumper renames the whole
    directory into place, so a partially-written packet is never
    listed)."""
    if not os.path.isdir(inc_dir):
        return
    for name in sorted(os.listdir(inc_dir)):
        pkt_dir = os.path.join(inc_dir, name)
        marker = os.path.join(pkt_dir, DONE_MARKER)
        if (name in applied or not name.startswith("inc_")
                or not os.path.exists(marker)):
            continue
        with open(marker) as f:
            info = json.load(f)
        yield name, pkt_dir, info


def packet_files(pkt_dir: str):
    """The ``(source_replica, path)`` pairs of one packet's ``.inc``
    files, in replica order. The file stem IS the dumping replica's
    index (the packet-name ``_r<replica>`` suffix repeats it) — the
    routing-aware consumers key ownership filtering on it."""
    out = []
    for fn in sorted(os.listdir(pkt_dir)):
        if not fn.endswith(".inc"):
            continue
        try:
            replica = int(fn[:-len(".inc")])
        except ValueError:
            continue
        out.append((replica, os.path.join(pkt_dir, fn)))
    return out


class IncrementalUpdateDumper:
    """Train-side: attach to a holder; call ``commit(signs)`` after every
    gradient update."""

    def __init__(self, holder, inc_dir: str, buffer_size: int = 1_000_000,
                 replica_index: int = 0):
        self.holder = holder
        self.inc_dir = inc_dir
        self.buffer_size = buffer_size
        self.replica_index = replica_index
        self._buffer: Set[int] = set()
        self._lock = threading.Lock()
        self._seq = 0
        os.makedirs(inc_dir, exist_ok=True)

    def commit(self, signs: np.ndarray):
        flush: Optional[Set[int]] = None
        with self._lock:
            self._buffer.update(int(s) for s in signs)
            if len(self._buffer) >= self.buffer_size:
                flush = self._buffer
                self._buffer = set()
                seq = self._seq = self._seq + 1
        if flush:
            self._dump_packet(flush, seq)

    def flush(self):
        with self._lock:
            flush, self._buffer = self._buffer, set()
            if flush:
                seq = self._seq = self._seq + 1
        if flush:
            self._dump_packet(flush, seq)

    def _dump_packet(self, signs: Set[int], seq: int):
        import struct

        from persia_tpu.ps.optim import RowPrecision
        from persia_tpu.ps.store import _DTYPE_CODES, DUMP_MAGIC

        # packets honor the holder's storage policy: a half-precision
        # holder ships v2 records (fp16/bf16 emb bytes + f32 state) —
        # half the train->serve sync bytes; the loader's version-agnostic
        # reader widens on apply. fp32 holders keep the v1 layout.
        row_dtype = getattr(self.holder, "row_dtype", "fp32")
        rp = RowPrecision(row_dtype)
        version = 1 if rp.is_fp32 else 2

        # the replica index is part of the packet NAME, not just the
        # file inside: all replicas share one inc_dir (global config),
        # and two replicas flushing in the same second used to collide
        # on the same packet directory (rename onto a non-empty dir ->
        # the update RPC that triggered the flush failed). A restarted
        # replica restarts seq at 1, so the pid suffix keeps a fresh
        # incarnation from colliding with its predecessor's packets.
        # ``seq`` is allocated inside commit/flush's locked region:
        # concurrent update handlers (dispatch pool, shard-parallel)
        # both flushing used to race the unguarded `self._seq += 1`
        # here and could mint the SAME packet name within one second of
        # one pid — the within-replica twin of the cross-replica
        # collision above, surfaced by persialint's lock pass.
        name = (f"inc_{time.strftime('%Y%m%d%H%M%S')}_{seq:06d}"
                f"_r{self.replica_index}_p{os.getpid()}")
        pkt_dir = os.path.join(self.inc_dir, name)
        tmp_dir = pkt_dir + ".tmp"
        os.makedirs(tmp_dir, exist_ok=True)
        path = os.path.join(tmp_dir, f"{self.replica_index}.inc")
        records = []
        count = 0
        for sign in signs:
            entry = self.holder.get_entry(sign)
            if entry is None:
                continue
            dim, vec = entry
            vec = np.ascontiguousarray(vec, np.float32)
            if version == 1:
                records.append(struct.pack("<QII", sign, dim, len(vec)))
                records.append(vec.tobytes())
            else:
                records.append(struct.pack(
                    "<QIBI", sign, dim, _DTYPE_CODES[rp.name],
                    len(vec) - dim))
                records.append(rp.pack(vec, dim).tobytes())
            count += 1
        with open(path, "wb") as f:
            f.write(DUMP_MAGIC)
            f.write(struct.pack("<IQ", version, count))
            for r in records:
                f.write(r)
        with open(os.path.join(tmp_dir, DONE_MARKER), "w") as f:
            json.dump({"count": count, "time": time.time()}, f)
        os.rename(tmp_dir, pkt_dir)
        _logger.info("incremental packet %s: %d entries", name, count)


class IncrementalUpdateLoader:
    """Infer-side: scan ``inc_dir`` and hot-load new packets.

    ``replica_index`` restricts the load to that replica's ``.inc``
    files — the crash-recovery boot replay uses this so a restored PS
    shard reconstructs exactly ITS rows (all replicas share one
    inc_dir); the default (None) keeps the infer-side behavior of
    loading every replica's entries.

    ``routing`` (a :class:`~persia_tpu.routing.RoutingTable`) replaces
    the filename filter with OWNERSHIP filtering: every replica's
    packets are read, and only entries the table routes to
    ``replica_index`` apply. This is the correct replay across a
    shard-count change — a replica recovering after a 2→3 reshard must
    reconstruct the rows it owns NOW, which live scattered across the
    old fleet's packet files, and must never apply rows it no longer
    owns (they would shadow the live owner's state at the next
    checkpoint merge)."""

    def __init__(self, holder, inc_dir: str, scan_interval_sec: float = 10.0,
                 replica_index: Optional[int] = None, routing=None):
        self.holder = holder
        self.inc_dir = inc_dir
        self.scan_interval_sec = scan_interval_sec
        self.replica_index = replica_index
        self.routing = routing
        if routing is not None and replica_index is None:
            raise ValueError(
                "routing-filtered replay needs the replica_index the "
                "table should route to")
        self._applied: Set[str] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_delay_sec: float = 0.0
        self.packets_applied: int = 0
        # Serving-freshness observables: last_delay_sec existed but was
        # never exported — it now rides the registry as a gauge, and the
        # per-packet sign-to-servable age (apply time minus the packet's
        # dump timestamp) lands in an age-shaped histogram, so "how
        # stale is serving" is a distribution, not one scan-time point.
        from persia_tpu.metrics import (AGE_BUCKETS, COUNT_BUCKETS,
                                        default_registry)

        reg = default_registry()
        self._g_delay = reg.gauge(
            "inc_update_last_delay_sec",
            help_text="age of the newest applied incremental packet at "
                      "its apply time (train->serve sync delay)")
        # The STALL signal: last_delay_sec freezes at its last healthy
        # value when packets stop arriving (it is only written on
        # apply), so detecting a dead sync loop needs a clock that
        # keeps running — seconds since the last apply (or since this
        # loader armed, so a dumper dead from boot also trips it),
        # refreshed on EVERY scan whether or not anything applied.
        self._t_last_apply = time.monotonic()
        self._g_since_apply = reg.gauge(
            "inc_update_sec_since_last_apply",
            help_text="seconds since this loader last applied a packet "
                      "(or since it started) — keeps rising while the "
                      "train->serve sync loop is stalled")
        self._h_freshness = reg.histogram(
            "inc_update_freshness_lag_sec",
            help_text="per-packet sign-to-servable age: packet dump "
                      "timestamp to its apply completing",
            buckets=AGE_BUCKETS)
        self._h_entries = reg.histogram(
            "inc_update_packet_entries",
            help_text="entries loaded per applied incremental packet",
            buckets=COUNT_BUCKETS)
        self._c_packets = reg.counter(
            "inc_update_packets_applied_total",
            help_text="incremental packets applied by this loader")
        self._c_entries = reg.counter(
            "inc_update_entries_applied_total",
            help_text="entries hot-loaded from incremental packets")

    def scan_once(self) -> int:
        """Apply any unapplied complete packets; returns entries loaded."""
        from persia_tpu.checkpoint import iter_psd_entries

        loaded = 0
        for name, pkt_dir, info in ready_packets(self.inc_dir,
                                                 self._applied):
            pkt_loaded = 0
            for src, path in packet_files(pkt_dir):
                if (self.routing is None and self.replica_index is not None
                        and src != self.replica_index):
                    continue
                if self.routing is not None:
                    # ownership replay: read EVERY replica's file,
                    # batch the entries, and keep only the rows the
                    # NEW table routes here — the filename filter
                    # encodes the old fleet's shard count and is
                    # wrong the moment it changes
                    batch = list(iter_psd_entries(path))
                    if not batch:
                        continue
                    owners = self.routing.replica_of(np.array(
                        [b[0] for b in batch], dtype=np.uint64))
                    for (sign, dim, vec), owner in zip(batch, owners):
                        if int(owner) != self.replica_index:
                            continue
                        self.holder.set_entry(sign, dim, vec)
                        pkt_loaded += 1
                    continue
                for sign, dim, vec in iter_psd_entries(path):
                    self.holder.set_entry(sign, dim, vec)
                    pkt_loaded += 1
            loaded += pkt_loaded
            self._applied.add(name)
            # freshness lag measured when the packet's rows are
            # SERVABLE (apply done), against its dump timestamp —
            # the per-packet distribution; last_delay_sec stays the
            # scan-time scalar callers already read
            self.last_delay_sec = max(0.0, time.time() - info["time"])
            self.packets_applied += 1
            self._h_freshness.observe(self.last_delay_sec)
            self._h_entries.observe(pkt_loaded)
            self._c_packets.inc()
            self._c_entries.inc(pkt_loaded)
            self._g_delay.set(self.last_delay_sec)
            self._t_last_apply = time.monotonic()
        self._g_since_apply.set(self.sec_since_last_apply)
        return loaded

    @property
    def sec_since_last_apply(self) -> float:
        return max(0.0, time.monotonic() - self._t_last_apply)

    def start(self):
        def run():
            while not self._stop.wait(self.scan_interval_sec):
                try:
                    self.scan_once()
                except Exception as e:  # keep scanning on bad packets
                    _logger.error("incremental scan failed: %s", e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="inc-update-scanner")
        self._thread.start()

    def stop(self):
        self._stop.set()
