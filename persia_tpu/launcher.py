"""persia-tpu-launcher: role entrypoint CLI (reference: persia/launcher.py).

Subcommands launch one process of each role with env-var fallbacks so k8s
manifests stay declarative:

    python -m persia_tpu.launcher coordinator --port 23333
    python -m persia_tpu.launcher data-loader [script.py]   (PERSIA_DATALOADER_ENTRY)
    python -m persia_tpu.launcher nn-worker [script.py]     (PERSIA_NN_WORKER_ENTRY)
    python -m persia_tpu.launcher embedding-worker --embedding-config ...
    python -m persia_tpu.launcher embedding-parameter-server ...

Unlike the reference there is no torch.distributed.launch wrapping for
nn-workers: multi-chip scale-out is an in-process jax Mesh (single
controller per host), so one nn-worker process per TPU host suffices.
"""

import argparse
import os
import sys

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import run_command

_logger = get_default_logger("persia_tpu.launcher")


def _run_script(entry_env: str, argv):
    script = argv[0] if argv else knobs.get(entry_env)
    if not script:
        raise SystemExit(
            f"no script given and {entry_env} not set"
        )
    cmd = [sys.executable, script, *argv[1:]]
    _logger.info("launching %s", " ".join(cmd))
    proc = run_command(cmd)
    raise SystemExit(proc.wait())


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(prog="persia-tpu-launcher")
    p.add_argument("role", choices=[
        "coordinator", "data-loader", "nn-worker", "embedding-worker",
        "embedding-parameter-server",
    ])
    args, rest = p.parse_known_args(argv)

    if args.role == "coordinator":
        from persia_tpu.service import coordinator

        sys.argv = ["coordinator", *rest]
        coordinator.main()
    elif args.role == "embedding-worker":
        from persia_tpu.service import worker_service

        sys.argv = ["worker_service", *rest]
        worker_service.main()
    elif args.role == "embedding-parameter-server":
        from persia_tpu.service import ps_service

        sys.argv = ["ps_service", *rest]
        ps_service.main()
    elif args.role == "data-loader":
        _run_script("PERSIA_DATALOADER_ENTRY", rest)
    elif args.role == "nn-worker":
        _run_script("PERSIA_NN_WORKER_ENTRY", rest)


if __name__ == "__main__":
    main()
