"""persia-tpu-launcher: role entrypoint CLI (reference: persia/launcher.py).

Subcommands launch one process of each role with env-var fallbacks so k8s
manifests stay declarative:

    python -m persia_tpu.launcher coordinator --port 23333
    python -m persia_tpu.launcher data-loader [script.py]   (PERSIA_DATALOADER_ENTRY)
    python -m persia_tpu.launcher nn-worker [script.py]     (PERSIA_NN_WORKER_ENTRY)
    python -m persia_tpu.launcher embedding-worker --embedding-config ...
    python -m persia_tpu.launcher embedding-parameter-server ...

Multi-chip scale-out within a host is an in-process jax Mesh (single
controller per host), so one nn-worker process per TPU host suffices;
POD scale-out sets ``PERSIA_TRAINER_PROCESSES`` and the nn-worker role
spawns that many trainer copies (the reference's
``torch.distributed.launch`` analogue), each carrying
``PERSIA_PROCESS_INDEX``/``PERSIA_PROCESS_COUNT`` for stream sharding
and jax.distributed mesh rendezvous.
"""

import argparse
import os
import sys
import time

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger
from persia_tpu.utils import run_command

_logger = get_default_logger("persia_tpu.launcher")


def _run_script(entry_env: str, argv):
    script = argv[0] if argv else knobs.get(entry_env)
    if not script:
        raise SystemExit(
            f"no script given and {entry_env} not set"
        )
    cmd = [sys.executable, script, *argv[1:]]
    _logger.info("launching %s", " ".join(cmd))
    proc = run_command(cmd)
    raise SystemExit(proc.wait())


def _run_trainer_group(argv):
    """nn-worker role: PERSIA_TRAINER_PROCESSES copies of the entry
    script, each with PERSIA_PROCESS_INDEX/PERSIA_PROCESS_COUNT set so
    the trainer drivers shard the deterministic batch stream and
    rendezvous their jax.distributed mesh (the reference's
    ``torch.distributed.launch`` role, done pod-style: one process per
    trainer host, co-scheduled with the PS/worker tiers). Exits with
    the first nonzero child rc — one dead group member means the
    collective is wedged, so the whole group should be restarted by
    whatever supervises the launcher."""
    n = knobs.get("PERSIA_TRAINER_PROCESSES")
    if n <= 1:
        _run_script("PERSIA_NN_WORKER_ENTRY", argv)
        return
    script = argv[0] if argv else knobs.get("PERSIA_NN_WORKER_ENTRY")
    if not script:
        raise SystemExit("no script given and PERSIA_NN_WORKER_ENTRY not set")
    cmd = [sys.executable, script, *argv[1:]]
    procs = []
    for i in range(n):
        _logger.info("launching trainer %d/%d: %s", i, n, " ".join(cmd))
        procs.append(run_command(cmd, env={
            "PERSIA_PROCESS_INDEX": i, "PERSIA_PROCESS_COUNT": n}))
    # poll, don't wait sequentially: a crashed member wedges the rest
    # on the next collective, and a wait() on a wedged process would
    # mask the crash forever
    rc = None
    while rc is None:
        rcs = [proc.poll() for proc in procs]
        bad = [(i, r) for i, r in enumerate(rcs) if r not in (None, 0)]
        if bad:
            i, rc = bad[0]
            _logger.error("trainer %d exited rc=%d; terminating group",
                          i, rc)
        elif all(r == 0 for r in rcs):
            rc = 0
        else:
            time.sleep(0.2)
    if rc != 0:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
    raise SystemExit(rc)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(prog="persia-tpu-launcher")
    p.add_argument("role", choices=[
        "coordinator", "data-loader", "nn-worker", "embedding-worker",
        "embedding-parameter-server",
    ])
    args, rest = p.parse_known_args(argv)

    if args.role == "coordinator":
        from persia_tpu.service import coordinator

        sys.argv = ["coordinator", *rest]
        coordinator.main()
    elif args.role == "embedding-worker":
        from persia_tpu.service import worker_service

        sys.argv = ["worker_service", *rest]
        worker_service.main()
    elif args.role == "embedding-parameter-server":
        from persia_tpu.service import ps_service

        sys.argv = ["ps_service", *rest]
        ps_service.main()
    elif args.role == "data-loader":
        _run_script("PERSIA_DATALOADER_ENTRY", rest)
    elif args.role == "nn-worker":
        _run_trainer_group(rest)


if __name__ == "__main__":
    main()
