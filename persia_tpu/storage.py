"""Storage abstraction: local disk + HDFS (reference: persia-storage).

The reference's ``PersiaPath`` dispatches between std::fs and shelling
out to ``hdfs dfs`` / ``hadoop fs`` (persia-storage/src/lib.rs:177-391).
Checkpoint and incremental-update paths accept ``hdfs://`` URIs through
this module; everything else is plain local IO.
"""

import os
import shutil
import subprocess
from typing import List, Optional


def _hdfs_bin() -> List[str]:
    for candidate in (["hdfs", "dfs"], ["hadoop", "fs"]):
        if shutil.which(candidate[0]):
            return candidate
    raise RuntimeError("no hdfs/hadoop binary on PATH for hdfs:// paths")


class PersiaPath:
    """One file path on disk or HDFS."""

    def __init__(self, path: str):
        self.path = path
        self.is_hdfs = path.startswith("hdfs://")

    def _run(self, *args) -> subprocess.CompletedProcess:
        return subprocess.run(
            [*_hdfs_bin(), *args], check=True, capture_output=True
        )

    def read_bytes(self) -> bytes:
        if self.is_hdfs:
            return self._run("-cat", self.path).stdout
        with open(self.path, "rb") as f:
            return f.read()

    def read_range(self, offset: int, length: int) -> bytes:
        """``length`` bytes starting at ``offset`` — the spill tier's
        single-row fault-in. Local paths seek; HDFS has no cheap random
        read through the CLI, so it degrades to a full read + slice
        (spill packets are bounded, see ps/spill.py). Short reads raise
        (a truncated packet must fail loudly, not hand back garbage)."""
        if self.is_hdfs:
            data = self.read_bytes()[offset:offset + length]
        else:
            with open(self.path, "rb") as f:
                f.seek(offset)
                data = f.read(length)
        if len(data) != length:
            raise IOError(
                f"{self.path}: short read ({len(data)} of {length} bytes "
                f"at offset {offset})")
        return data

    def write_bytes(self, data: bytes):
        if self.is_hdfs:
            proc = subprocess.Popen(
                [*_hdfs_bin(), "-put", "-f", "-", self.path],
                stdin=subprocess.PIPE,
            )
            proc.communicate(data)
            if proc.returncode != 0:
                raise IOError(f"hdfs put failed for {self.path}")
            return
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(self.path, "wb") as f:
            f.write(data)

    def write_bytes_atomic(self, data: bytes):
        """All-or-nothing AND durable write: the destination either
        keeps its old content (or stays absent) or holds ``data`` in
        full — never a torn prefix. Local paths write ``<name>.tmp``
        then rename (POSIX atomic within a filesystem), fsyncing the
        tmp file BEFORE the rename and the parent directory AFTER it
        (PERSIA_FSYNC, default on) — without both, a host crash after
        ``os.replace`` returns can still lose the record the caller
        was told is durable (journal entries, snapshot manifests).
        HDFS ``-put -f -`` already replaces whole files, so plain
        write_bytes is the same guarantee."""
        if self.is_hdfs:
            self.write_bytes(data)
            return
        from persia_tpu import knobs
        fsync = knobs.get("PERSIA_FSYNC")
        tmp = PersiaPath(self.path + ".tmp")
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(tmp.path, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp.path, self.path)
        if fsync and parent:
            # The rename itself lives in the directory entry; sync it
            # too or the file can revert to the old name post-crash.
            dfd = os.open(parent, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)

    def exists(self) -> bool:
        if self.is_hdfs:
            try:
                self._run("-test", "-e", self.path)
                return True
            except subprocess.CalledProcessError:
                return False
        return os.path.exists(self.path)

    def makedirs(self):
        if self.is_hdfs:
            self._run("-mkdir", "-p", self.path)
        else:
            os.makedirs(self.path, exist_ok=True)

    def listdir(self) -> List[str]:
        if self.is_hdfs:
            out = self._run("-ls", self.path).stdout.decode()
            return [
                line.rsplit(" ", 1)[-1]
                for line in out.splitlines()
                if line.startswith(("-", "d"))
            ]
        return [os.path.join(self.path, n) for n in os.listdir(self.path)]

    def remove(self):
        if self.is_hdfs:
            self._run("-rm", "-r", "-f", self.path)
        elif os.path.isdir(self.path):
            shutil.rmtree(self.path)
        elif os.path.exists(self.path):
            os.remove(self.path)
