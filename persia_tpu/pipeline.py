"""Async forward/backward pipeline engines.

Re-design of the reference's pipelined nn-worker runtime
(rust/persia-core/src/forward.rs + backward.rs) on Python threads (the
lookup path releases the GIL inside the C++ store and inside device
transfers, so threads overlap for the operations that matter):

- **ForwardEngine** (forward.rs:470-780): a feeder pulls ``PersiaBatch``es
  from the dataset; N lookup workers ingest them into the embedding
  worker and perform the lookup, bounded by the **embedding-staleness
  semaphore** (forward.rs:509-511, :686-700); results flow through an
  optional **reorder buffer** so iteration order is deterministic under
  ``reproducible=True`` (PerisaDataOrderManager, forward.rs:396-468).
- **BackwardEngine** (backward.rs:233-354): gradient updates are queued
  and shipped to the embedding worker from background threads; the
  staleness permit is released only after the update lands, giving the
  same bounded-staleness semantics as the reference.

``TrainCtx.train_step`` accepts the engine's :class:`LookedUpBatch` and
routes its gradients through the batch's backward engine instead of
updating synchronously.
"""

import heapq
import itertools
import queue
import threading
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from persia_tpu import tracing
from persia_tpu.data.batch import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.tracing import (
    StageTimer,
    heartbeat,
    start_deadlock_detection,
    work_finished,
    work_started,
)

_logger = get_default_logger(__name__)

_SENTINEL = object()


def _retry_with_recovery(worker, fn, what: str, max_recoveries: int = 4,
                         stop: Optional[threading.Event] = None):
    """Run ``fn`` surviving transient server failures: on RPC/connection
    errors, wait for the service tier to recover (the reference's
    forward workers block on wait_for_serving, forward.rs:708-761) and
    retry, up to ``max_recoveries`` times. Shared by the forward lookup
    and backward update paths."""
    import time

    from persia_tpu.rpc import RpcError

    attempts = 0
    while True:
        try:
            return fn()
        except (RpcError, ConnectionError, OSError) as e:
            attempts += 1
            if attempts > max_recoveries or (
                stop is not None and stop.is_set()
            ):
                raise
            _logger.warning("%s failed (%s); waiting for serving, "
                            "retry %d/%d", what, e, attempts, max_recoveries)
            wait = getattr(worker, "wait_for_serving", None)
            if wait is not None:
                wait(timeout=120.0)
            else:
                time.sleep(min(0.5 * attempts, 2.0))


@dataclass
class LookedUpBatch:
    """A batch whose embeddings have been fetched — ready for the jitted
    dense step (reference: PersiaTrainingBatch, forward.rs:101-117).

    ``staged`` carries the device-resident inputs when the engine's
    prefetch worker already ran the host->device staging (the
    postprocess_worker -> GPU move of forward.rs:572-638). ``trace`` is
    the batch's trace context ``(trace_id, span_id)`` opened by the
    prefetch worker's lookup span, so the trainer's step span and the
    async backward update join the SAME trace the worker/PS spans are
    already on."""

    batch: PersiaBatch
    lookup: Dict[str, Any]
    ref_id: Optional[int]
    engine: Optional["ForwardEngine"] = None
    staged: Optional[tuple] = None
    trace: Optional[Tuple[int, int]] = None

    @property
    def requires_grad(self) -> bool:
        return self.batch.requires_grad


@dataclass
class _PackedGrads:
    """A still-on-device packed gradient array awaiting d2h + unpack.

    ``slot_dims`` set means the batch-major (batch, sum dims) DDP wire
    layout; otherwise the flat per-slot concatenation of ``shapes``."""

    flat: Any  # device array (one wire-dtype blob)
    shapes: Sequence[Tuple[int, ...]]
    names: Sequence[str]
    slot_dims: Optional[Sequence[int]] = None


class _GaugedSemaphore:
    """Semaphore that mirrors permits-in-use into a registry gauge (the
    trainer-side staleness observable: pegged at the bound == the PS
    tier is the bottleneck; near zero == the chip is)."""

    def __init__(self, value: int, gauge):
        self._sem = threading.Semaphore(value)
        self._gauge = gauge

    def acquire(self, *a, **kw):
        got = self._sem.acquire(*a, **kw)
        if got:
            self._gauge.add(1)
        return got

    def release(self):
        self._gauge.dec(1)
        self._sem.release()

    @property
    def _value(self):
        """Available-permit count, mirroring threading.Semaphore's
        internal (the permit-leak tests assert on it)."""
        return self._sem._value


def flush_backward_engines(worker, timeout: Optional[float] = None):
    """Flush every BackwardEngine feeding ``worker`` (quiesce in-flight
    async gradient updates — required before a checkpoint dump so the
    sparse snapshot is consistent)."""
    for engine in list(getattr(worker, "_backward_engines", ())):
        engine.flush(timeout=timeout)


class BackwardEngine:
    """Async gradient return path (reference backward.rs:233-354).

    Each backward worker thread's ``worker.update_gradients`` call runs
    the streaming data plane underneath (PR 2): per-(shard,dim) gradient
    groups ship as soon as their features aggregate, over tagged
    multiplexed connections when the PS tier supports them, and the
    aggregate/ship split is exported per worker through the metrics
    registry (``update_aggregate_time_cost_sec`` /
    ``update_ship_time_cost_sec``) next to this engine's
    ``backward_client_time_cost_sec``."""

    def __init__(self, worker, num_workers: int = 2,
                 staleness_sem: Optional[threading.Semaphore] = None,
                 loss_scale: float = 1.0, queue_size: int = 16):
        self.worker = worker
        self.staleness_sem = staleness_sem
        self.loss_scale = loss_scale
        # Bounded: packed submissions hold still-on-device gradient blobs,
        # so an unbounded queue would pin accelerator memory without limit
        # whenever PS updates lag the training step (submit() blocking is
        # the backpressure; the staleness semaphore usually binds first).
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._pending = 0
        self._pending_cv = threading.Condition()
        self._errors: List[BaseException] = []
        self._timer_hist = StageTimer("backward_client_time_cost_sec").hist
        from persia_tpu.metrics import STEP_BUCKETS, default_registry

        # pending-update depth (queued + executing): the backward lag
        # observable next to the staleness gauge
        self._g_pending = default_registry().gauge(
            "pipeline_backward_pending_updates")
        # gradient staleness in STEPS, trainer-side: how many batches
        # were submitted after this one before its update applied (the
        # staleness semaphore bounds it; this histogram shows where
        # inside the bound the pipeline actually runs). Step-shaped
        # buckets — the default sub-second latency boundaries would
        # put every observation in one bucket.
        self._h_staleness = default_registry().histogram(
            "pipeline_gradient_staleness_steps",
            help_text="training steps submitted between a batch's "
                      "gradient submit and its PS apply",
            buckets=STEP_BUCKETS)
        self._submit_seq = 0  # guarded by _pending_cv
        # updates whose ship exhausted every transport retry: bounded-
        # staleness async SGD tolerates a dropped sparse update, so a
        # PERMANENT ship failure releases its permit and counts here
        # instead of poisoning the engine (which used to wedge the
        # trainer at the staleness bound — every later batch's permit
        # was acquired by the feeder but its grads never enqueued).
        # Programming errors (missing grads, bad ref) still propagate.
        self.lost_updates = 0
        self._c_lost = default_registry().counter(
            "pipeline_lost_updates_total")
        # register on the worker so checkpoint dumps can quiesce us
        engines = getattr(worker, "_backward_engines", None)
        if engines is None:
            engines = worker._backward_engines = weakref.WeakSet()
        engines.add(self)
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"backward-worker-{i}")
            for i in range(num_workers)
        ]
        for t in self._threads:
            t.start()

    def submit(self, ref_id: int, grads: Dict[str, Any]):
        if self._errors:
            # this batch's grads will never enqueue, so the permit its
            # lookup acquired must not stay captive (the round-4 leak:
            # after `staleness` poisoned submits the feeder blocked in
            # acquire forever — trainer deadlocked at the bound)
            if self.staleness_sem is not None:
                self.staleness_sem.release()
            raise self._errors[0]
        with self._pending_cv:
            self._pending += 1
            self._submit_seq += 1
            seq = self._submit_seq
        self._g_pending.add(1)
        work_started()
        # carry the submitting thread's trace context (the trainer's
        # step span) into the backward worker thread, and the submit
        # sequence number the staleness histogram diffs at apply time
        self._q.put((ref_id, grads, tracing.current_context(), seq))

    def submit_packed(self, ref_id: int, flat_grads,
                      shapes: Sequence[Tuple[int, ...]],
                      names: Sequence[str],
                      slot_dims: Optional[Sequence[int]] = None):
        """Queue a packed gradient array WITHOUT forcing the device->host
        transfer: the fetch + unpack happen in a backward worker thread
        (the reference does its d2h in backward_to_cpu_worker,
        backward.rs:233-302), keeping the slow link off the training
        thread."""
        self.submit(ref_id, _PackedGrads(flat_grads, shapes, names,
                                         slot_dims))

    def _update_with_recovery(self, ref_id, grads):
        """Ship one gradient batch, surviving server failures like the
        forward path. The worker restores its post-forward entry on a
        failed update, so the retry still finds its batch."""
        return _retry_with_recovery(
            self.worker,
            lambda: self.worker.update_gradients(
                ref_id, grads, loss_scale=self.loss_scale),
            "gradient update",
        )

    def _run(self):
        import numpy as np

        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            ref_id, grads, tctx, seq = item
            try:
                with self._timer_hist.timer(), \
                        tracing.span("pipeline/backward_update", ctx=tctx,
                                     ref_id=ref_id):
                    if isinstance(grads, _PackedGrads):
                        from persia_tpu.parallel.train import (
                            unpack_embedding_grads,
                            unpack_embedding_grads_batch_major,
                        )

                        if grads.slot_dims is not None:
                            per_slot = unpack_embedding_grads_batch_major(
                                np.asarray(grads.flat), grads.slot_dims)
                        else:
                            per_slot = unpack_embedding_grads(
                                np.asarray(grads.flat), grads.shapes)
                        grads = dict(zip(grads.names, per_slot))
                    self._update_with_recovery(ref_id, grads)
                with self._pending_cv:
                    now_seq = self._submit_seq
                self._h_staleness.observe(now_seq - seq)
                heartbeat()
            except BaseException as e:
                from persia_tpu.rpc import RpcDeadlineExceeded

                # transport loss and shed deadlines only — nested-hop
                # transport failures arrive typed as RpcConnectionLost/
                # RpcTimeout (ConnectionError/OSError subclasses) via
                # the err-envelope mapping. A PLAIN RpcError is a real
                # application failure (bad gradient shape, handler bug)
                # and must propagate: silently counting every update of
                # a buggy job as "lost" would train nothing and say so
                # nowhere.
                if isinstance(e, (RpcDeadlineExceeded, ConnectionError,
                                  OSError)):
                    # transport-class failure that survived the full
                    # recovery ladder: the service tier is (still) down.
                    # Drop THIS update — count it, release its permit
                    # (finally below) — rather than wedging the whole
                    # engine; async sparse SGD's staleness bound already
                    # prices in a bounded number of lost updates.
                    with self._pending_cv:
                        self.lost_updates += 1
                    self._c_lost.inc()
                    _logger.error(
                        "backward update permanently failed (%s); "
                        "counted as lost_update #%d, permit released",
                        e, self.lost_updates)
                else:  # programming error: propagate to the trainer
                    _logger.error("backward update failed: %s", e)
                    self._errors.append(e)
            finally:
                work_finished()
                self._g_pending.dec(1)
                if self.staleness_sem is not None:
                    self.staleness_sem.release()
                with self._pending_cv:
                    self._pending -= 1
                    self._pending_cv.notify_all()

    def flush(self, timeout: Optional[float] = None):
        """Block until every queued update has been applied."""
        with self._pending_cv:
            ok = self._pending_cv.wait_for(
                lambda: self._pending == 0, timeout=timeout
            )
        if not ok:
            raise TimeoutError("backward engine flush timed out")
        if self._errors:
            raise self._errors[0]

    def shutdown(self):
        for _ in self._threads:
            self._q.put(_SENTINEL)


class ForwardEngine:
    """Prefetching lookup pipeline (reference forward.rs:470-780)."""

    def __init__(
        self,
        ctx,
        num_workers: int = 8,
        buffer_size: int = 10,
        reproducible: bool = False,
        embedding_staleness: Optional[int] = None,
    ):
        self.ctx = ctx
        self.worker = ctx.worker
        self.num_workers = num_workers
        self.buffer_size = buffer_size
        self.reproducible = reproducible
        from persia_tpu.metrics import default_registry

        reg = default_registry()
        self.staleness_sem = (
            _GaugedSemaphore(
                embedding_staleness,
                reg.gauge("pipeline_staleness_permits_in_use"))
            if embedding_staleness is not None else None
        )
        self._g_in_q = reg.gauge("pipeline_lookup_queue_depth")
        self._g_out_q = reg.gauge("pipeline_ready_queue_depth")
        self.backward = BackwardEngine(
            self.worker, staleness_sem=self.staleness_sem
        )
        self._forward_hist = StageTimer("forward_client_time_cost_sec").hist
        start_deadlock_detection()

    def _lookup_with_recovery(self, batch,
                              stop: Optional[threading.Event] = None):
        """One batch's lookup, surviving server failures. The worker
        restores its forward-buffer entry on a failed lookup, so a retry
        by ref_id still finds its batch; a put_batch that already
        succeeded is never re-sent (no orphaned duplicate entries —
        ``state`` carries the ref across attempts)."""
        rref = getattr(batch, "remote_ref", None)
        state = {"ref_id": None}

        def attempt():
            if rref is not None:
                # ID features already live in a worker's forward buffer
                # (sent by a remote data-loader)
                lookup = self.worker.lookup(rref,
                                            training=batch.requires_grad)
                return (rref if batch.requires_grad else None), lookup
            if batch.requires_grad:
                if state["ref_id"] is None:
                    state["ref_id"] = self.worker.put_batch(
                        batch.id_type_features)
                return state["ref_id"], self.worker.lookup(
                    state["ref_id"], training=True)
            return None, self.worker.lookup_direct(
                batch.id_type_features, training=False)

        return _retry_with_recovery(self.worker, attempt, "lookup",
                                    stop=stop)

    def run(self, batches: Iterator[PersiaBatch],
            timeout_ms: int = 600_000) -> Iterator[LookedUpBatch]:
        timeout = timeout_ms / 1000.0
        in_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        out_q: "queue.Queue" = queue.Queue(maxsize=self.buffer_size)
        errors: List[BaseException] = []
        stop = threading.Event()
        n_workers = 1 if self.reproducible else self.num_workers
        seq_counter = itertools.count()

        def feeder():
            try:
                for batch in batches:
                    if stop.is_set():
                        break
                    # Acquire the staleness permit HERE, in sequence order.
                    # Acquiring inside the racing lookup workers can
                    # deadlock with the output reorder buffer: permits all
                    # held by out-of-order batches while the next-needed
                    # sequence waits for a permit.
                    if batch.requires_grad and self.staleness_sem is not None:
                        self.staleness_sem.acquire()
                    in_q.put((next(seq_counter), batch))
                    self._g_in_q.add(1)
            except BaseException as e:
                errors.append(e)
            finally:
                for _ in range(n_workers):
                    in_q.put(_SENTINEL)

        def lookup_worker():
            while True:
                item = in_q.get()
                if item is _SENTINEL:
                    out_q.put(_SENTINEL)
                    return
                self._g_in_q.dec(1)
                seq, batch = item
                if stop.is_set():
                    # another worker hit a fatal error: drain, don't process
                    if batch.requires_grad and self.staleness_sem is not None:
                        self.staleness_sem.release()
                    continue
                work_started()
                try:
                    # one ROOT span per batch: the trace every
                    # downstream tier (worker stages, PS handlers, the
                    # trainer step, the async backward update) joins.
                    # The histogram timer stays on the LOOKUP alone —
                    # forward_client_time_cost_sec predates this span
                    # and dashboards compare it against the PR-2
                    # baselines, so staging must not leak into it.
                    with tracing.span("pipeline/lookup", root=True,
                                      seq=seq) as sp:
                        with self._forward_hist.timer():
                            ref_id, lookup = self._lookup_with_recovery(
                                batch, stop=stop)
                        staged = None
                        stage = getattr(self.ctx, "stage_batch", None)
                        if stage is not None and batch.requires_grad:
                            # host->device staging off the training
                            # thread; device_put is async so the upload
                            # overlaps the in-flight compute
                            staged = stage(batch, lookup)
                    heartbeat()
                    out_q.put((seq, LookedUpBatch(batch, lookup, ref_id,
                                                  self, staged,
                                                  trace=sp.ctx)))
                    self._g_out_q.add(1)
                except BaseException as e:
                    # this batch will never train: its permit must not
                    # stay captive, and the feeder must stop acquiring
                    if batch.requires_grad and self.staleness_sem is not None:
                        self.staleness_sem.release()
                    stop.set()
                    errors.append(e)
                    out_q.put(_SENTINEL)
                    return
                finally:
                    work_finished()

        feeder_thread = threading.Thread(target=feeder, daemon=True,
                                         name="forward-feeder")
        threads = [feeder_thread]
        threads += [
            threading.Thread(target=lookup_worker, daemon=True,
                             name=f"forward-worker-{i}")
            for i in range(n_workers)
        ]
        for t in threads:
            t.start()

        heap: list = []
        finished_workers = 0
        if self.reproducible:
            # single ordered worker: results arrive in sequence already
            while finished_workers < n_workers:
                item = out_q.get(timeout=timeout)
                if item is _SENTINEL:
                    finished_workers += 1
                    continue
                self._g_out_q.dec(1)
                yield item[1]
        else:
            # reorder by seq so iteration order is stable even with
            # concurrent workers (cheap; determinism of *updates* still
            # requires staleness=1)
            next_seq = 0
            while finished_workers < n_workers:
                item = out_q.get(timeout=timeout)
                if item is _SENTINEL:
                    finished_workers += 1
                    continue
                self._g_out_q.dec(1)
                heapq.heappush(heap, item)
                while heap and heap[0][0] == next_seq:
                    _, lb = heapq.heappop(heap)
                    next_seq += 1
                    yield lb
            if not errors:
                while heap:
                    _, lb = heapq.heappop(heap)
                    yield lb
        if errors:
            self._release_abandoned_permits(in_q, out_q, heap, feeder_thread)
            raise errors[0]

    def _release_abandoned_permits(self, in_q, out_q, heap, feeder_thread):
        """After a fatal pipeline error, permits acquired for batches that
        will never reach a gradient update (queued, looked-up-but-unyielded,
        or reordered-but-unyielded) are handed back, so an engine that
        outlives the error is not permanently throttled."""
        if self.staleness_sem is None:
            return
        import time

        def release_for(batch):
            if batch.requires_grad:
                self.staleness_sem.release()

        # heap/out_q first: their permits may be the very ones a blocked
        # feeder is waiting to acquire — releasing them unblocks it so
        # the in_q drain below terminates instead of timing out
        for _, lb in heap:
            release_for(lb.batch)
        while True:
            try:
                item = out_q.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL:
                self._g_out_q.dec(1)
                release_for(item[1].batch)
        deadline = time.monotonic() + 10.0
        while feeder_thread.is_alive() or not in_q.empty():
            try:
                item = in_q.get(timeout=0.2)
            except queue.Empty:
                if time.monotonic() > deadline:
                    break
                continue
            if item is not _SENTINEL:
                self._g_in_q.dec(1)
                release_for(item[1])

    def flush(self, timeout: Optional[float] = None):
        self.backward.flush(timeout=timeout)

    def shutdown(self):
        self.backward.shutdown()
