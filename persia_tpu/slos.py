"""Declarative SLO engine over scraped metric windows.

The fleet monitor (:mod:`persia_tpu.fleet`) scrapes every service's
``/metrics`` exposition; this module turns those per-target sample
snapshots into *judgements*: a rule is ``metric expression + comparison
+ threshold + burn window``, evaluated continuously, with alerts that
carry the breaching service's name and a bounded breach-event log for
postmortems and CI gates.

Expression grammar (deliberately small — every form is something the
scrape windows can answer without a query language):

- ``up``                  — synthetic per-target liveness (1 scraped ok,
  0 down); the "replica dead / sidecar wedged" rule.
- ``<metric>``            — the latest value of a gauge/counter, summed
  across the service's matching series.
- ``rate(<metric>)``      — per-second increase over the burn window
  (counter-reset aware: a restart counts from zero, not negative).
- ``increase(<metric>)``  — absolute increase over the burn window.
- ``ratio(<a>, <b>)``     — increase(a) / increase(b) over the window
  (0 when b did not move): error ratios, degradation ratios.
- ``p50/p90/p95/p99(<metric>)`` — quantile from a Prometheus histogram's
  ``_bucket`` series, computed on the window's bucket *increases* (the
  recent distribution, not the since-boot one).
- ``sustained(<metric>)``  — the comparison's conservative extremum of
  the per-scrape summed values across the window: under ``>``/``>=``
  the window MINIMUM ("never dipped below X"), under ``<``/``<=`` the
  MAXIMUM ("never rose above Y"), so the rule fires only when EVERY
  scrape in the window breaches. Answers None until the window holds
  at least 80% of its span. The hysteresis primitive an instantaneous
  scrape cannot express — the autopilot's scale decisions key on it.
- ``trend(<metric>)``      — least-squares slope (units/sec) of the
  per-scrape summed values over the window (None with <2 points):
  capacity-drift detection ("queue depth rising for 10 min").

Rules evaluate per matching service by default (``scope: service``) so
an alert names the replica that breached; ``scope: fleet`` aggregates
the expression across all matching services first (fleet-wide budgets).

A rule fires after the condition has held for ``for_sec`` (0 = first
breach fires immediately); each 0->1 firing transition is recorded in
``breaches`` (bounded) and handed to the ``on_breach`` callback — the
fleet monitor uses that hook to capture postmortem flight bundles.
"""

import re
import threading
import time
from collections import deque, namedtuple
from typing import Callable, Dict, List, Optional, Tuple

from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

_EXPR_RE = re.compile(
    r"^\s*(?:(?P<fn>rate|increase|ratio|sustained|trend"
    r"|p50|p90|p95|p99)\s*\(\s*"
    r"(?P<arg1>[a-zA-Z_:][a-zA-Z0-9_:]*)\s*"
    r"(?:,\s*(?P<arg2>[a-zA-Z_:][a-zA-Z0-9_:]*)\s*)?\)"
    r"|(?P<metric>[a-zA-Z_:][a-zA-Z0-9_:]*))\s*$")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


class SloRule:
    """One declarative objective. ``service`` is a regex matched against
    fleet service names (``ps0``, ``worker1``, ``serving:9000``...);
    None matches every service."""

    def __init__(self, name: str, expr: str, op: str, threshold: float,
                 window_sec: float = 60.0, for_sec: float = 0.0,
                 service: Optional[str] = None, scope: str = "service",
                 severity: str = "page", description: str = "",
                 by_label: Optional[str] = None):
        m = _EXPR_RE.match(expr)
        if m is None:
            raise ValueError(f"rule {name!r}: bad expression {expr!r}")
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: bad comparison {op!r} "
                             f"(one of {sorted(_OPS)})")
        if scope not in ("service", "fleet"):
            raise ValueError(f"rule {name!r}: scope must be service|fleet")
        self.name = name
        self.expr = expr
        self.fn = m.group("fn")          # None for bare metric / up
        self.arg1 = m.group("arg1") or m.group("metric")
        self.arg2 = m.group("arg2")
        if self.fn == "ratio" and not self.arg2:
            raise ValueError(f"rule {name!r}: ratio() needs two metrics")
        self.op = op
        self.threshold = float(threshold)
        self.window_sec = float(window_sec)
        self.for_sec = float(for_sec)
        self.service = service
        self._service_re = re.compile(service) if service else None
        self.scope = scope
        self.severity = severity
        self.description = description
        # per-label-value evaluation (the multi-variant serving tier's
        # isolation contract): the rule evaluates once PER VALUE of
        # this label — e.g. by_label="variant" judges every model
        # variant's series separately, so one broken canary fires its
        # own alert instead of hiding inside the service aggregate
        if by_label is not None and scope == "fleet":
            raise ValueError(f"rule {name!r}: by_label needs "
                             "service scope")
        self.by_label = by_label

    @classmethod
    def from_dict(cls, d: Dict) -> "SloRule":
        return cls(
            name=d["name"], expr=d["expr"], op=d.get("op", ">"),
            threshold=d["threshold"],
            window_sec=d.get("window_sec", 60.0),
            for_sec=d.get("for_sec", 0.0),
            service=d.get("service"), scope=d.get("scope", "service"),
            severity=d.get("severity", "page"),
            description=d.get("description", ""),
            by_label=d.get("by_label"),
        )

    def matches(self, service: str) -> bool:
        return self._service_re is None or bool(
            self._service_re.search(service))

    def describe(self) -> Dict:
        return {"name": self.name, "expr": self.expr, "op": self.op,
                "threshold": self.threshold,
                "window_sec": self.window_sec, "for_sec": self.for_sec,
                "service": self.service, "scope": self.scope,
                "severity": self.severity,
                "description": self.description,
                "by_label": self.by_label}


def load_rules(path: str) -> List[SloRule]:
    """Load a YAML (or JSON — YAML is a superset) rule file: a list of
    rule dicts, or ``{"rules": [...]}``."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    if isinstance(doc, dict):
        doc = doc.get("rules", [])
    return [SloRule.from_dict(d) for d in doc or []]


def default_rules() -> List[SloRule]:
    """The paved-road fleet objectives — every signal a hybrid
    train+serve deployment pages on today. A rule file replaces these
    wholesale; they double as grammar documentation."""
    return [
        SloRule("target_down", "up", "<", 1.0, window_sec=30.0,
                description="a service's sidecar stopped answering "
                            "scrapes (crashed or wedged replica)"),
        SloRule("lost_updates", "rate(pipeline_lost_updates_total)",
                ">", 0.0, window_sec=60.0,
                description="backward ships exhausted retries — "
                            "gradient updates are being dropped"),
        SloRule("serving_degraded",
                "ratio(inference_degraded_lookups_total,"
                " inference_requests_total)",
                ">", 0.05, window_sec=120.0,
                description="more than 5% of predicts served with "
                            "zero-vector embedding fallback"),
        SloRule("lookup_p99_slow", "p99(lookup_rpc_time_cost_sec)",
                ">", 1.0, window_sec=120.0,
                description="worker-observed PS lookup p99 above 1s"),
        SloRule("trace_ring_overrun", "rate(tracing_spans_dropped_total)",
                ">", 100.0, window_sec=60.0, severity="ticket",
                description="trace ring evicting >100 spans/s — "
                            "captures are incomplete"),
        # workload-telemetry objectives (both evaluate to no-data until
        # the staleness/freshness series exist, so unarmed fleets never
        # page on them)
        SloRule("gradient_staleness_high",
                "p99(ps_gradient_staleness_steps)",
                ">", 256.0, window_sec=120.0, severity="ticket",
                description="PS-observed gradient staleness p99 above "
                            "256 update steps — async updates are "
                            "applying far behind their lookups"),
        # sec_since_last_apply, not last_delay_sec: the delay gauge is
        # only written when a packet APPLIES, so it freezes at its last
        # healthy value during an actual stall — the since-apply clock
        # keeps rising on every scan, which is what stall detection
        # needs
        SloRule("serving_freshness_stale",
                "inc_update_sec_since_last_apply",
                ">", 600.0, window_sec=60.0,
                description="no incremental packet applied for over "
                            "10 minutes — the train->serve sync loop "
                            "is stalled"),
        # tier-ladder health: the device cache's whole value is hits
        # never paying the PS cycle; a collapsed hit rate means every
        # step silently degrades to flat-PS speed. ratio() is 0 while
        # the probes counter does not move, so uncached trainers never
        # page on this.
        # arena health: the slab arena never returns memory — evicted
        # slots are reused, not freed — so slab bytes parked in free
        # lists instead of live rows are invisible resident waste. A
        # sustained majority-free arena means the workload shrank far
        # below the allocated high-water mark (shrink the table, or
        # restart the replica to compact). No-data until a ps_arena_*
        # gauge exists, so legacy-holder fleets never page on this.
        SloRule("arena_fragmentation_runaway",
                "ps_arena_fragmentation_ratio",
                ">", 0.5, window_sec=120.0, for_sec=60.0,
                severity="ticket",
                description="over half the embedding arena's allocated "
                            "row slots are eviction-churned free space "
                            "for 2+ minutes — slab memory is parked "
                            "idle; shrink capacity or restart to "
                            "compact"),
        # elastic-tier objectives (no-data until a reshard controller
        # exports its gauges, so static fleets never page on them)
        SloRule("reshard_stuck", "reshard_active", ">", 0.0,
                window_sec=120.0, for_sec=600.0,
                description="a slot migration has been in flight for "
                            "over 10 minutes — the copy/replay loop is "
                            "stuck (donor wedged, capture set not "
                            "settling, or the controller died "
                            "mid-freeze); check /fleet/routing for the "
                            "frozen donor"),
        SloRule("reshard_frozen_slot_stuck", "ps_frozen_slot_age_sec",
                ">", 120.0, window_sec=60.0, for_sec=60.0,
                description="a donor PS has held write-frozen slots for "
                            "over 2 minutes — its reshard controller "
                            "died post-freeze (the controller-side "
                            "reshard_stuck gauge cannot see this) or "
                            "the cutover wedged; the freeze lease "
                            "(PERSIA_RESHARD_FREEZE_LEASE_SEC) will "
                            "auto-thaw the donor, then resume() the "
                            "migration from its journal or abort it "
                            "(docs/DEPLOY.md runbook)"),
        SloRule("reshard_replay_runaway",
                "rate(reshard_replayed_rows_total)", ">", 100000.0,
                window_sec=120.0, severity="ticket",
                description="capture replay moving >100k rows/s for "
                            "minutes — write traffic into the moving "
                            "slots outruns the drain; shrink the move "
                            "batch or reshard off-peak"),
        # online-learning loop objectives (both no-data until a serving
        # delta subscriber exports its series, so TTL-only fleets never
        # page on them). The stall clock itself is covered by
        # serving_freshness_stale above: the subscriber exports the
        # SAME inc_update_sec_since_last_apply name, so that rule now
        # fires per serving replica too.
        SloRule("serving_sign_to_servable_slow",
                "p99(serving_sign_to_servable_lag_sec)",
                ">", 60.0, window_sec=300.0, severity="ticket",
                description="online-learning freshness p99 above 60s — "
                            "trained rows are taking over a minute to "
                            "become servable (scan interval too slow, "
                            "governor throttling hard, or the dumper's "
                            "flush cadence collapsed)"),
        # per-VARIANT isolation: by_label fans the judgement out per
        # model variant, so one broken canary fires alone instead of
        # averaging into the healthy default's traffic
        SloRule("variant_degraded",
                "ratio(inference_variant_degraded_total,"
                " inference_variant_requests_total)",
                ">", 0.05, window_sec=120.0, by_label="variant",
                description="more than 5% of ONE model variant's "
                            "predicts served zero-vector embedding "
                            "fallback — judged per variant, so an A/B "
                            "arm degrading alone still pages"),
        SloRule("device_cache_hit_collapse",
                "ratio(device_cache_misses_total,"
                " device_cache_probes_total)",
                ">", 0.5, window_sec=120.0, for_sec=60.0,
                severity="ticket",
                description="device-cache hit rate below 50% over 2 "
                            "minutes — the HBM tier is thrashing (hot "
                            "set outgrew capacity, or cold traffic is "
                            "flooding admission); training pays the "
                            "PS cycle on most rows"),
    ]


class _Window:
    """Per-service scrape history: a deque of ``(t, series)`` snapshots
    where ``series`` maps ``(name, labels_tuple) -> value``."""

    def __init__(self):
        self.snaps: "deque[Tuple[float, Dict]]" = deque()
        self.up = True

    def add(self, t: float, series: Dict, keep_sec: float):
        self.snaps.append((t, series))
        while self.snaps and self.snaps[0][0] < t - keep_sec:
            self.snaps.popleft()


# immutable view handed to expression evaluation (the scrape thread
# keeps appending to the live deques; evaluation reads a frozen copy)
_Frozen = namedtuple("_Frozen", ["snaps", "up"])


class SloEngine:
    """Continuous evaluation of :class:`SloRule` objectives over
    per-service scrape snapshots.

    Thread-safe: the fleet scrape loop calls :meth:`ingest` /
    :meth:`mark_down` per target, anyone may call :meth:`evaluate` /
    :meth:`alerts`. Breach events (0->1 firing transitions) land in
    ``breaches`` (bounded ring) and fire ``on_breach(alert_dict)``.
    """

    MAX_BREACHES = 256

    def __init__(self, rules: Optional[List[SloRule]] = None,
                 on_breach: Optional[Callable[[Dict], None]] = None):
        self.rules = list(rules) if rules is not None else default_rules()
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._windows: Dict[str, _Window] = {}
        # (rule name, service) -> {"pending_since", "firing_since"}
        self._state: Dict[Tuple[str, str], Dict] = {}
        self.breaches: "deque[Dict]" = deque(maxlen=self.MAX_BREACHES)
        self._keep_sec = max([r.window_sec for r in self.rules] + [60.0])

    def add_rules(self, rules: List[SloRule]):
        """Install additional rules at runtime, idempotent by name —
        the autopilot contributes its policy rules to an already-
        running engine. The retention window re-widens to cover the
        largest new window."""
        with self._lock:
            have = {r.name for r in self.rules}
            for r in rules:
                if r.name not in have:
                    self.rules.append(r)
                    have.add(r.name)
            self._keep_sec = max([r.window_sec for r in self.rules]
                                 + [60.0])

    # --- ingestion -------------------------------------------------------

    def ingest(self, service: str, samples, t: Optional[float] = None):
        """Feed one scrape's parsed samples (``metrics.parse_exposition``
        output, or any iterable of (name, labels, value))."""
        t = time.monotonic() if t is None else t
        series: Dict = {}
        for name, labels, value in samples:
            key = (name, tuple(sorted(labels.items())))
            # duplicate series within one scrape (multiple servers in
            # one process): sum — one exposition, one sample per key
            series[key] = series.get(key, 0.0) + value
        with self._lock:
            w = self._windows.setdefault(service, _Window())
            w.up = True
            w.add(t, series, self._keep_sec)

    def mark_down(self, service: str, t: Optional[float] = None):
        """A scrape failed: the service contributes ``up == 0`` and its
        stale series stop advancing (rates decay to 0 naturally)."""
        with self._lock:
            w = self._windows.setdefault(service, _Window())
            w.up = False

    def forget(self, service: str):
        # by_label judgement state is keyed "service[label=value]" —
        # forgetting a service must drop those too, or a re-registered
        # service inherits a drained variant's firing_since and never
        # fires a fresh breach
        with self._lock:
            self._windows.pop(service, None)
            for key in [k for k in self._state
                        if k[1] == service
                        or k[1].startswith(service + "[")]:
                self._state.pop(key, None)

    # --- expression evaluation -------------------------------------------

    @staticmethod
    def _latest(w: _Window, name: str) -> Optional[float]:
        if not w.snaps:
            return None
        _, series = w.snaps[-1]
        vals = [v for (n, _l), v in series.items() if n == name]
        return sum(vals) if vals else None

    @staticmethod
    def _series_increase(w: _Window, name: str, window_sec: float,
                         now: float):
        """Per-series (increase, dt) over the window, counter-reset
        aware. Returns dict keyed by labels_tuple."""
        if not w.snaps:
            return {}
        t_last, last = w.snaps[-1]
        first_by_key: Dict = {}
        t_first_by_key: Dict = {}
        for t, series in w.snaps:
            if t < now - window_sec:
                continue
            for key, v in series.items():
                if key not in first_by_key:
                    first_by_key[key] = v
                    t_first_by_key[key] = t
        out = {}
        for (n, lbl), v_last in last.items():
            if n != name:
                continue
            v_first = first_by_key.get((n, lbl), v_last)
            inc = v_last - v_first
            if inc < 0:  # counter reset mid-window (service restart)
                inc = v_last
            out[lbl] = (inc, max(t_last - t_first_by_key.get((n, lbl),
                                                            t_last), 0.0))
        return out

    def _increase(self, w: _Window, name: str, window_sec: float,
                  now: float) -> Optional[float]:
        per = self._series_increase(w, name, window_sec, now)
        if not per:
            return None
        return sum(inc for inc, _ in per.values())

    def _rate(self, w: _Window, name: str, window_sec: float,
              now: float) -> Optional[float]:
        per = self._series_increase(w, name, window_sec, now)
        vals = [inc / dt for inc, dt in per.values() if dt > 0]
        if not vals:
            return None
        return sum(vals)

    def _quantile(self, w: _Window, name: str, q: float,
                  window_sec: float, now: float) -> Optional[float]:
        """Histogram quantile over the window's bucket increases; falls
        back to the cumulative buckets when the window saw no traffic
        start (fresh window)."""
        per = self._series_increase(w, name + "_bucket", window_sec, now)
        buckets: Dict[float, float] = {}
        for lbl, (inc, _dt) in per.items():
            le = dict(lbl).get("le")
            if le is None:
                continue
            bound = float("inf") if le == "+Inf" else float(le)
            buckets[bound] = buckets.get(bound, 0.0) + inc
        if not buckets or all(v <= 0 for v in buckets.values()):
            return None
        bounds = sorted(buckets)
        total = buckets[bounds[-1]]  # +Inf cumulative == count
        if total <= 0:
            return None
        rank = q * total
        lo = 0.0
        prev_cum = 0.0
        for b in bounds:
            cum = buckets[b]
            if cum >= rank:
                if b == float("inf"):
                    return lo  # pessimistic finite answer
                width = cum - prev_cum
                frac = ((rank - prev_cum) / width) if width > 0 else 1.0
                return lo + (b - lo) * min(max(frac, 0.0), 1.0)
            prev_cum = cum
            lo = b if b != float("inf") else lo
        return bounds[-2] if len(bounds) > 1 else 0.0

    @staticmethod
    def _points(w: _Window, name: str, window_sec: float,
                now: float) -> List[Tuple[float, float]]:
        """Per-snapshot summed values of ``name`` inside the window —
        the time series sustained()/trend() aggregate over."""
        pts: List[Tuple[float, float]] = []
        for t, series in w.snaps:
            if t < now - window_sec:
                continue
            vals = [v for (n, _l), v in series.items() if n == name]
            if vals:
                pts.append((t, sum(vals)))
        return pts

    def _sustained(self, w: _Window, name: str, window_sec: float,
                   now: float, op: str = ">") -> Optional[float]:
        """The comparison's conservative extremum of the per-scrape
        summed values over the window: min under >/>= ("never dipped
        below"), max under </<= ("never rose above") — either way the
        rule only fires when every in-window scrape breaches. Answers
        None until the window holds >=80% of its span — a freshly
        started monitor (or a freshly appeared series) must not
        declare load "sustained" off its first two scrapes. 80% rather
        than 100% because retention prunes to exactly the largest rule
        window, so strict coverage could never be met."""
        pts = self._points(w, name, window_sec, now)
        if not pts or now - pts[0][0] < window_sec * 0.8:
            return None
        ys = [v for _, v in pts]
        return max(ys) if op in ("<", "<=") else min(ys)

    def _trend(self, w: _Window, name: str, window_sec: float,
               now: float) -> Optional[float]:
        """Least-squares slope (units/sec) of the per-scrape summed
        values over the window; None until two points exist."""
        pts = self._points(w, name, window_sec, now)
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return None
        n = len(pts)
        t0 = pts[0][0]
        xs = [t - t0 for t, _ in pts]
        ys = [v for _, v in pts]
        mx = sum(xs) / n
        my = sum(ys) / n
        den = sum((x - mx) ** 2 for x in xs)
        if den <= 0:
            return None
        num = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
        return num / den

    def _eval_expr(self, rule: SloRule, w: _Window,
                   now: float) -> Optional[float]:
        if rule.arg1 == "up" and rule.fn is None:
            return 1.0 if w.up else 0.0
        if rule.fn is None:
            return self._latest(w, rule.arg1)
        if rule.fn == "rate":
            return self._rate(w, rule.arg1, rule.window_sec, now)
        if rule.fn == "increase":
            return self._increase(w, rule.arg1, rule.window_sec, now)
        if rule.fn == "sustained":
            return self._sustained(w, rule.arg1, rule.window_sec, now,
                                   op=rule.op)
        if rule.fn == "trend":
            return self._trend(w, rule.arg1, rule.window_sec, now)
        if rule.fn == "ratio":
            num = self._increase(w, rule.arg1, rule.window_sec, now)
            den = self._increase(w, rule.arg2, rule.window_sec, now)
            if num is None and den is None:
                return None
            if not den:
                return 0.0
            return (num or 0.0) / den
        q = {"p50": 0.50, "p90": 0.90, "p95": 0.95, "p99": 0.99}[rule.fn]
        return self._quantile(w, rule.arg1, q, rule.window_sec, now)

    # --- per-label evaluation (by_label rules) ---------------------------

    @staticmethod
    def _label_values(w, rule: SloRule) -> set:
        """Values of ``rule.by_label`` present on the rule's own series
        in the latest snapshot (restricting to the rule's metric names
        keeps an unrelated metric that happens to carry the label from
        minting phantom groups)."""
        if not w.snaps:
            return set()
        names = {rule.arg1, rule.arg1 + "_bucket"}
        if rule.arg2:
            names.add(rule.arg2)
        _, series = w.snaps[-1]
        out = set()
        for (name, lbl) in series:
            if name in names:
                val = dict(lbl).get(rule.by_label)
                if val is not None:
                    out.add(val)
        return out

    @staticmethod
    def _filter_label(w, label: str, value: str):
        """A window view holding only series whose ``label`` equals
        ``value`` — what a by_label rule evaluates per group."""
        snaps = [(t, {k: v for k, v in series.items()
                      if dict(k[1]).get(label) == value})
                 for t, series in w.snaps]
        return _Frozen(snaps, w.up)

    # --- evaluation ------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """Evaluate every rule against the current windows; returns the
        full alert list (firing and healthy) and records/announces new
        breaches."""
        now = time.monotonic() if now is None else now
        fired: List[Dict] = []
        with self._lock:
            windows = {s: _Frozen(list(w.snaps), w.up)
                       for s, w in self._windows.items()}
        alerts: List[Dict] = []
        # tuple(): add_rules may append concurrently mid-evaluation
        for rule in tuple(self.rules):
            matched = {s: w for s, w in windows.items()
                       if rule.matches(s)}
            if rule.scope == "fleet":
                vals = [self._eval_expr(rule, w, now)
                        for w in matched.values()]
                vals = [v for v in vals if v is not None]
                value = sum(vals) if vals else None
                alerts.append(self._judge(rule, "fleet", value, now,
                                          fired))
            elif rule.by_label is not None:
                # per-label-value isolation: one judgement per value of
                # the label (e.g. per model variant), keyed
                # service[label=value] so alert/breach state never
                # blends across values — a healthy default cannot mask
                # (or be masked by) a broken canary
                judged = set()
                for service in sorted(matched):
                    w = matched[service]
                    for val in sorted(self._label_values(w, rule)):
                        skey = f"{service}[{rule.by_label}={val}]"
                        judged.add((rule.name, skey))
                        value = self._eval_expr(
                            rule, self._filter_label(w, rule.by_label,
                                                     val), now)
                        alerts.append(self._judge(
                            rule, skey, value, now, fired))
                # label-value churn: a value absent from its service's
                # latest snapshot (variant drained/removed) must not
                # park pending/firing state — a re-registered variant
                # that is STILL breaching gets a fresh breach event
                # instead of silently inheriting firing_since (which
                # would suppress the postmortem capture)
                with self._lock:
                    for k in [k for k in self._state
                              if k[0] == rule.name and "[" in k[1]
                              and k not in judged]:
                        self._state.pop(k, None)
            else:
                for service in sorted(matched):
                    value = self._eval_expr(rule, matched[service], now)
                    alerts.append(self._judge(rule, service, value, now,
                                              fired))
        for alert in fired:
            self.breaches.append(alert)
            _logger.warning("SLO breach: %s on %s — %s %s %s (value %s)",
                            alert["rule"], alert["service"], alert["expr"],
                            alert["op"], alert["threshold"],
                            alert["value"])
            if self.on_breach is not None:
                try:
                    self.on_breach(alert)
                except Exception:
                    _logger.exception("on_breach callback failed")
        return alerts

    def _judge(self, rule: SloRule, service: str, value: Optional[float],
               now: float, fired: List[Dict]) -> Dict:
        key = (rule.name, service)
        breaching = value is not None and _OPS[rule.op](value,
                                                        rule.threshold)
        with self._lock:
            st = self._state.setdefault(
                key, {"pending_since": None, "firing_since": None})
            if breaching:
                if st["pending_since"] is None:
                    st["pending_since"] = now
                held = now - st["pending_since"]
                if held >= rule.for_sec and st["firing_since"] is None:
                    st["firing_since"] = now
                    new_breach = True
                else:
                    new_breach = False
            else:
                st["pending_since"] = None
                st["firing_since"] = None
                new_breach = False
            firing = st["firing_since"] is not None
            firing_since = st["firing_since"]
        alert = {
            "rule": rule.name, "service": service,
            "expr": rule.expr, "op": rule.op,
            "threshold": rule.threshold,
            "value": value, "firing": firing,
            "firing_since": firing_since, "t": now,
            "severity": rule.severity,
            "description": rule.description,
        }
        if new_breach:
            fired.append(dict(alert))
        return alert

    def alerts(self, firing_only: bool = False) -> List[Dict]:
        out = self.evaluate()
        if firing_only:
            out = [a for a in out if a["firing"]]
        return out

    def breach_events(self) -> List[Dict]:
        with self._lock:
            return list(self.breaches)

    def exit_code(self) -> int:
        """CI gate: nonzero iff any rule is currently firing."""
        return 1 if any(a["firing"] for a in self.evaluate()) else 0
