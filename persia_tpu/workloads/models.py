"""Dense towers for the workload-zoo scenarios (flax.linen, bf16-first).

All three share the repo's model calling convention
(``model(non_id_tensors, embedding_tensors, train=...)``) and run on
the existing ctx/pipeline stack unchanged — the zoo adds model SHAPES
(mixed embedding dims, worker-pooled session slots, multi-task heads),
not a new training path.
"""

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import MLP


def _pool_if_raw(e, dt):
    """(bs, dim) pooled slots pass through; a raw (emb, index) pair is
    mean-pooled on device (fallback — zoo schemas pool on the worker)."""
    if isinstance(e, (tuple, list)):
        from persia_tpu.models.common import gather_raw_embedding

        emb, index = e
        gathered, mask = gather_raw_embedding(emb, index)
        denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
        return (gathered.sum(axis=1) / denom).astype(dt)
    return e.astype(dt)


class ZooDLRM(nn.Module):
    """DLRM-shaped tower over a MIXED-dim embedding schema.

    The classic DLRM interaction needs every field at one width; real
    schemas ladder dims by table cardinality. Fields whose dim differs
    from ``proj_dim`` go through a per-field linear projection first
    (the standard mixed-dim DLRM trick), then the usual lower-triangle
    pairwise dots + bottom/top MLPs.
    """

    proj_dim: int = 16
    bottom_mlp: Sequence[int] = (64, 32)
    top_mlp: Sequence[int] = (128, 64)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors, embedding_tensors,
                 train: bool = False):
        dt = self.compute_dtype
        dense_x = non_id_tensors[0].astype(dt)
        bottom = MLP((*self.bottom_mlp, self.proj_dim),
                     compute_dtype=dt)(dense_x, train)
        fields = []
        for i, e in enumerate(embedding_tensors):
            x = _pool_if_raw(e, dt)
            if x.shape[-1] != self.proj_dim:
                x = nn.Dense(self.proj_dim, dtype=dt,
                             name=f"field_proj_{i}")(x)
            fields.append(x)
        t = jnp.stack([bottom, *fields], axis=1)  # (bs, F+1, proj_dim)
        dots = jnp.einsum("bfd,bgd->bfg", t, t)
        f = t.shape[1]
        iu, ju = jnp.triu_indices(f, k=1)
        interactions = dots[:, iu, ju]
        top_in = jnp.concatenate([bottom, interactions.astype(dt)], axis=1)
        out = MLP((*self.top_mlp, 1), final_activation=False,
                  compute_dtype=dt)(top_in, train)
        return nn.sigmoid(out.astype(jnp.float32))


class PooledSessionNet(nn.Module):
    """Session tower over WORKER-pooled slots: every embedding input is
    already a (bs, dim) vector (mean / last-N pooling ran on the worker
    tier), so the device side is one concat + MLP — the cheap-inference
    counterpart of the attention SequenceTower."""

    mlp: Sequence[int] = (128, 64)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors, embedding_tensors,
                 train: bool = False):
        dt = self.compute_dtype
        parts = [t.astype(dt) for t in non_id_tensors]
        parts += [_pool_if_raw(e, dt) for e in embedding_tensors]
        x = jnp.concatenate(parts, axis=1)
        out = MLP((*self.mlp, 1), final_activation=False,
                  compute_dtype=dt)(x, train)
        return nn.sigmoid(out.astype(jnp.float32))


class MultiTaskDNN(nn.Module):
    """Shared-bottom multi-task tower: one trunk over the shared
    embedding tables + dense features, one small head per task,
    predictions concatenated to (bs, num_tasks) — labels travel as one
    (bs, num_tasks) array, so the whole single-Label train path (packed
    wire, DDP step, pipeline) carries both objectives unchanged."""

    num_tasks: int = 2
    bottom_mlp: Sequence[int] = (128, 64)
    head_mlp: Sequence[int] = (32,)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors, embedding_tensors,
                 train: bool = False):
        dt = self.compute_dtype
        parts = [t.astype(dt) for t in non_id_tensors]
        parts += [_pool_if_raw(e, dt) for e in embedding_tensors]
        x = jnp.concatenate(parts, axis=1)
        trunk = MLP(tuple(self.bottom_mlp), compute_dtype=dt)(x, train)
        heads = []
        for t in range(self.num_tasks):
            h = MLP((*self.head_mlp, 1), final_activation=False,
                    compute_dtype=dt, name=f"head_{t}")(trunk, train)
            heads.append(h)
        out = jnp.concatenate(heads, axis=1)
        return nn.sigmoid(out.astype(jnp.float32))


def multitask_bce(pred: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Mean BCE over every task column: d(L)/d(shared embedding) is the
    SUM of the per-task gradients (the shared-table accounting the zoo
    tests pin), scaled by 1/num_tasks."""
    pred = jnp.clip(pred, 1e-7, 1.0 - 1e-7)
    return -jnp.mean(label * jnp.log(pred)
                     + (1.0 - label) * jnp.log(1.0 - pred))
