"""Workload zoo: production-shaped, seed-deterministic scenarios wired
as first-class bench drivers (ROADMAP item 5).

See :mod:`persia_tpu.workloads.generator` for the data layer,
:mod:`persia_tpu.workloads.models` for the dense towers, and
:mod:`persia_tpu.workloads.registry` for the scenario registry that
``bench.py --mode e2e --scenario {dlrm,seqrec,multitask}`` resolves.
"""

from persia_tpu.workloads.registry import (
    Scenario,
    evaluate_auc,
    get_scenario,
    register_scenario,
    scenario_names,
)

__all__ = [
    "Scenario",
    "evaluate_auc",
    "get_scenario",
    "register_scenario",
    "scenario_names",
]
