"""Scenario registry: production-shaped workloads as first-class bench
drivers.

A :class:`Scenario` bundles everything a bench or example needs to run
one zoo workload end to end on the existing stack — the embedding
schema (dims, pooling modes), the dense tower, the deterministic batch
generator, the loss, and the convergence gate the e2e smoke enforces.
``bench.py --mode e2e --scenario {dlrm,seqrec,multitask}`` resolves
through :func:`get_scenario`; examples import the same factories so
tests, benches and the examples all train the ONE shared workload
definition.

Scenario knobs: ``PERSIA_WORKLOAD_ALPHA`` (zipf skew) and
``PERSIA_WORKLOAD_SEED`` (base seed) set the defaults; ``get_scenario``
arguments override.
"""

import functools
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from persia_tpu.config import EmbeddingSchema, SlotConfig, uniform_slots
from persia_tpu.workloads import generator as gen


@dataclass(frozen=True)
class Scenario:
    """One runnable zoo workload (schema + model + stream + gates)."""

    name: str
    description: str
    schema: EmbeddingSchema
    model_fn: Callable[[], object]       # () -> flax module
    batches: Callable[..., Iterator]     # (num_samples, batch_size,
    #                                       seed=, requires_grad=) -> iter
    num_dense: int
    tasks: Tuple[str, ...] = ("ctr",)
    loss_fn: Optional[Callable] = None   # None -> ctx default (bce)
    # convergence smoke: held-out AUC floor (min over tasks) after the
    # smoke row budget; deliberately loose — it catches "not learning",
    # not "state of the art"
    auc_gate: float = 0.55
    # ragged (worker-pooled / raw) feature names, () when the wire
    # carries single-id features only (the byte-identical-wire pin arm)
    ragged_features: Tuple[str, ...] = ()
    # default per-step batch rows for the e2e bench (smoke shrinks it)
    bench_batch_size: int = 1024
    seed: int = 0

    def model(self):
        return self.model_fn()


_FACTORIES: Dict[str, Callable[..., Scenario]] = {}


def register_scenario(name: str):
    def deco(fn):
        _FACTORIES[name] = fn
        return fn
    return deco


def scenario_names() -> Tuple[str, ...]:
    return tuple(sorted(_FACTORIES))


def get_scenario(name: str, smoke: bool = False,
                 alpha: Optional[float] = None,
                 seed: Optional[int] = None, **kw) -> Scenario:
    """Resolve a scenario by name. ``smoke`` shrinks vocabs/batches to
    the CI row budget; ``alpha``/``seed`` default to the
    ``PERSIA_WORKLOAD_*`` knobs."""
    from persia_tpu import knobs

    if name not in _FACTORIES:
        raise KeyError(
            f"unknown scenario {name!r}; registered: "
            f"{', '.join(scenario_names())}")
    if alpha is None:
        alpha = float(knobs.get("PERSIA_WORKLOAD_ALPHA"))
    if seed is None:
        seed = int(knobs.get("PERSIA_WORKLOAD_SEED"))
    return _FACTORIES[name](smoke=smoke, alpha=alpha, seed=seed, **kw)


def _bind_seed(fn, default_seed):
    """Bind a generator (with its spec pre-applied via partial) to the
    scenario's default seed; callers may still override (eval streams
    pass seed+1000 and stay disjoint draws of the same task)."""
    def batches(num_samples, batch_size, seed=default_seed,
                requires_grad=True):
        return fn(num_samples, batch_size, seed=seed,
                  requires_grad=requires_grad)
    return batches


@register_scenario("dlrm")
def _dlrm(smoke: bool = False, alpha: float = 1.05, seed: int = 0,
          scale: Optional[float] = None) -> Scenario:
    """Criteo-schema DLRM: 26 zipf categorical tables with a realistic
    log-spread vocab/dim mix + 13 dense floats, mixed-dim interaction
    tower. The wire carries single-id features ONLY — this is the
    byte-identical-wire pin arm of the e2e gate, and the scenario whose
    traffic validates the hotness planner."""
    if scale is None:
        scale = 0.02 if smoke else 0.2
    spec = gen.CriteoSpec.build(scale=scale, alpha=alpha)
    slots = {
        name: SlotConfig(name=name, dim=spec.dims[t])
        for t, name in enumerate(gen.CRITEO_SLOT_NAMES)
    }
    schema = EmbeddingSchema(slots_config=slots)

    def model_fn():
        from persia_tpu.workloads.models import ZooDLRM

        return ZooDLRM(proj_dim=16)

    batches = _bind_seed(
        functools.partial(gen.dlrm_batches, spec=spec), seed)

    return Scenario(
        name="dlrm",
        description=("Criteo-schema DLRM: 26 zipf tables (mixed "
                     "vocab/dim), 13 dense, pairwise interaction"),
        schema=schema, model_fn=model_fn, batches=batches,
        num_dense=spec.num_dense, auc_gate=0.60,
        bench_batch_size=2048 if not smoke else 256, seed=seed)


@register_scenario("seqrec")
def _seqrec(smoke: bool = False, alpha: float = 1.05,
            seed: int = 0) -> Scenario:
    """Session recommendation over WORKER-pooled ragged history: a
    mean-pooled recent-items slot + a last-N-pooled clicks slot sharing
    the target's item sign space, label planted in history homogeneity."""
    spec = gen.SeqRecSpec(
        item_vocab=2_000 if smoke else 20_000,
        t_hist=12 if smoke else 20,
        alpha=alpha)
    dim = spec.dim
    slots = {
        **uniform_slots(list(gen.SEQ_PROFILE_SLOTS), dim=dim),
        gen.SEQ_HISTORY_SLOT: SlotConfig(
            name=gen.SEQ_HISTORY_SLOT, dim=dim, pooling="mean"),
        gen.SEQ_CLICKS_SLOT: SlotConfig(
            name=gen.SEQ_CLICKS_SLOT, dim=dim,
            pooling=f"last{spec.last_n}"),
        gen.SEQ_TARGET_SLOT: SlotConfig(
            name=gen.SEQ_TARGET_SLOT, dim=dim),
    }
    schema = EmbeddingSchema(slots_config=slots)

    def model_fn():
        from persia_tpu.workloads.models import PooledSessionNet

        return PooledSessionNet()

    batches = _bind_seed(
        functools.partial(gen.seqrec_batches, spec=spec), seed)

    return Scenario(
        name="seqrec",
        description=("session/sequence features: ragged histories "
                     "pooled mean + last-N on the worker tier"),
        schema=schema, model_fn=model_fn, batches=batches,
        num_dense=spec.num_dense, auc_gate=0.60,
        ragged_features=(gen.SEQ_HISTORY_SLOT, gen.SEQ_CLICKS_SLOT),
        bench_batch_size=512 if not smoke else 128, seed=seed)


@register_scenario("multitask")
def _multitask(smoke: bool = False, alpha: float = 1.05,
               seed: int = 0) -> Scenario:
    """Two objectives (click, convert) over one shared set of embedding
    tables; labels ride as one (batch, 2) array through the unchanged
    single-Label train path."""
    spec = gen.MultiTaskSpec(
        user_vocab=2_000 if smoke else 20_000,
        item_vocab=5_000 if smoke else 50_000,
        alpha=alpha)
    dim = spec.dim
    slots = {
        "user": SlotConfig(name="user", dim=dim),
        "item": SlotConfig(name="item", dim=dim),
        "ctx_0": SlotConfig(name="ctx_0", dim=8),
        "ctx_1": SlotConfig(name="ctx_1", dim=8),
    }
    schema = EmbeddingSchema(slots_config=slots)

    def model_fn():
        from persia_tpu.workloads.models import MultiTaskDNN

        return MultiTaskDNN(num_tasks=2)

    batches = _bind_seed(
        functools.partial(gen.multitask_batches, spec=spec), seed)

    from persia_tpu.workloads.models import multitask_bce

    return Scenario(
        name="multitask",
        description=("multi-task head (click + convert) sharing "
                     "embedding tables across two objectives"),
        schema=schema, model_fn=model_fn, batches=batches,
        num_dense=spec.num_dense, tasks=gen.MT_TASKS,
        loss_fn=multitask_bce, auc_gate=0.55,
        bench_batch_size=1024 if not smoke else 256, seed=seed)


# --- shared evaluation helper -------------------------------------------

def evaluate_auc(ctx, scenario: Scenario, num_samples: int = 4096,
                 batch_size: int = 512,
                 seed_offset: int = 1000) -> Dict[str, float]:
    """Held-out per-task AUC through the ctx's eval path. The eval
    stream uses ``scenario.seed + seed_offset`` — a disjoint draw from
    the SAME hidden task (the generators' determinism contract)."""
    from persia_tpu.ctx import eval_ctx
    from persia_tpu.utils import roc_auc

    preds, labels = [], []
    with eval_ctx(ctx) as ectx:
        for batch in scenario.batches(num_samples, batch_size,
                                      seed=scenario.seed + seed_offset,
                                      requires_grad=False):
            pred, lab = ectx.forward(batch)
            preds.append(np.asarray(pred))
            labels.append(np.asarray(lab[0]))
    pred = np.concatenate(preds)
    pred = pred.reshape(pred.shape[0], -1)
    label = np.concatenate(labels).reshape(pred.shape[0], -1)
    return {
        task: float(roc_auc(label[:, t], pred[:, t]))
        for t, task in enumerate(scenario.tasks)
    }
