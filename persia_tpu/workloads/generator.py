"""Production-shaped synthetic workload generators — the zoo's data layer.

Every bench and convergence gate in the repo used to train ONE uniform
synthetic table; the workload zoo replaces that with scenario streams
shaped like traffic from millions of users:

- **Criteo-schema DLRM traffic** (:func:`dlrm_batches`): 13 dense floats
  + 26 categorical tables with a realistic log-spread vocab mix, each
  table drawing signs from an EXACT truncated zipf (configurable alpha)
  — the skew the hotness telemetry/planner stack (PR 8/9) was built to
  measure but never met from a source it did not itself generate.
- **Session/sequence traffic** (:func:`seqrec_batches`): variable-length
  sign lists (ragged CSR features) pooled on the WORKER tier
  (mean / last-N; see ``SlotConfig.pooling``), with the label signal
  planted IN the session history.
- **Multi-task traffic** (:func:`multitask_batches`): two objectives
  (click + convert) over one shared set of embedding tables, labels
  shipped as one (batch, 2) array.

Determinism contract: every generator is a pure function of its
arguments — the same ``seed`` yields byte-identical batch streams
(paired A/Bs and convergence smokes depend on it), and the label
structure (hidden per-sign weights) is FIXED independently of ``seed``,
so different seeds are disjoint draws from the same task: train on one
seed, evaluate on another.
"""

from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import numpy as np

from persia_tpu.data.batch import (
    IDTypeFeature,
    IDTypeFeatureWithSingleID,
    Label,
    NonIDTypeFeature,
    PersiaBatch,
)

NUM_DENSE = 13
NUM_TABLES = 26
CRITEO_SLOT_NAMES = [f"C{i + 1}" for i in range(NUM_TABLES)]

_U64 = np.uint64


# --- exact truncated zipf ------------------------------------------------

def zipf_cdf(vocab: int, alpha: float) -> np.ndarray:
    """CDF of the truncated zipf(alpha) law over ranks 1..vocab.

    Exact inverse-CDF sampling on purpose: ``rng.zipf`` folds an
    unbounded tail back through ``%``, distorting the head that the
    telemetry accuracy gates (and the planner validation) fit against.
    """
    p = np.arange(1, vocab + 1, dtype=np.float64) ** -float(alpha)
    return np.cumsum(p / p.sum())


def zipf_ranks(rng: np.random.Generator, cdf: np.ndarray,
               size) -> np.ndarray:
    """0-based zipf ranks drawn through a precomputed :func:`zipf_cdf`.
    float cumsum can leave cdf[-1] a hair below 1 — clip so the sliver
    cannot mint rank ``vocab``."""
    return np.searchsorted(cdf, rng.random(size)).clip(
        max=len(cdf) - 1).astype(np.int64)


# --- deterministic hidden task structure ---------------------------------

def hidden_weight(stream: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Deterministic ~N(0,1) hidden weight per (stream, id), computed by
    hashing on the fly (splitmix64 mixing + Box-Muller): materializing a
    (streams, vocab) matrix costs hundreds of MB per loader replica at
    production vocabs, all for rows that are mostly never drawn. The
    weights do NOT depend on the generator seed — they define the task,
    not the draw."""
    x = (ids.astype(np.uint64) * _U64(0x9E3779B97F4A7C15)
         + (np.asarray(stream, np.uint64) + _U64(1))
         * _U64(0xBF58476D1CE4E5B9))

    def mix(v):
        v = v ^ (v >> _U64(30))
        v = v * _U64(0xBF58476D1CE4E5B9)
        v = v ^ (v >> _U64(27))
        v = v * _U64(0x94D049BB133111EB)
        return v ^ (v >> _U64(31))

    h1 = mix(x)
    h2 = mix(x ^ _U64(0xD6E8FEB86659FD93))
    u1 = ((h1 >> _U64(11)).astype(np.float64) + 1.0) / (2.0**53 + 2)
    u2 = (h2 >> _U64(11)).astype(np.float64) / 2.0**53
    return np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)


def _labels_from_logits(rng: np.random.Generator, logits: np.ndarray,
                        noise: float) -> np.ndarray:
    """Std-normalized logistic draw: the label is recoverable (training
    must learn the hidden weights to beat AUC 0.5) but never separable
    (the ``noise`` fraction of the logit scale is irreducible)."""
    std = float(logits.std()) or 1.0
    noisy = logits + rng.normal(0.0, noise * std, size=logits.shape)
    prob = 1.0 / (1.0 + np.exp(-2.5 * noisy / std))
    return (rng.random(logits.shape) < prob).astype(np.float32)


# --- Criteo-schema spec --------------------------------------------------

@dataclass(frozen=True)
class CriteoSpec:
    """Shape of the synthetic Criteo-schema stream: per-table vocab
    sizes (log-spread, like the real Criteo tables' wild cardinality
    mix), per-table embedding dims (rank-laddered: bigger vocab, wider
    embedding), and the zipf skew."""

    vocabs: Tuple[int, ...]
    dims: Tuple[int, ...]
    alpha: float = 1.05
    num_dense: int = NUM_DENSE
    label_noise: float = 0.25

    @property
    def num_tables(self) -> int:
        return len(self.vocabs)

    @property
    def sign_offsets(self) -> np.ndarray:
        """Per-table base offsets keeping sign ranges disjoint in the
        shared PS keyspace (+1 everywhere keeps sign 0 = "missing")."""
        return np.concatenate(
            [[0], np.cumsum(np.asarray(self.vocabs, np.int64))])[:-1]

    @classmethod
    def build(cls, scale: float = 1.0, alpha: float = 1.05,
              num_tables: int = NUM_TABLES,
              num_dense: int = NUM_DENSE) -> "CriteoSpec":
        """Deterministic spec: vocabs log-spaced from ~100*scale to
        ~200k*scale, shuffled by a fixed stride so neighboring columns
        don't ramp monotonically; dims follow vocab rank (the realistic
        big-table-wide-embedding mix)."""
        lo, hi = max(50, int(100 * scale)), max(200, int(200_000 * scale))
        v = np.logspace(np.log10(lo), np.log10(hi), num_tables)
        stride = 11 if num_tables % 11 else 7
        perm = (np.arange(num_tables) * stride) % num_tables
        vocabs = tuple(int(x) for x in v[perm])
        order = np.argsort(np.argsort(vocabs))  # rank of each table
        third = max(1, num_tables // 3)
        dims = tuple(
            32 if r >= num_tables - third else (16 if r >= third else 8)
            for r in order)
        return cls(vocabs=vocabs, dims=dims, alpha=float(alpha),
                   num_dense=num_dense)


def _spec_cdfs(spec: CriteoSpec) -> list:
    return [zipf_cdf(v, spec.alpha) for v in spec.vocabs]


def dlrm_batches(
    num_samples: int,
    batch_size: int = 4096,
    seed: int = 0,
    spec: Optional[CriteoSpec] = None,
    requires_grad: bool = True,
) -> Iterator[PersiaBatch]:
    """Criteo-schema DLRM stream: per-table zipf sign draws, 13 dense
    floats (log1p of positive draws, like the real transform), and a
    recoverable label from fixed hidden per-(table, id) weights + a
    dense linear term."""
    spec = spec or CriteoSpec.build()
    rng = np.random.default_rng([seed, 0xD12])
    cdfs = _spec_cdfs(spec)
    offsets = spec.sign_offsets
    dense_w = hidden_weight(
        np.arange(spec.num_dense, dtype=np.uint64) + _U64(1 << 20),
        np.full(spec.num_dense, 7, np.uint64)) * 0.5
    for batch_id, start in enumerate(range(0, num_samples, batch_size)):
        n = min(batch_size, num_samples - start)
        ids = np.empty((n, spec.num_tables), dtype=np.int64)
        for t in range(spec.num_tables):
            ids[:, t] = zipf_ranks(rng, cdfs[t], n)
        dense = np.log1p(np.abs(rng.normal(
            size=(n, spec.num_dense)))).astype(np.float32)
        logits = np.zeros(n, np.float64)
        for t in range(spec.num_tables):
            logits += hidden_weight(np.full(n, t, np.uint64),
                                    ids[:, t].astype(np.uint64))
        logits /= np.sqrt(spec.num_tables)
        logits += dense.astype(np.float64) @ dense_w
        label = _labels_from_logits(rng, logits, spec.label_noise)
        signs = (ids + offsets[None, :] + 1).astype(np.uint64)
        yield PersiaBatch(
            [IDTypeFeatureWithSingleID(
                CRITEO_SLOT_NAMES[t], np.ascontiguousarray(signs[:, t]))
             for t in range(spec.num_tables)],
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(label.reshape(n, 1))],
            requires_grad=requires_grad,
            batch_id=batch_id,
        )


# --- Criteo-shaped legacy streams (the examples' shared path) ------------

def criteo_uniform_batches(
    num_samples: int,
    batch_size: int = 4096,
    seed: int = 0,
    vocab_per_slot: int = 1 << 20,
    requires_grad: bool = True,
) -> Iterator[PersiaBatch]:
    """Criteo-shaped stream with UNIFORM sign draws and noise labels —
    the shape-only smoke stream (examples/criteo ``synthetic_batches``
    now aliases this; draw order is bit-compatible with the historical
    implementation, so existing goldens hold)."""
    rng = np.random.default_rng(seed)
    for batch_id, start in enumerate(range(0, num_samples, batch_size)):
        n = min(batch_size, num_samples - start)
        signs = rng.integers(1, vocab_per_slot, size=(n, NUM_TABLES),
                             dtype=np.uint64)
        dense = rng.normal(size=(n, NUM_DENSE)).astype(np.float32)
        label = (rng.random((n, 1)) < 0.25).astype(np.float32)
        yield PersiaBatch(
            [IDTypeFeatureWithSingleID(
                CRITEO_SLOT_NAMES[i], np.ascontiguousarray(signs[:, i]))
             for i in range(NUM_TABLES)],
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(label)],
            requires_grad=requires_grad,
            batch_id=batch_id,
        )


def criteo_learnable_batches(
    num_samples: int,
    batch_size: int = 4096,
    seed: int = 0,
    vocab_per_slot: int = 1000,
    noise: float = 0.25,
    requires_grad: bool = True,
) -> Iterator[PersiaBatch]:
    """Criteo-shaped stream with a *recoverable* signal: labels come
    from fixed hidden per-id weights (:func:`hidden_weight` — seed-
    independent) + a dense linear term. Bit-compatible with the
    historical examples/criteo ``learnable_batches`` (same splitmix64
    weights, same draw order), now the examples' shared path."""
    rng = np.random.default_rng(seed)
    hidden = np.random.default_rng(424242)
    dense_w = hidden.normal(0.0, 0.5, size=NUM_DENSE)
    slot_idx = np.arange(NUM_TABLES, dtype=np.uint64)[None, :]
    for batch_id, start in enumerate(range(0, num_samples, batch_size)):
        n = min(batch_size, num_samples - start)
        ids = rng.integers(0, vocab_per_slot, size=(n, NUM_TABLES))
        dense = rng.normal(size=(n, NUM_DENSE)).astype(np.float32)
        logits = hidden_weight(slot_idx, ids).sum(axis=1)
        logits += dense @ dense_w
        std = float(logits.std()) or 1.0  # n==1 tail batch: std is 0
        logits += rng.normal(0.0, noise * std, size=n)
        prob = 1.0 / (1.0 + np.exp(-2.5 * logits / std))
        label = (rng.random(n) < prob).astype(np.float32)[:, None]
        # distinct sign ranges per slot; +1 keeps sign 0 = "missing"
        signs = (ids + np.arange(NUM_TABLES)[None, :] * vocab_per_slot
                 + 1).astype(np.uint64)
        yield PersiaBatch(
            [IDTypeFeatureWithSingleID(
                CRITEO_SLOT_NAMES[i], np.ascontiguousarray(signs[:, i]))
             for i in range(NUM_TABLES)],
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(label)],
            requires_grad=requires_grad,
            batch_id=batch_id,
        )


# --- session / sequence scenario -----------------------------------------

@dataclass(frozen=True)
class SeqRecSpec:
    """Session-traffic shape: an item sign space shared by the ragged
    history slots AND the target slot (one logical item table read
    three ways), small profile vocabs, hidden cluster structure."""

    item_vocab: int = 20_000
    profile_vocabs: Tuple[int, ...] = (500, 64)
    n_clusters: int = 16
    t_hist: int = 20
    last_n: int = 4
    alpha: float = 1.05
    num_dense: int = 4
    dim: int = 16


SEQ_PROFILE_SLOTS = ("user_geo", "user_device")
SEQ_HISTORY_SLOT = "recent_items"
SEQ_CLICKS_SLOT = "recent_clicks"
SEQ_TARGET_SLOT = "target_item"


def seqrec_batches(
    num_samples: int,
    batch_size: int = 512,
    seed: int = 0,
    spec: Optional[SeqRecSpec] = None,
    requires_grad: bool = True,
) -> Iterator[PersiaBatch]:
    """Sessions whose label hides in the HISTORY: every item belongs to
    a hidden cluster (``id % n_clusters`` — opaque to the model, which
    only sees signs); "engaged" sessions draw their history from the
    target item's cluster and click with p=0.85, "browsing" sessions
    draw zipf-at-large and click with p=0.15. Only a model that pools
    per-item embeddings over the ragged history can find the signal —
    the worker-tier mean/last-N pooling path is the only road to it.
    """
    spec = spec or SeqRecSpec()
    rng = np.random.default_rng([seed, 0x5E9])
    cdf = zipf_cdf(spec.item_vocab, spec.alpha)
    nc = spec.n_clusters
    for batch_id, start in enumerate(range(0, num_samples, batch_size)):
        n = min(batch_size, num_samples - start)
        target = zipf_ranks(rng, cdf, n) + 1  # 1-based item ids
        engaged = rng.random(n) < 0.5
        hist = zipf_ranks(rng, cdf, (n, spec.t_hist)) + 1
        # snap engaged histories onto the target's cluster
        same = (hist // nc) * nc + (target % nc)[:, None]
        hist = np.where(engaged[:, None], same, hist)
        np.clip(hist, 1, spec.item_vocab - 1, out=hist)
        lengths = rng.integers(max(2, spec.t_hist // 4),
                               spec.t_hist + 1, size=n)
        label = np.where(engaged, rng.random(n) < 0.85,
                         rng.random(n) < 0.15).astype(np.float32)
        hist_rows = [np.ascontiguousarray(hist[i, :lengths[i]], np.uint64)
                     for i in range(n)]
        # the clicked sub-history: every other item, at least one
        click_rows = [r[::2] if len(r) > 1 else r for r in hist_rows]
        dense = rng.normal(size=(n, spec.num_dense)).astype(np.float32)
        profiles = [
            IDTypeFeatureWithSingleID(
                name,
                (rng.integers(0, pv, size=n)
                 + spec.item_vocab + 1
                 + sum(spec.profile_vocabs[:i])).astype(np.uint64))
            for i, (name, pv) in enumerate(
                zip(SEQ_PROFILE_SLOTS, spec.profile_vocabs))
        ]
        yield PersiaBatch(
            profiles
            + [IDTypeFeature(SEQ_HISTORY_SLOT, hist_rows),
               IDTypeFeature(SEQ_CLICKS_SLOT, click_rows),
               IDTypeFeatureWithSingleID(
                   SEQ_TARGET_SLOT,
                   np.ascontiguousarray(target, np.uint64))],
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(label.reshape(n, 1))],
            requires_grad=requires_grad,
            batch_id=batch_id,
        )


# --- multi-task scenario -------------------------------------------------

@dataclass(frozen=True)
class MultiTaskSpec:
    """Two objectives (click, convert) over ONE shared set of embedding
    tables. The convert logits reuse 60% of the click logits plus their
    own hidden weights, so the tasks are correlated but not identical —
    the regime where a shared bottom genuinely transfers."""

    user_vocab: int = 20_000
    item_vocab: int = 50_000
    ctx_vocabs: Tuple[int, ...] = (100, 30)
    alpha: float = 1.05
    num_dense: int = 6
    dim: int = 16
    label_noise: float = 0.25
    convert_carryover: float = 0.6


MT_TASKS = ("click", "convert")
MT_SLOTS = ("user", "item", "ctx_0", "ctx_1")


def multitask_batches(
    num_samples: int,
    batch_size: int = 1024,
    seed: int = 0,
    spec: Optional[MultiTaskSpec] = None,
    requires_grad: bool = True,
) -> Iterator[PersiaBatch]:
    """Zipf user/item draws; labels land as ONE (batch, 2) array
    (click, convert) so the existing single-Label train plumbing carries
    both objectives unchanged."""
    spec = spec or MultiTaskSpec()
    rng = np.random.default_rng([seed, 0x307])
    u_cdf = zipf_cdf(spec.user_vocab, spec.alpha)
    i_cdf = zipf_cdf(spec.item_vocab, spec.alpha)
    base_item = spec.user_vocab + 1
    base_ctx = base_item + spec.item_vocab
    for batch_id, start in enumerate(range(0, num_samples, batch_size)):
        n = min(batch_size, num_samples - start)
        user = zipf_ranks(rng, u_cdf, n).astype(np.uint64)
        item = zipf_ranks(rng, i_cdf, n).astype(np.uint64)
        ctx = [rng.integers(0, cv, size=n).astype(np.uint64)
               for cv in spec.ctx_vocabs]
        dense = rng.normal(size=(n, spec.num_dense)).astype(np.float32)
        shared = (hidden_weight(np.full(n, 0, np.uint64), user)
                  + hidden_weight(np.full(n, 1, np.uint64), item))
        # the pairwise term is intentionally small: a shared-bottom
        # model cannot memorize (user, item) pairs, so it acts as
        # structured label noise — at 0.5x it bounds click AUC without
        # drowning the learnable per-sign weights
        click_logits = shared + 0.5 * hidden_weight(
            np.full(n, 2, np.uint64), user * _U64(3) + item)
        conv_logits = (spec.convert_carryover * click_logits
                       + hidden_weight(np.full(n, 3, np.uint64), item)
                       + hidden_weight(np.full(n, 4, np.uint64), user))
        label = np.stack(
            [_labels_from_logits(rng, click_logits, spec.label_noise),
             _labels_from_logits(rng, conv_logits, spec.label_noise)],
            axis=1)
        feats = [
            IDTypeFeatureWithSingleID("user", user + _U64(1)),
            IDTypeFeatureWithSingleID("item", item + _U64(base_item)),
        ]
        off = base_ctx
        for i, c in enumerate(ctx):
            feats.append(IDTypeFeatureWithSingleID(
                MT_SLOTS[2 + i], c + _U64(off)))
            off += spec.ctx_vocabs[i]
        yield PersiaBatch(
            feats,
            non_id_type_features=[NonIDTypeFeature(dense)],
            labels=[Label(label)],
            requires_grad=requires_grad,
            batch_id=batch_id,
        )
