"""Fleet control plane: central scrape loop, federated observability,
SLO judgement, and the crash-postmortem flight recorder.

PR 3 gave every service its own sidecar (``/metrics`` ``/healthz``
``/trace`` ``/flight``); nothing watched the *fleet*. This module is
that watcher — the role the reference deployment delegates to
NATS + the k8s operator (PAPER.md layer L7): one process that knows the
live topology, scrapes every sidecar resiliently, and serves a single
federated view:

- ``GET /fleet/metrics`` — every service's exposition merged into one
  document, each series labeled ``service=``/``replica=`` (plus the
  fleet's own synthetic series: ``fleet_target_up``, scrape ages,
  breach counters). One scrape config instead of N.
- ``GET /fleet/status``  — JSON topology: role, addresses, up/ready,
  version (spot replica skew), uptime, last-scrape age per target.
- ``GET /fleet/trace[?trace_id=...]`` — the multi-process Chrome-trace
  merge, scraped live from every up target (the library form of what
  ``bench.py --mode trace`` prototyped).
- ``GET /fleet/alerts`` — the SLO engine's judgement
  (:mod:`persia_tpu.slos`): every rule, per service, with firing state.
- ``GET /fleet/breaches`` — the bounded breach-event log.
- ``GET /fleet/variants`` — the serving tier's variant topology merged
  per variant (fleet-wide request totals, weight/status/default skew
  detection — a half-landed variant_admin broadcast shows up here).
- ``GET /fleet/history`` — the bounded in-memory history ring: every
  scraped series' recent ``(t, value)`` points with window aggregates
  (avg/min/max/rate + per-service breakdown) — the evidence surface
  the autopilot decides on (:mod:`persia_tpu.autopilot`).

**Resilience contract**: scraping is PULL-ONLY (a fleet monitor that is
absent, down, or slow changes nothing about the services — no new wire
bytes on the RPC envelope), and one dead or hung sidecar marks that
target down instead of wedging the loop: every HTTP read carries a
socket-level timeout, targets are scraped concurrently, and a target
that exceeds its deadline is judged down this round while the others
proceed.

**Flight recorder**: the monitor (and the PR-4 supervisor in
``service/helper.py``) polls each target's ``/flight`` snapshot and
keeps a bounded ring per service; on a crash, an injected fault, or an
SLO breach, :class:`FlightRecorder.capture` writes a postmortem bundle
— trace (remote parents resolved), final health doc, last metrics
exposition, armed fault rules, environment — turning a SIGKILLed
replica into an artifact instead of archaeology.

Run: ``python -m persia_tpu.fleet --coordinator 127.0.0.1:23333
--port 9090 [--slo-rules rules.yml] [--postmortem-dir ./postmortems]``
"""

import argparse
import itertools
import json
import os
import re
import threading
import time
import urllib.request
from collections import deque
from concurrent.futures import ThreadPoolExecutor, wait
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from persia_tpu import knobs
from persia_tpu import tracing
from persia_tpu.logger import get_default_logger
from persia_tpu.metrics import MetricsRegistry, parse_exposition
from persia_tpu.service_discovery import get_fleet_targets
from persia_tpu.slos import SloEngine, load_rules
from persia_tpu.version import __version__

_logger = get_default_logger(__name__)


def _http_get(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


class ScrapeTarget:
    """One sidecar under watch, with its last-known observable state."""

    def __init__(self, service: str, http_addr: str, role: str = "static",
                 replica: int = 0, rpc_addr: Optional[str] = None):
        self.service = service
        self.http_addr = http_addr
        self.role = role
        self.replica = replica
        self.rpc_addr = rpc_addr
        self.up = False
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None
        self.last_scrape_t: Optional[float] = None  # monotonic, success
        self.last_attempt_t: Optional[float] = None
        self.last_health: Dict = {}
        self.last_samples: List = []
        self.last_families: Dict = {}
        self.last_flight_t: Optional[float] = None

    def status_doc(self, now: float) -> Dict:
        h = self.last_health
        return {
            "service": self.service,
            "role": self.role,
            "replica": self.replica,
            "rpc_addr": self.rpc_addr or h.get("rpc_addr"),
            "http_addr": self.http_addr,
            "up": self.up,
            "ready": h.get("ready"),
            "version": h.get("version"),
            "uptime_sec": h.get("uptime_sec"),
            "pid": h.get("pid"),
            "health_status": h.get("status"),
            # tier-ladder observables (PS replicas only; None elsewhere):
            # which rung rows occupy and how the write-back/update
            # version stream is advancing
            "update_version": h.get("update_version"),
            "spill": h.get("spill"),
            # elastic-tier observables: the replica's published routing
            # epoch and, mid-migration, its donor capture/freeze state
            "routing_epoch": h.get("routing_epoch"),
            "reshard": h.get("reshard"),
            # kernel-path + dispatch observables (PS replicas): which
            # SIMD path the native store selected and how requests are
            # parallelized — fleet_status cross-checks these so one
            # replica silently running scalar kernels is flagged
            "simd": h.get("simd"),
            "dispatch": h.get("dispatch"),
            # multi-process trainer observables (trainer rows only;
            # None elsewhere): which group member this row is, the
            # group size, and the jax mesh shape it rendezvoused —
            # fleet_status cross-checks mesh/version agreement across
            # the group (trainer_*_skew)
            "process_index": h.get("process_index"),
            "process_count": h.get("process_count"),
            "mesh_shape": h.get("mesh_shape"),
            "last_scrape_age_sec": (
                round(now - self.last_scrape_t, 3)
                if self.last_scrape_t is not None else None),
            "last_attempt_age_sec": (
                round(now - self.last_attempt_t, 3)
                if self.last_attempt_t is not None else None),
            "consecutive_failures": self.consecutive_failures,
            "last_error": self.last_error,
        }


class FlightRecorder:
    """Bounded ring of ``/flight`` snapshots per service + the bundle
    writer. ``observe`` is fed by whoever polls the sidecars (fleet
    monitor, PS supervisor); ``capture`` turns the last snapshot into a
    postmortem directory."""

    def __init__(self, out_dir: str, per_service: int = 4):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self._rings: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self.captures: List[str] = []
        self._per_service = per_service

    def observe(self, service: str, flight_doc: Dict):
        with self._lock:
            ring = self._rings.setdefault(
                service, deque(maxlen=self._per_service))
            ring.append(flight_doc)

    def last(self, service: str) -> Optional[Dict]:
        with self._lock:
            ring = self._rings.get(service)
            return ring[-1] if ring else None

    def capture(self, service: str, reason: str,
                extra: Optional[Dict] = None) -> Optional[str]:
        """Write a postmortem bundle from the last observed snapshot of
        ``service``. Returns the bundle directory, or None when the
        service was never observed (nothing to save beats a misleading
        empty bundle)."""
        doc = self.last(service)
        if doc is None:
            _logger.warning("no flight snapshot for %s — skipping "
                            "postmortem capture (%s)", service, reason)
            return None
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", service)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = os.path.join(
            self.out_dir,
            f"postmortem_{safe}_{stamp}_{next(self._seq)}")
        os.makedirs(path, exist_ok=True)
        spans = tracing.promote_remote_parents(
            tracing.as_span_dicts(doc.get("spans", [])))
        trace_doc = tracing.chrome_trace(spans)
        trace_doc["otherData"] = {
            "spans_dropped_total": doc.get("spans_dropped_total", 0),
            "service": service,
            "reason": reason,
        }
        manifest = {
            "service": service,
            "reason": reason,
            "captured_at": time.time(),
            "observed_at": doc.get("t_wall"),
            "version": doc.get("version"),
            "pid": doc.get("pid"),
            "extra": extra or {},
        }
        for name, payload in (
                ("flight.json", doc),
                ("health.json", doc.get("health", {})),
                ("trace.json", trace_doc),
                ("faults.json", doc.get("faults", [])),
                ("env.json", doc.get("env", {})),
                ("reason.json", manifest)):
            with open(os.path.join(path, name), "w") as f:
                json.dump(payload, f, indent=1)
        with open(os.path.join(path, "metrics.prom"), "w") as f:
            f.write(doc.get("metrics", ""))
        with self._lock:
            self.captures.append(path)
        _logger.warning("postmortem bundle for %s (%s) -> %s",
                        service, reason, path)
        return path


class FleetHistory:
    """Bounded in-memory ring over every scraped metric: per-series
    ``(t, value)`` points with time-window retention
    (``PERSIA_FLEET_HISTORY_SEC``) and a per-series point cap
    (``PERSIA_FLEET_HISTORY_POINTS``). Series are keyed
    ``(service, metric, labels)``; duplicate series within one scrape
    sum, same as the SLO engine's ingestion.

    This is the substrate instantaneous scrapes cannot provide:
    ``avg/min/max/rate_over(window)`` for capacity questions,
    per-service ``breakdown`` for imbalance questions, and bounded
    ``excerpt`` slices for autopilot decision evidence and
    ``GET /fleet/history``. Pull-only by construction — it only ever
    observes what the scrape loop already fetched."""

    def __init__(self, keep_sec: Optional[float] = None,
                 max_points: Optional[int] = None):
        self.keep_sec = float(keep_sec if keep_sec is not None
                              else knobs.get("PERSIA_FLEET_HISTORY_SEC"))
        self.max_points = int(max_points if max_points is not None
                              else knobs.get(
                                  "PERSIA_FLEET_HISTORY_POINTS"))
        self._lock = threading.Lock()
        # (service, metric, labels_tuple) -> deque[(t, value)]
        self._series: Dict[tuple, deque] = {}

    def record(self, service: str, samples, t: Optional[float] = None):
        """Feed one scrape's parsed samples (``parse_exposition``
        output, or any iterable of ``(name, labels, value)``)."""
        t = time.monotonic() if t is None else t
        acc: Dict[tuple, float] = {}
        for name, labels, value in samples:
            key = (service, name, tuple(sorted(labels.items())))
            acc[key] = acc.get(key, 0.0) + value
        horizon = t - self.keep_sec
        with self._lock:
            for key, v in acc.items():
                dq = self._series.setdefault(
                    key, deque(maxlen=self.max_points))
                dq.append((t, v))
                while dq and dq[0][0] < horizon:
                    dq.popleft()

    def record_up(self, service: str, up: bool,
                  t: Optional[float] = None):
        """The synthetic liveness series, recorded every round whether
        the scrape succeeded or not (a down target still moves its
        history)."""
        self.record(service, [("up", {}, 1.0 if up else 0.0)], t=t)

    # --- queries ---------------------------------------------------------

    def _windowed(self, metric: str, window_sec: float,
                  service: Optional[str] = None,
                  now: Optional[float] = None) -> Dict[tuple, list]:
        """``{(service, labels): [(t, v), ...]}`` restricted to the
        window; ``service`` is a regex (same contract as SloRule)."""
        now = time.monotonic() if now is None else now
        svc_re = re.compile(service) if service else None
        out: Dict[tuple, list] = {}
        with self._lock:
            for (svc, name, lbl), dq in self._series.items():
                if name != metric:
                    continue
                if svc_re is not None and not svc_re.search(svc):
                    continue
                pts = [(t, v) for t, v in dq if t >= now - window_sec]
                if pts:
                    out[(svc, lbl)] = pts
        return out

    @staticmethod
    def _series_rate(pts) -> float:
        """Counter-reset-aware per-second rate over one series' window
        points (a restart counts from zero, not negative)."""
        if len(pts) < 2:
            return 0.0
        inc = 0.0
        for (_, prev), (_, cur) in zip(pts, pts[1:]):
            inc += cur - prev if cur >= prev else cur
        dt = pts[-1][0] - pts[0][0]
        return inc / dt if dt > 0 else 0.0

    def _agg(self, metric: str, window_sec: float, fn: str,
             service: Optional[str] = None,
             now: Optional[float] = None) -> Optional[float]:
        per = self._windowed(metric, window_sec, service, now)
        if not per:
            return None
        vals = []
        for pts in per.values():
            ys = [v for _, v in pts]
            if fn == "avg":
                vals.append(sum(ys) / len(ys))
            elif fn == "min":
                vals.append(min(ys))
            elif fn == "max":
                vals.append(max(ys))
            elif fn == "rate":
                vals.append(self._series_rate(pts))
        # summed across series: the same aggregation the SLO engine
        # applies, so history answers and rule answers agree
        return sum(vals)

    def avg_over(self, metric, window_sec, service=None, now=None):
        return self._agg(metric, window_sec, "avg", service, now)

    def min_over(self, metric, window_sec, service=None, now=None):
        return self._agg(metric, window_sec, "min", service, now)

    def max_over(self, metric, window_sec, service=None, now=None):
        return self._agg(metric, window_sec, "max", service, now)

    def rate_over(self, metric, window_sec, service=None, now=None):
        return self._agg(metric, window_sec, "rate", service, now)

    def breakdown(self, metric: str, window_sec: float,
                  agg: str = "avg", service: Optional[str] = None,
                  now: Optional[float] = None) -> Dict[str, float]:
        """Per-service decomposition of an aggregate — the imbalance
        view ('which replica carries the load'). Returns
        ``{service: value}`` with each service's series summed."""
        per = self._windowed(metric, window_sec, service, now)
        out: Dict[str, float] = {}
        for (svc, _lbl), pts in per.items():
            ys = [v for _, v in pts]
            if agg == "avg":
                v = sum(ys) / len(ys)
            elif agg == "min":
                v = min(ys)
            elif agg == "max":
                v = max(ys)
            elif agg == "rate":
                v = self._series_rate(pts)
            else:
                raise ValueError(f"bad agg {agg!r}")
            out[svc] = out.get(svc, 0.0) + v
        return out

    def excerpt(self, metric: Optional[str] = None,
                window_sec: float = 60.0,
                service: Optional[str] = None,
                points: int = 32,
                now: Optional[float] = None) -> List[Dict]:
        """Bounded raw slices for evidence bundles and the HTTP view:
        one entry per matching series, each with at most ``points``
        stride-downsampled points (newest kept exactly)."""
        now = time.monotonic() if now is None else now
        if metric is None:
            with self._lock:
                names = sorted({k[1] for k in self._series})
            return [{"metric": n} for n in names]
        per = self._windowed(metric, window_sec, service, now)
        out = []
        for (svc, lbl) in sorted(per):
            pts = per[(svc, lbl)]
            if len(pts) > points:
                stride = len(pts) / points
                pts = [pts[min(int(i * stride), len(pts) - 1)]
                       for i in range(points - 1)] + [pts[-1]]
            out.append({
                "service": svc, "metric": metric, "labels": dict(lbl),
                "points": [[round(now - t, 3), v] for t, v in pts],
            })
        return out

    def stats(self) -> Dict:
        with self._lock:
            return {"n_series": len(self._series),
                    "n_points": sum(len(d)
                                    for d in self._series.values()),
                    "keep_sec": self.keep_sec,
                    "max_points_per_series": self.max_points}


class FleetMonitor:
    """The scrape loop + federation + SLO wiring.

    Targets come from an explicit list, a static spec, and/or a
    coordinator (rediscovered periodically, so restarted replicas with
    new ports are picked up). ``start()`` runs the loop on a daemon
    thread; embedders (tests, the bench) may instead call
    :meth:`scrape_once` synchronously.
    """

    def __init__(self,
                 targets: Optional[List[Dict]] = None,
                 coordinator_addr: Optional[str] = None,
                 static_targets: Optional[str] = None,
                 scrape_interval: float = 5.0,
                 scrape_timeout: float = 2.0,
                 flight_interval: float = 10.0,
                 rediscover_interval: float = 10.0,
                 slo_engine: Optional[SloEngine] = None,
                 postmortem_dir: Optional[str] = None,
                 capture_on_breach: bool = True,
                 first_scrape_delay: float = 0.0):
        self.coordinator_addr = coordinator_addr
        self.static_targets = static_targets
        self.scrape_interval = float(scrape_interval)
        self.scrape_timeout = float(scrape_timeout)
        self.flight_interval = float(flight_interval)
        self.rediscover_interval = float(rediscover_interval)
        # 0 = scrape immediately on start (fast first picture); the
        # bench's paired A/B sets one interval so every measured block
        # carries exactly the configured scrape duty cycle
        self.first_scrape_delay = float(first_scrape_delay)
        self._targets: Dict[str, ScrapeTarget] = {}
        self._targets_lock = threading.Lock()
        self.recorder = (FlightRecorder(postmortem_dir)
                         if postmortem_dir else None)
        self.capture_on_breach = capture_on_breach and (
            self.recorder is not None)
        self.engine = slo_engine if slo_engine is not None else SloEngine()
        # chain, don't clobber: an embedder may have its own callback
        self._user_on_breach = self.engine.on_breach
        self.engine.on_breach = self._on_breach
        # fleet-own metrics live in a PRIVATE registry: embedding a
        # monitor in a bench/test process must not leak fleet series
        # into that process's service exposition
        self.registry = MetricsRegistry()
        self._m_rounds = self.registry.counter(
            "fleet_scrape_rounds_total",
            help_text="completed scrape rounds")
        self._m_failures = self.registry.counter(
            "fleet_scrape_failures_total",
            help_text="individual target scrape failures")
        self._m_breaches = self.registry.counter(
            "fleet_slo_breaches_total",
            help_text="SLO firing transitions observed")
        self._t_round = self.registry.histogram(
            "fleet_scrape_round_sec",
            help_text="wall time of one full scrape round — a wedged "
                      "or slow sidecar shows up here before it pages")
        # bounded per-series history over everything scraped: the
        # substrate for /fleet/history, autopilot evidence excerpts,
        # and hysteresis questions instantaneous scrapes cannot answer
        self.history = FleetHistory()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_discover = 0.0
        self._t0 = time.monotonic()
        self.rounds = 0
        if targets:
            self._merge_targets(targets)
        # discovery only runs for sources the CALLER named: a monitor
        # built with an explicit target list must not silently absorb
        # ambient PERSIA_FLEET_TARGETS / PERSIA_COORDINATOR_ADDR env
        # (the binary's main() resolves those env defaults explicitly)
        if self.coordinator_addr or self.static_targets:
            self.discover()

    # --- target management ----------------------------------------------

    def _merge_targets(self, dicts: List[Dict]):
        with self._targets_lock:
            for d in dicts:
                t = self._targets.get(d["service"])
                if t is None:
                    self._targets[d["service"]] = ScrapeTarget(
                        d["service"], d["http_addr"],
                        role=d.get("role", "static"),
                        replica=d.get("replica", 0),
                        rpc_addr=d.get("rpc_addr"))
                elif t.http_addr != d["http_addr"]:
                    # same service, new sidecar address: a restarted
                    # replica — repoint, reset the failure streak
                    t.http_addr = d["http_addr"]
                    t.rpc_addr = d.get("rpc_addr", t.rpc_addr)
                    t.consecutive_failures = 0

    def discover(self):
        """Refresh the target set from the coordinator/static spec.
        Discovery failures are non-fatal: the monitor keeps scraping
        what it already knows."""
        self._last_discover = time.monotonic()
        try:
            # empty strings (not None) pin get_fleet_targets to the
            # caller-named sources — no env-var fallback in the library
            found = get_fleet_targets(self.coordinator_addr or "",
                                      static=self.static_targets or "")
        except Exception as e:
            _logger.warning("fleet discovery failed: %s", e)
            return
        if found:
            self._merge_targets(found)

    def targets(self) -> List[ScrapeTarget]:
        with self._targets_lock:
            return sorted(self._targets.values(),
                          key=lambda t: t.service)

    def add_target(self, service: str, http_addr: str, **kw):
        self._merge_targets([{"service": service, "http_addr": http_addr,
                              **kw}])

    # --- scraping --------------------------------------------------------

    def _scrape_one(self, t: ScrapeTarget, fetch_flight: bool) -> Dict:
        base = f"http://{t.http_addr}"
        metrics_text = _http_get(
            f"{base}/metrics", self.scrape_timeout).decode()
        samples, families = parse_exposition(metrics_text)
        health = json.loads(_http_get(
            f"{base}/healthz", self.scrape_timeout).decode())
        out = {"samples": samples, "families": families, "health": health}
        if fetch_flight and self.recorder is not None:
            # a flight hiccup is not a liveness failure (same rule as
            # the PS supervisor): /flight is the heavy GET — spans ride
            # along — and a busy target whose snapshot runs past the
            # timeout must not read as DOWN while /metrics + /healthz
            # answered fine
            try:
                out["flight"] = json.loads(_http_get(
                    f"{base}/flight", self.scrape_timeout).decode())
            except Exception as e:
                _logger.debug("flight fetch of %s failed: %s",
                              t.service, e)
        return out

    def scrape_once(self) -> int:
        """One full round over every known target; returns the number of
        up targets. Per-target failures (timeout, connection refused,
        garbage output, death mid-scrape) mark that target down and
        never abort the round."""
        now = time.monotonic()
        if (self.coordinator_addr or self.static_targets) and (
                now - self._last_discover >= self.rediscover_interval):
            self.discover()
        targets = self.targets()
        # lazy pool init under the lock: scrape_once is public API, and
        # two overlapping first rounds (background loop + a caller-
        # driven round) racing the None check would each build a pool —
        # one of them orphaned with live worker threads, never shut down
        with self._targets_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=min(16, max(4, len(targets) or 1)),
                    thread_name_prefix="fleet-scrape")
        t_round0 = time.perf_counter()
        futs = {}
        for t in targets:
            fetch_flight = (
                self.recorder is not None
                and (t.last_flight_t is None
                     or now - t.last_flight_t >= self.flight_interval))
            t.last_attempt_t = now
            futs[self._pool.submit(self._scrape_one, t, fetch_flight)] = (
                t, fetch_flight)
        # the socket timeout bounds each GET; this deadline is the
        # belt-and-braces backstop so a pathological target cannot hold
        # the ROUND open either
        done, not_done = wait(futs, timeout=self.scrape_timeout * 3 + 1)
        n_up = 0
        for fut, (t, _fetched) in futs.items():
            if fut in not_done or fut.exception() is not None:
                err = ("scrape deadline exceeded" if fut in not_done
                       else repr(fut.exception()))
                self._target_down(t, err)
                continue
            res = fut.result()
            t.up = True
            n_up += 1
            t.consecutive_failures = 0
            t.last_error = None
            t.last_scrape_t = time.monotonic()
            t.last_samples = res["samples"]
            t.last_families = res["families"]
            t.last_health = res["health"]
            if res.get("flight") is not None:
                t.last_flight_t = now
                self.recorder.observe(t.service, res["flight"])
            self.engine.ingest(t.service, res["samples"])
            self.history.record(t.service, res["samples"])
        # liveness moves every round for every target — a down target
        # still advances its history (the autopilot's "is it back" view)
        for t in targets:
            self.history.record_up(t.service, t.up)
        self.engine.evaluate()
        self._m_rounds.inc()
        # under the targets lock: scrape_once is public API — the
        # background loop and a caller-driven round (tests, the CLI
        # --check gate) may overlap, and an unguarded += here is the
        # lost-increment shape persialint's lock pass flags
        with self._targets_lock:
            self.rounds += 1
        self._t_round.observe(time.perf_counter() - t_round0)
        return n_up

    def _target_down(self, t: ScrapeTarget, err: str):
        t.up = False
        t.consecutive_failures += 1
        t.last_error = err
        self._m_failures.inc()
        self.engine.mark_down(t.service)
        _logger.warning("fleet: target %s (%s) down: %s",
                        t.service, t.http_addr, err)

    def _on_breach(self, alert: Dict):
        self._m_breaches.inc()
        if self.capture_on_breach and alert["service"] != "fleet":
            try:
                self.recorder.capture(alert["service"],
                                      f"slo:{alert['rule']}",
                                      extra=alert)
            except Exception:
                _logger.exception("breach postmortem capture failed")
        if self._user_on_breach is not None:
            self._user_on_breach(alert)

    # --- loop ------------------------------------------------------------

    def start(self) -> "FleetMonitor":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-monitor")
        self._thread.start()
        return self

    def _run(self):
        if self.first_scrape_delay and self._stop.wait(
                self.first_scrape_delay):
            return
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.scrape_once()
            except Exception:
                _logger.exception("fleet scrape round failed")
            elapsed = time.monotonic() - t0
            self._stop.wait(max(self.scrape_interval - elapsed, 0.05))

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._targets_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)  # start() after stop(): fresh pool

    # --- federated views -------------------------------------------------

    def fleet_metrics(self) -> str:
        """One exposition document for the whole fleet: every up
        target's families (``# TYPE``/``# HELP`` deduped across
        services) with ``service``/``replica`` labels injected, then
        the monitor's own synthetic series."""
        from persia_tpu.metrics import _fmt

        now = time.monotonic()
        lines: List[str] = []
        seen_families = set()
        for t in self.targets():
            if not t.up:
                continue
            extra = {"service": t.service, "replica": str(t.replica)}
            pending_family: Optional[str] = None
            for name, labels, value in t.last_samples:
                family = re.sub(r"_(bucket|sum|count)$", "", name)
                if family != pending_family:
                    pending_family = family
                    if family not in seen_families:
                        seen_families.add(family)
                        fam = (t.last_families.get(family)
                               or t.last_families.get(name) or {})
                        if fam.get("help"):
                            lines.append(
                                f"# HELP {family} {fam['help']}")
                        if fam.get("type"):
                            lines.append(
                                f"# TYPE {family} {fam['type']}")
                merged = {**labels, **extra}
                lines.append(f"{name}{_fmt(merged)} {value}")
        # synthetic per-target series
        lines.append("# TYPE fleet_target_up gauge")
        for t in self.targets():
            lbl = _fmt({"service": t.service, "replica": str(t.replica),
                        "role": t.role})
            lines.append(f"fleet_target_up{lbl} {1.0 if t.up else 0.0}")
        lines.append("# TYPE fleet_target_last_scrape_age_sec gauge")
        for t in self.targets():
            if t.last_scrape_t is None:
                continue
            lbl = _fmt({"service": t.service, "replica": str(t.replica)})
            lines.append(f"fleet_target_last_scrape_age_sec{lbl} "
                         f"{round(now - t.last_scrape_t, 3)}")
        own = self.registry.render()
        return "\n".join(lines) + "\n" + own

    def fleet_status(self) -> Dict:
        now = time.monotonic()
        targets = [t.status_doc(now) for t in self.targets()]
        versions = {t["version"] for t in targets if t["version"]}
        # kernel-path skew, same shape as version_skew: PS replicas
        # reporting different SIMD paths (one fell back to scalar —
        # env forced down, wrong .so, heterogeneous hosts) serve
        # bit-identical results but at silently different cost, which
        # capacity planning must see
        simd_paths = {t["simd"] for t in targets if t.get("simd")}
        # trainer-group skew, same shape as simd_skew: the rows of a
        # multi-process trainer group must agree on package version
        # (mixed rollout mid-job = divergent step functions) and mesh
        # shape (a member that rendezvoused a different mesh cannot be
        # in the same collective) — either is a co-scheduling bug the
        # fleet view must flag before the collectives deadlock
        trainers = [t for t in targets
                    if t.get("process_index") is not None]
        trainer_versions = {t["version"] for t in trainers
                            if t.get("version")}
        trainer_meshes = {t["mesh_shape"] for t in trainers
                          if t.get("mesh_shape")}
        return {
            "fleet_monitor": {
                "version": __version__,
                "pid": os.getpid(),
                "uptime_sec": round(now - self._t0, 3),
                "scrape_interval_sec": self.scrape_interval,
                "rounds": self.rounds,
            },
            "n_targets": len(targets),
            "n_up": sum(1 for t in targets if t["up"]),
            "version_skew": len(versions) > 1,
            "simd_skew": len(simd_paths) > 1,
            "simd_paths": sorted(simd_paths),
            "n_trainer_processes": len(trainers),
            "trainer_version_skew": len(trainer_versions) > 1,
            "trainer_mesh_skew": len(trainer_meshes) > 1,
            "trainer_mesh_shapes": sorted(trainer_meshes),
            "targets": targets,
        }

    def fleet_trace(self, trace_id: Optional[str] = None,
                    n: int = 8192, fmt: str = "chrome") -> Dict:
        """Live multi-process trace merge: pull ``/trace?format=raw``
        from every up target, merge, resolve cross-capture parentage.
        ``trace_id`` (hex) filters to one logical operation."""
        groups = []
        dropped = 0
        for t in self.targets():
            if not t.up:
                continue
            try:
                doc = json.loads(_http_get(
                    f"http://{t.http_addr}/trace?n={n}&format=raw",
                    self.scrape_timeout).decode())
            except Exception as e:
                _logger.warning("fleet trace scrape of %s failed: %s",
                                t.service, e)
                continue
            dropped += doc.get("dropped_total", 0) \
                if isinstance(doc, dict) else 0
            groups.append(doc)
        merged = tracing.merge_span_dicts(groups, trace_id=trace_id)
        merged = tracing.promote_remote_parents(merged)
        if fmt == "raw":
            return {"spans": merged, "dropped_total": dropped}
        doc = tracing.chrome_trace(merged)
        doc["otherData"] = {"spans_dropped_total": dropped,
                            "n_spans": len(merged)}
        return doc

    def fleet_hotness(self, hbm_bytes: Optional[int] = None,
                      num_replicas: Optional[int] = None,
                      measured_hit_rate: Optional[float] = None) -> Dict:
        """Cross-shard workload-hotness merge: pull every up target's
        ``/hotness?full=1`` snapshot (disabled/absent targets
        contribute nothing), merge them exactly — totals equal the sum
        of per-shard snapshots, Space-Saving counts add, count-min
        cells add, HLL registers max — then render per-table zipfian
        fits, coverage curves ("top p% of rows serve q% of lookups"),
        and, when an HBM budget is named, the frequency-admission
        capacity plan for the device-cache tier ladder (ROADMAP item
        2). Pull-only like every other fleet view: zero requests on
        the RPC plane."""
        from persia_tpu import hotness as _hotness

        snaps, scraped = self._hotness_snaps()
        merged = _hotness.merge_snapshots(snaps)
        report = _hotness.fleet_report(merged, hbm_bytes=hbm_bytes,
                                       num_replicas=num_replicas,
                                       measured_hit_rate=measured_hit_rate)
        report["sources"] = scraped
        return report

    def _hotness_snaps(self):
        """Pull every up target's full hotness snapshot (disabled or
        absent targets contribute nothing)."""
        snaps = []
        scraped = []
        for t in self.targets():
            if not t.up:
                continue
            try:
                doc = json.loads(_http_get(
                    f"http://{t.http_addr}/hotness?full=1",
                    self.scrape_timeout).decode())
            except Exception as e:
                _logger.debug("fleet hotness scrape of %s failed: %s",
                              t.service, e)
                continue
            if doc.get("enabled"):
                snaps.append(doc)
                scraped.append({"service": t.service,
                                "total": int(doc.get("total", 0))})
        return snaps, scraped

    def hotness_plan(self, num_replicas: int,
                     num_slots: Optional[int] = None,
                     current_table=None) -> Dict:
        """Hotness-balanced placement plan against the LIVE merged
        sketches — what the autopilot's rebalance policy and the
        operator's reshard driver size moves from. ``current_table``
        pins slot count and enables moved-slot minimization; without
        it the plan assumes a fresh hash-even layout. Pull-only like
        every other fleet view."""
        from persia_tpu import hotness as _hotness

        snaps, _ = self._hotness_snaps()
        merged = _hotness.merge_snapshots(snaps)
        return _hotness.placement_plan(merged, num_replicas,
                                       num_slots=num_slots,
                                       current_table=current_table)

    def fleet_history(self, metric: Optional[str] = None,
                      service: Optional[str] = None,
                      window_sec: float = 60.0,
                      points: int = 32) -> Dict:
        """The history ring's HTTP view: without ``metric``, the series
        inventory + ring stats; with one, bounded per-series excerpts
        plus the window aggregates (avg/min/max/rate + per-service
        breakdown) so operators and CI read the same numbers the
        autopilot decides on."""
        doc = {"stats": self.history.stats(), "window_sec": window_sec}
        if metric is None:
            doc["metrics"] = [e["metric"]
                              for e in self.history.excerpt()]
            return doc
        now = time.monotonic()
        doc.update({
            "metric": metric,
            "service": service,
            "avg": self.history.avg_over(metric, window_sec, service,
                                         now),
            "min": self.history.min_over(metric, window_sec, service,
                                         now),
            "max": self.history.max_over(metric, window_sec, service,
                                         now),
            "rate": self.history.rate_over(metric, window_sec, service,
                                           now),
            "breakdown": self.history.breakdown(metric, window_sec,
                                                "avg", service, now),
            "series": self.history.excerpt(metric, window_sec, service,
                                           points, now),
        })
        return doc

    def fleet_routing(self) -> Dict:
        """The elastic tier's control-plane view: every target's
        published routing epoch, the fleet-wide min/max (a skew means a
        cutover is mid-publish or a replica missed it), and any
        in-flight donor migration state — the operator's one-stop
        'is the reshard done / stuck' document."""
        now = time.monotonic()
        targets = []
        epochs = []
        migrating = []
        for t in self.targets():
            h = t.last_health or {}
            ep = h.get("routing_epoch")
            doc = {
                "service": t.service,
                "role": t.role,
                "up": t.up,
                "routing_epoch": ep,
                "reshard": h.get("reshard"),
                "last_scrape_age_sec": (
                    round(now - t.last_scrape_t, 3)
                    if t.last_scrape_t is not None else None),
            }
            targets.append(doc)
            if t.up and ep is not None:
                epochs.append(int(ep))
            if t.up and h.get("reshard"):
                # up-gated like the epoch aggregation: a donor that
                # died mid-migration keeps its stale health doc, and a
                # forever-"migrating" ghost would block the runbook's
                # no-concurrent-reshard precondition
                migrating.append(t.service)
        # donors whose moving slots are write-frozen, with the age the
        # reshard_frozen_slot_stuck rule alarms on — the operator's
        # shortlist when deciding between resume() and abort (the
        # DEPLOY.md wedged-migration runbook keys on this field)
        frozen_donors = [
            {"service": d["service"],
             "frozen_age_sec": d["reshard"].get("frozen_age_sec"),
             "pending_epoch": d["reshard"].get("pending_epoch"),
             "mig_id": d["reshard"].get("mig_id")}
            for d in targets
            if d["up"] and d["reshard"] and d["reshard"].get("frozen")
        ]
        return {
            "epoch_min": min(epochs) if epochs else None,
            "epoch_max": max(epochs) if epochs else None,
            "epoch_skew": bool(epochs) and min(epochs) != max(epochs),
            "migrating": migrating,
            "frozen_donors": frozen_donors,
            "targets": targets,
        }

    def fleet_variants(self) -> Dict:
        """The multi-variant serving tier's control-plane view: every
        serving replica's variant topology (ridden on its health doc),
        merged per variant name with fleet-wide request totals —
        plus skew detection: replicas disagreeing on a variant's
        weight, status, or the default marker means a variant_admin
        broadcast only half-landed (the operator's re-push signal,
        like /fleet/routing's epoch_skew)."""
        per_variant: Dict[str, Dict] = {}
        replicas = []
        for t in self.targets():
            h = t.last_health or {}
            variants = h.get("variants")
            if variants is None:
                continue
            replicas.append({"service": t.service, "up": t.up,
                             "variants": [v["name"] for v in variants],
                             "default": next(
                                 (v["name"] for v in variants
                                  if v.get("default")), None)})
            if not t.up:
                continue
            for v in variants:
                agg = per_variant.setdefault(v["name"], {
                    "name": v["name"], "replicas": 0, "requests": 0,
                    "degraded": 0, "weights": set(), "statuses": set(),
                    "default_on": 0})
                agg["replicas"] += 1
                agg["requests"] += int(v.get("requests", 0))
                agg["degraded"] += int(v.get("degraded", 0))
                agg["weights"].add(float(v.get("weight", 0.0)))
                agg["statuses"].add(v.get("status", "live"))
                agg["default_on"] += 1 if v.get("default") else 0
        out = []
        skew = False
        n_serving = sum(1 for r in replicas if r["up"])
        for name in sorted(per_variant):
            agg = per_variant[name]
            v_skew = (len(agg["weights"]) > 1
                      or len(agg["statuses"]) > 1
                      or agg["replicas"] != n_serving
                      or agg["default_on"] not in (0, agg["replicas"]))
            skew = skew or v_skew
            out.append({
                "name": name,
                "replicas": agg["replicas"],
                "requests": agg["requests"],
                "degraded": agg["degraded"],
                "weight": (sorted(agg["weights"])
                           if len(agg["weights"]) > 1
                           else next(iter(agg["weights"]))),
                "status": sorted(agg["statuses"]),
                "default": agg["default_on"] > 0,
                "skew": v_skew,
            })
        return {"variants": out, "skew": skew,
                "serving_replicas": replicas}

    def alerts(self, firing_only: bool = False) -> List[Dict]:
        return self.engine.alerts(firing_only=firing_only)

    # --- HTTP surface ----------------------------------------------------

    def serve_http(self, host: str = "127.0.0.1",
                   port: int = 0) -> "FleetHttpServer":
        return FleetHttpServer(self, host, port).start()


class FleetHttpServer:
    """HTTP front for one :class:`FleetMonitor` (same dependency-free
    http.server arrangement as the per-service sidecar)."""

    def __init__(self, monitor: FleetMonitor, host: str = "127.0.0.1",
                 port: int = 0):
        self.monitor = monitor
        mon = monitor

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: D102
                pass

            def do_GET(self):  # noqa: N802
                try:
                    url = urlparse(self.path)
                    q = parse_qs(url.query)
                    ctype = "application/json"
                    if url.path == "/fleet/metrics":
                        body = mon.fleet_metrics().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif url.path == "/fleet/status":
                        body = json.dumps(mon.fleet_status()).encode()
                    elif url.path == "/fleet/trace":
                        body = json.dumps(mon.fleet_trace(
                            trace_id=q.get("trace_id", [None])[0],
                            n=int(q.get("n", ["8192"])[0]),
                            fmt=q.get("format", ["chrome"])[0],
                        )).encode()
                    elif url.path == "/fleet/alerts":
                        firing = q.get("firing", ["0"])[0] not in ("", "0")
                        body = json.dumps(
                            mon.alerts(firing_only=firing)).encode()
                    elif url.path == "/fleet/breaches":
                        body = json.dumps(
                            mon.engine.breach_events()).encode()
                    elif url.path == "/fleet/history":
                        # ?metric= names the series (omit for the
                        # inventory); ?service= regex-filters;
                        # ?window= seconds; ?points= per-series cap
                        body = json.dumps(mon.fleet_history(
                            metric=q.get("metric", [None])[0],
                            service=q.get("service", [None])[0],
                            window_sec=float(
                                q.get("window", ["60"])[0]),
                            points=int(q.get("points", ["32"])[0]),
                        )).encode()
                    elif url.path == "/fleet/routing":
                        body = json.dumps(mon.fleet_routing()).encode()
                    elif url.path == "/fleet/variants":
                        body = json.dumps(mon.fleet_variants()).encode()
                    elif url.path == "/fleet/hotness":
                        # ?hbm_gb= names the device-tier budget the
                        # capacity planner sizes against
                        # ?replicas= additionally renders the elastic
                        # tier's hotness-balanced placement plan
                        # ?measured_hit_rate= pairs an externally-
                        # measured device hit rate with the prediction
                        # (the planner emits the signed delta)
                        hbm_gb = q.get("hbm_gb", [None])[0]
                        replicas = q.get("replicas", [None])[0]
                        measured = q.get("measured_hit_rate", [None])[0]
                        body = json.dumps(mon.fleet_hotness(
                            hbm_bytes=(int(float(hbm_gb) * (1 << 30))
                                       if hbm_gb else None),
                            num_replicas=(int(replicas)
                                          if replicas else None),
                            measured_hit_rate=(float(measured)
                                               if measured else None),
                        )).encode()
                    elif url.path == "/healthz":
                        doc = mon.fleet_status()["fleet_monitor"]
                        doc.update({"status": "ok", "ready": True,
                                    "service": "fleet_monitor"})
                        body = json.dumps(doc).encode()
                    else:
                        self.send_error(404, "unknown path")
                        return
                except Exception as e:  # noqa: BLE001
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetHttpServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"fleet-http-{self.addr}")
        self._thread.start()
        return self

    def stop(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def main(argv=None):
    p = argparse.ArgumentParser(
        description="persia_tpu fleet monitor: central scrape/SLO "
                    "engine + merged traces + postmortem recorder")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="/fleet/* HTTP port (0 = ephemeral)")
    p.add_argument("--addr-file", default=None,
                   help="write the bound address here after listen")
    p.add_argument("--coordinator",
                   default=knobs.get_raw("PERSIA_COORDINATOR_ADDR"),
                   help="coordinator for sidecar discovery")
    p.add_argument("--targets",
                   default=knobs.get_raw("PERSIA_FLEET_TARGETS"),
                   help="static name=host:port targets, comma separated")
    p.add_argument("--scrape-interval", type=float, default=5.0)
    p.add_argument("--scrape-timeout", type=float, default=2.0)
    p.add_argument("--flight-interval", type=float, default=10.0)
    p.add_argument("--slo-rules", default=None,
                   help="YAML rule file (default: built-in rules)")
    p.add_argument("--postmortem-dir",
                   default=knobs.get_raw("PERSIA_POSTMORTEM_DIR"),
                   help="where breach/crash bundles land (enables the "
                        "flight recorder)")
    p.add_argument("--check", type=int, default=0, metavar="ROUNDS",
                   help="CI gate mode: run ROUNDS scrape rounds "
                        "synchronously, print the alert table plus an "
                        "actionable FIRING summary (rule, label set, "
                        "value vs threshold), exit nonzero iff any SLO "
                        "is firing")
    p.add_argument("--json", action="store_true",
                   help="with --check: emit the full alert/breach "
                        "document as JSON instead of the table "
                        "(machine-readable CI logs)")
    args = p.parse_args(argv)

    engine = SloEngine(load_rules(args.slo_rules)
                       if args.slo_rules else None)
    monitor = FleetMonitor(
        coordinator_addr=args.coordinator,
        static_targets=args.targets,
        scrape_interval=args.scrape_interval,
        scrape_timeout=args.scrape_timeout,
        flight_interval=args.flight_interval,
        slo_engine=engine,
        postmortem_dir=args.postmortem_dir,
    )
    if args.check:
        for _ in range(args.check):
            monitor.scrape_once()
            time.sleep(args.scrape_interval)
        alerts = monitor.alerts()
        firing = [a for a in alerts if a["firing"]]
        if args.json:
            print(json.dumps({
                "firing": firing,
                "alerts": alerts,
                "breaches": monitor.engine.breach_events(),
                "targets": [t.status_doc(time.monotonic())
                            for t in monitor.targets()],
            }, indent=1, default=str))
            raise SystemExit(1 if firing else 0)
        for a in alerts:
            state = "FIRING" if a["firing"] else "ok"
            print(f"{state:>6}  {a['rule']:<24} {a['service']:<12} "
                  f"{a['expr']} {a['op']} {a['threshold']} "
                  f"(value={a['value']})")
        # the actionable summary CI logs need: WHAT breached, on which
        # label set, and by how much — not just a nonzero exit
        if firing:
            print(f"\n{len(firing)} SLO rule(s) FIRING:")
            for a in firing:
                val = a["value"]
                val = f"{val:.6g}" if isinstance(val, float) else val
                since = a.get("firing_since")
                held = (f", firing for "
                        f"{time.monotonic() - since:.0f}s"
                        if since is not None else "")
                print(f"  {a['rule']} on {a['service']}: "
                      f"{a['expr']} = {val}, breaching "
                      f"{a['op']} {a['threshold']}{held}"
                      + (f" — {a['description']}"
                         if a.get("description") else ""))
        raise SystemExit(1 if firing else 0)
    http = monitor.serve_http(args.host, args.port)
    monitor.start()
    _logger.info("fleet monitor serving /fleet/* on %s (%d targets)",
                 http.addr, len(monitor.targets()))
    if args.addr_file:
        from persia_tpu.utils import write_addr_file

        write_addr_file(http.addr, args.addr_file)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        monitor.stop()
        http.stop()


if __name__ == "__main__":
    main()
