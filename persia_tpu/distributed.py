"""Dense distributed options (reference: persia/distributed.py).

The reference wraps torch DDP (`DDPOption`) or Bagua
(`BaguaDistributedOption`) — process-group NCCL/Gloo allreduce with a
NATS master rendezvous. On TPU all of that collapses into mesh
configuration: XLA inserts the collectives, ICI is the fabric, and
multi-host jobs use ``jax.distributed.initialize`` (the JAX coordination
service plays the master-discovery role of nats.rs:22-100).

``DistributedOption`` therefore describes a mesh, and
``get_default_distributed_option`` mirrors the reference's helper
(persia/distributed.py:413-428): pure data parallelism over every
visible device.
"""

import os
from dataclasses import dataclass
from typing import Optional, Tuple

from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)


@dataclass
class DistributedOption:
    """Mesh-shaped replacement for DDP/Bagua options.

    Args:
        mesh_shape: (data, model) device grid; None = all devices on the
            data axis (the reference's DDP topology).
        multihost: initialize ``jax.distributed`` from the standard env
            (coordinator address/process id), for pods spanning hosts.
        coordinator_address / num_processes / process_id: explicit
            multihost rendezvous parameters; default to the JAX env vars.
        grad_reduce_dtype: "bf16" casts dense gradients before the
            cross-replica all-reduce (the analogue of Bagua's
            low-precision algorithms, persia/distributed.py:204-410);
            "int8_ef" uses an error-feedback int8 two-phase all-reduce
            (the ByteGrad analogue — 4x fewer wire bytes, for
            multi-host DCN meshes; see parallel/train.py _ef_int8_mean);
            None reduces in f32. Decentralized/async peer algorithms are
            deliberately absent — ICI all-reduce is already the fast
            path they approximate. Pass to ``TrainCtx`` alongside the
            mesh this option builds.
    """

    mesh_shape: Optional[Tuple[int, int]] = None
    multihost: bool = False
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    grad_reduce_dtype: Optional[str] = None

    def initialize(self):
        """Bring up multi-host JAX if requested; returns the Mesh."""
        import jax

        from persia_tpu.parallel.mesh import make_mesh

        # jax.process_count() would itself initialize the backend, which
        # jax.distributed.initialize refuses to run after — probe the
        # distributed client state instead. jax < 0.5 has no
        # jax.distributed.is_initialized; fall back to the internal
        # client handle it would read.
        is_init = getattr(jax.distributed, "is_initialized", None)
        if is_init is None:
            from jax._src import distributed as _jax_dist

            def is_init():
                return getattr(_jax_dist.global_state, "client",
                               None) is not None
        if self.multihost and not is_init():
            # CPU pods (the cluster-in-a-box dev/CI recipe) need a
            # cross-process collectives backend: jaxlib ships Gloo but
            # jax 0.4.x leaves the CPU backend collective-less by
            # default ("Multiprocess computations aren't implemented on
            # the CPU backend"). Turn it on before the backend exists.
            plats = str(getattr(jax.config, "jax_platforms", None)
                        or os.environ.get("JAX_PLATFORMS", ""))
            if "cpu" in plats.split(","):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except (AttributeError, ValueError):
                    pass  # newer jax: gloo is already the default
            kwargs = {}
            if self.coordinator_address:
                kwargs["coordinator_address"] = self.coordinator_address
            if self.num_processes is not None:
                kwargs["num_processes"] = self.num_processes
            if self.process_id is not None:
                kwargs["process_id"] = self.process_id
            jax.distributed.initialize(**kwargs)
            _logger.info("jax.distributed up: process %d/%d",
                         jax.process_index(), jax.process_count())
        return make_mesh(self.mesh_shape)

    def train_ctx_kwargs(self) -> dict:
        """Everything TrainCtx needs from this option:
        ``TrainCtx(..., **option.train_ctx_kwargs())`` wires both the
        mesh and the gradient-reduction dtype (a bare ``initialize()``
        returns only the mesh and would drop grad_reduce_dtype)."""
        return {
            "mesh": self.initialize(),
            "grad_reduce_dtype": self.grad_reduce_dtype,
        }


def get_default_distributed_option() -> DistributedOption:
    """Data parallelism over every visible chip — the reference default."""
    multihost = os.environ.get("JAX_COORDINATOR_ADDRESS") is not None
    return DistributedOption(mesh_shape=None, multihost=multihost)
