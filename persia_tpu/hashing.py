"""64-bit hashing used for sign→shard routing and hashstack compression.

The reference routes every sign through ``farmhash::hash64(sign.to_le_bytes())``
(rust/persia-embedding-server/src/embedding_worker_service/mod.rs:341-345) and
uses the same hash for multi-round hashstack bucketing (mod.rs:347-400).
We keep bit-exact FarmHash64 semantics for fixed 8-byte little-endian keys so
the reference's golden transform tests carry over unchanged, and so a
checkpoint's shard assignment is reproducible across Python and C++.

Both a scalar and a vectorized numpy implementation are provided; the C++
runtime (native/src/farmhash.h) implements the identical function.
"""

import numpy as np

_MASK = 0xFFFFFFFFFFFFFFFF
_K2 = 0x9AE16A3B2F90404F
_MUL8 = (_K2 + 16) & _MASK  # HashLen0to16's `mul` for len == 8


def farmhash64(sign: int) -> int:
    """FarmHash64 of the 8-byte little-endian encoding of ``sign``.

    Specialization of FarmHash's HashLen0to16 for len == 8, where both
    64-bit fetches read the same word (the sign itself).
    """
    a = (sign + _K2) & _MASK
    b = sign & _MASK
    c = (((b >> 37) | (b << 27)) & _MASK) * _MUL8 + a & _MASK
    c &= _MASK
    d = ((((a >> 25) | (a << 39)) & _MASK) + b) * _MUL8 & _MASK
    # HashLen16(c, d, mul)
    h = ((c ^ d) * _MUL8) & _MASK
    h ^= h >> 47
    h = ((d ^ h) * _MUL8) & _MASK
    h ^= h >> 47
    h = (h * _MUL8) & _MASK
    return h


def farmhash64_np(signs: np.ndarray) -> np.ndarray:
    """Vectorized :func:`farmhash64` over a uint64 array."""
    s = signs.astype(np.uint64, copy=False)
    k2 = np.uint64(_K2)
    mul = np.uint64(_MUL8)
    with np.errstate(over="ignore"):
        a = s + k2
        b = s
        c = (((b >> np.uint64(37)) | (b << np.uint64(27))) * mul) + a
        d = (((a >> np.uint64(25)) | (a << np.uint64(39))) + b) * mul
        h = (c ^ d) * mul
        h ^= h >> np.uint64(47)
        h = (d ^ h) * mul
        h ^= h >> np.uint64(47)
        h *= mul
    return h


def sign_to_shard(signs: np.ndarray, replica_size: int) -> np.ndarray:
    """Shard index for each sign: farmhash64(sign) % replica_size
    (reference: embedding_worker_service/mod.rs:341-345)."""
    return (farmhash64_np(signs) % np.uint64(replica_size)).astype(np.int64)
