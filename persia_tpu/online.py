"""Online learning loop: stream trainer deltas into live serving.

PR 8 built the clocks (``inc_update_freshness_lag_sec``, the stall
SLO) and PR 1 the read-only hot-row cache, but the loop between a
trained sign update and a servable row was only closed by TTL expiry:
a row the trainer just moved stayed stale in every serving replica's
cache for up to ``cache_ttl_sec``. This module closes it directly:

- :class:`DeltaSubscriber` attaches to an ``InferenceServer``'s
  :class:`~persia_tpu.serving.HotRowCache` and scans the SAME
  incremental-update packet stream the infer-tier PS loader consumes
  (:mod:`persia_tpu.inc_update` — one wire, two subscribers), applying
  each packet's rows to RESIDENT cache entries as a **versioned
  in-place upsert**: no inserts, no evictions, no TTL dependence —
  a delta-applied row refreshes its version and TTL stamp atomically,
  so a concurrent predict either sees the whole old row or the whole
  new row, and a stale PS fetch can never resurrect the pre-delta
  value (the cache's ``put`` is version-guarded).
- A **write-rate governor** (token bucket over applied rows,
  ``PERSIA_ONLINE_APPLY_ROWS_PER_SEC``) bounds how hard a training
  burst can hammer the cache lock: a multi-million-row flush spreads
  its applies instead of convoying the predict path — the bench's
  serving-p99-inflation gate (<= 3%) is the contract.
- **Routing awareness** across reshard epochs (PR 11/12): each packet
  file names its dumping PS replica; with a routing view attached, a
  row only applies when that replica OWNS the row's slot under the
  live table (or the double-read predecessor while the migration
  window is open). A donor's late packet flushed after cutover can
  therefore never shadow the new owner's fresher rows — the same
  one-owner discipline the loader's ownership replay enforces.
- The end-to-end age lands in ``serving_sign_to_servable_lag_sec``
  (packet dump timestamp -> apply completed in the serving cache) and
  the per-replica stall clock ``inc_update_sec_since_last_apply``
  (label ``consumer="serving"``), so the existing
  ``serving_freshness_stale`` SLO fires per SERVING replica, not just
  per PS.

Off is free: a server that never attaches a subscriber runs exactly
the PR-13 code — no thread, no extra RPCs, byte-identical wire
(pinned by bench.py --mode online's served-request counts).
"""

import threading
import time
from typing import Dict, Optional, Set, Tuple

import numpy as np

from persia_tpu import knobs
from persia_tpu.inc_update import packet_files, ready_packets
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

# sign-to-servable ages in seconds: the subscriber regime is sub-second
# to seconds (scan interval + governor), the TTL-only regime tens of
# seconds — both must resolve (AGE_BUCKETS starts at 0.5s, too coarse
# for the fast half of the A/B this histogram exists to judge)
LAG_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
               120.0, 300.0, 600.0)


class RateGovernor:
    """Token bucket over applied rows (1s burst). ``spend(rows)``
    blocks until the budget allows the batch and returns the seconds it
    throttled. ``rows_per_sec <= 0`` disables (never blocks). Clock and
    sleep are injectable so tests run on a fake timeline."""

    def __init__(self, rows_per_sec: float,
                 clock=time.monotonic, sleep=time.sleep):
        self.rows_per_sec = float(max(rows_per_sec, 0.0))
        self._clock = clock
        self._sleep = sleep
        self._allowance = self.rows_per_sec  # start with one full burst
        self._t_last = clock()
        self.throttled_sec = 0.0

    def spend(self, rows: int) -> float:
        if self.rows_per_sec <= 0 or rows <= 0:
            return 0.0
        now = self._clock()
        self._allowance = min(
            self.rows_per_sec,
            self._allowance + (now - self._t_last) * self.rows_per_sec)
        self._t_last = now
        if rows <= self._allowance:
            self._allowance -= rows
            return 0.0
        deficit = rows - self._allowance
        self._allowance = 0.0
        wait = deficit / self.rows_per_sec
        self._sleep(wait)
        # the slept-for tokens were consumed by this batch; advance the
        # refill origin past the sleep so they are not double-counted
        self._t_last = self._clock()
        self.throttled_sec += wait
        return wait


class DeltaSubscriber:
    """Scan the inc-update packet stream and upsert resident hot rows.

    ``routing_fn`` returns ``(table, prev)`` — the live
    :class:`~persia_tpu.routing.RoutingTable` and the double-read
    predecessor (or None) — e.g. an in-process
    ``EmbeddingWorker.routing_window``. Without it every packet's rows
    apply (the single-PS / remote-worker case).

    Single-threaded by design: one scanner thread owns ``_applied``
    and the metrics; the only shared object is the cache, whose
    versioned batch apply is the concurrency boundary with the
    predict path.
    """

    def __init__(self, cache, inc_dir: str,
                 scan_interval_sec: Optional[float] = None,
                 rows_per_sec: Optional[float] = None,
                 batch_rows: Optional[int] = None,
                 routing_fn=None,
                 consumer: str = "serving"):
        self.cache = cache
        self.inc_dir = inc_dir
        self.scan_interval_sec = float(
            scan_interval_sec if scan_interval_sec is not None
            else knobs.get("PERSIA_ONLINE_SCAN_SEC"))
        self.batch_rows = int(
            batch_rows if batch_rows is not None
            else knobs.get("PERSIA_ONLINE_APPLY_BATCH_ROWS"))
        self.governor = RateGovernor(
            rows_per_sec if rows_per_sec is not None
            else knobs.get("PERSIA_ONLINE_APPLY_ROWS_PER_SEC"))
        self.routing_fn = routing_fn
        self._applied: Set[str] = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.packets_applied = 0
        self.rows_applied = 0
        self.rows_skipped = 0     # not resident in the cache
        self.rows_filtered = 0    # routing says the dumper lost the row
        self.last_lag_sec = 0.0
        self.last_packet: Optional[str] = None
        self.last_packet_seq = 0
        self._t_last_apply = time.monotonic()

        from persia_tpu.metrics import default_registry

        reg = default_registry()
        labels = {"consumer": consumer}
        self._h_lag = reg.histogram(
            "serving_sign_to_servable_lag_sec", labels,
            help_text="end-to-end online-learning freshness: packet "
                      "dump timestamp to its rows being servable from "
                      "the hot-row cache (delta apply completed)",
            buckets=LAG_BUCKETS)
        self._c_packets = reg.counter(
            "serving_delta_packets_applied_total", labels,
            help_text="incremental packets the serving delta "
                      "subscriber applied into the hot-row cache")
        self._c_rows = reg.counter(
            "serving_delta_rows_applied_total", labels,
            help_text="resident hot rows upserted in place from "
                      "incremental packets")
        self._c_skipped = reg.counter(
            "serving_delta_rows_skipped_total", labels,
            help_text="packet rows ignored because the sign is not "
                      "resident in the hot-row cache (a later miss "
                      "fetches the fresh row from the PS anyway)")
        self._c_filtered = reg.counter(
            "serving_delta_rows_filtered_total", labels,
            help_text="packet rows dropped by the routing ownership "
                      "filter (the dumping replica no longer owns the "
                      "sign's slot — a stale donor packet must not "
                      "shadow the live owner)")
        self._g_throttle = reg.gauge(
            "serving_delta_throttled_sec_total", labels,
            help_text="cumulative seconds the write-rate governor "
                      "stalled delta applies to protect serving p99")
        # the per-serving-replica stall clock: SAME metric name the PS
        # loader exports, so the serving_freshness_stale SLO rule fires
        # for a serving replica whose subscriber went quiet, not just
        # for a PS whose loader did (the consumer label separates them
        # when both live in one process)
        self._g_since_apply = reg.gauge(
            "inc_update_sec_since_last_apply", labels,
            help_text="seconds since this delta subscriber last "
                      "applied a packet (or since it started) — keeps "
                      "rising while the train->serve loop is stalled")

    # --- packet application ----------------------------------------------

    def _owner_mask(self, signs: np.ndarray, src: int,
                    ) -> Optional[np.ndarray]:
        """True where the dumping replica ``src`` owns the sign under
        the live routing view (or the double-read predecessor). None =
        no routing view: apply everything."""
        if self.routing_fn is None:
            return None
        try:
            table, prev = self.routing_fn()
        except Exception:  # routing view unavailable: fail open
            return None
        if table is None:
            return None
        keep = table.replica_of(signs) == src
        if prev is not None and prev.num_slots == table.num_slots:
            keep |= prev.replica_of(signs) == src
        return keep

    def _apply_packet(self, name: str, pkt_dir: str,
                      info: Dict) -> Tuple[int, int, int]:
        from persia_tpu.checkpoint import iter_psd_entries

        applied = skipped = filtered = 0
        for src, path in packet_files(pkt_dir):
            # bucket the file's entries per dim (cache keys are
            # (dim, sign); packets interleave dims freely)
            per_dim: Dict[int, list] = {}
            for sign, dim, vec in iter_psd_entries(path):
                # packet vecs carry [emb | optimizer state]; the cache
                # stores only the embedding slice
                per_dim.setdefault(int(dim), []).append(
                    (sign, np.asarray(vec[:dim], np.float32)))
            for dim, entries in per_dim.items():
                signs = np.array([s for s, _ in entries], np.uint64)
                rows = np.stack([r for _, r in entries])
                keep = self._owner_mask(signs, src)
                if keep is not None:
                    filtered += int(len(signs) - keep.sum())
                    signs, rows = signs[keep], rows[keep]
                for at in range(0, len(signs), self.batch_rows):
                    chunk = slice(at, at + self.batch_rows)
                    self.governor.spend(len(signs[chunk]))
                    n = self.cache.apply_delta(signs[chunk], dim,
                                               rows[chunk])
                    applied += n
                    skipped += len(signs[chunk]) - n
        return applied, skipped, filtered

    def scan_once(self) -> int:
        """Apply every unapplied complete packet; returns resident rows
        upserted. Packet names are the dedup key — a packet applies
        exactly once per subscriber lifetime, whatever epochs change
        between scans."""
        total = 0
        for name, pkt_dir, info in ready_packets(self.inc_dir,
                                                 self._applied):
            applied, skipped, filtered = self._apply_packet(
                name, pkt_dir, info)
            self._applied.add(name)
            self.packets_applied += 1
            self.rows_applied += applied
            self.rows_skipped += skipped
            self.rows_filtered += filtered
            self.last_packet = name
            # inc_<ts>_<seq>_r<replica>_p<pid>
            try:
                self.last_packet_seq = int(name.split("_")[2])
            except (IndexError, ValueError):
                pass
            # sign-to-servable: the packet's rows are servable NOW
            # (apply done), against its dump timestamp
            self.last_lag_sec = max(0.0, time.time() - info["time"])
            self._h_lag.observe(self.last_lag_sec)
            self._c_packets.inc()
            self._c_rows.inc(applied)
            self._c_skipped.inc(skipped)
            self._c_filtered.inc(filtered)
            self._t_last_apply = time.monotonic()
            total += applied
        self._g_throttle.set(self.governor.throttled_sec)
        self._g_since_apply.set(self.sec_since_last_apply)
        return total

    @property
    def sec_since_last_apply(self) -> float:
        return max(0.0, time.monotonic() - self._t_last_apply)

    def health(self) -> Dict:
        """The /healthz rider: what a pager needs to judge one serving
        replica's freshness (the satellite contract — the stall clock
        and the last packet seq live HERE, per replica, not only on
        the PS loader)."""
        return {
            "sec_since_last_apply": round(self.sec_since_last_apply, 3),
            "last_lag_sec": round(self.last_lag_sec, 3),
            "last_packet": self.last_packet,
            "last_packet_seq": self.last_packet_seq,
            "packets_applied": self.packets_applied,
            "rows_applied": self.rows_applied,
            "rows_skipped": self.rows_skipped,
            "rows_filtered": self.rows_filtered,
            "throttled_sec": round(self.governor.throttled_sec, 3),
            "inc_dir": self.inc_dir,
        }

    # --- lifecycle -------------------------------------------------------

    def start(self):
        def run():
            while not self._stop.wait(self.scan_interval_sec):
                try:
                    self.scan_once()
                except Exception as e:  # keep scanning on bad packets
                    _logger.error("delta-subscriber scan failed: %s", e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serving-delta-subscriber")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
