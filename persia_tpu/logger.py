"""Logger factory (reference: persia/logger.py — colorlog + optional file).

Uses stdlib logging with an ANSI color formatter; no third-party deps.
"""

import logging
import os
import sys
from typing import Optional

_LEVEL_COLORS = {
    logging.DEBUG: "\x1b[36m",  # cyan
    logging.INFO: "\x1b[32m",  # green
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
    logging.CRITICAL: "\x1b[35m",  # magenta
}
_RESET = "\x1b[0m"

_loggers = {}


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        color = _LEVEL_COLORS.get(record.levelno, "")
        base = super().format(record)
        if color and sys.stderr.isatty():
            return f"{color}{base}{_RESET}"
        return base


def get_logger(
    name: str,
    level: Optional[int] = None,
    log_file: Optional[str] = None,
) -> logging.Logger:
    """Create (or fetch) a configured logger.

    Level comes from the ``LOG_LEVEL`` env var unless given explicitly,
    mirroring the tracing env filter the reference uses in every binary.
    """
    if name in _loggers:
        cached_level, cached_file, logger = _loggers[name]
        if (level is not None and level != cached_level) or (
            log_file is not None and log_file != cached_file
        ):
            import warnings

            warnings.warn(
                f"get_logger({name!r}) called with level={level!r} "
                f"log_file={log_file!r} but a logger was already configured "
                f"with level={cached_level!r} log_file={cached_file!r}; "
                f"keeping the original configuration",
                stacklevel=2,
            )
        return logger

    logger = logging.getLogger(name)
    if level is None:
        level = getattr(
            logging, os.environ.get("LOG_LEVEL", "INFO").upper(), logging.INFO
        )
    logger.setLevel(level)
    logger.propagate = False

    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        _ColorFormatter(
            fmt="%(asctime)s %(levelname)s [%(name)s] %(message)s",
            datefmt="%Y-%m-%d %H:%M:%S",
        )
    )
    logger.addHandler(handler)

    if log_file is not None:
        file_handler = logging.FileHandler(log_file)
        file_handler.setFormatter(
            logging.Formatter(fmt="%(asctime)s %(levelname)s [%(name)s] %(message)s")
        )
        logger.addHandler(file_handler)

    _loggers[name] = (level, log_file, logger)
    return logger


def get_default_logger(name: str = "persia_tpu") -> logging.Logger:
    return get_logger(name)
