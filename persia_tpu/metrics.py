"""Metrics: counters/gauges/histograms with Prometheus text exposition.

Re-design of rust/persia-metrics/src/lib.rs (PersiaMetricsManager over the
prometheus crate with a push-gateway thread): a dependency-free registry
with the same metric surface. ``push_loop`` PUTs the text exposition to a
Prometheus push gateway (PERSIA_METRICS_GATEWAY_ADDR) at a fixed
interval; scrapers pull ``render()`` through the HTTP sidecar
(:mod:`persia_tpu.obs_http` serves it at ``/metrics``) or call it
in-process.
"""

import bisect
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from persia_tpu.env import get_metrics_gateway_addr
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)


class Counter:
    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, by: float = 1.0):
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Settable AND incrementable: queue-depth gauges are bumped from
    many threads (pipeline feeders, RPC handler pools), and an unlocked
    read-modify-write there loses counts — so ``add``/``dec`` take the
    lock. ``set`` locks too, so a concurrent ``set``/``add`` pair
    cannot interleave mid-update."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float):
        with self._lock:
            self._value = v

    def add(self, by: float = 1.0):
        with self._lock:
            self._value += by

    def dec(self, by: float = 1.0):
        self.add(-by)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus cumulative convention).

    ``DEFAULT_BUCKETS`` suit sub-second latencies; pass purpose-shaped
    boundaries for anything else — ``STEP_BUCKETS`` for staleness
    measured in steps, ``AGE_BUCKETS`` for freshness lags in seconds,
    ``COUNT_BUCKETS`` for size/count distributions. Mis-shaped buckets
    collapse every observation into the overflow cell and make p99
    read as the top bound forever."""

    DEFAULT_BUCKETS = (
        0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
        0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError("histogram buckets must be strictly "
                             "increasing")
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float):
        # bisect_left finds the first boundary >= v (the `v <= b`
        # bucket) in O(log n) — the old linear scan held the lock for
        # the full boundary walk on every overflow-bucket observation
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._sum += v
            self._total += 1
            self._counts[i] += 1

    def timer(self):
        return _Timer(self)

    @property
    def sum(self) -> float:
        """Total of observed values (Prometheus ``_sum`` series)."""
        return self._sum

    @property
    def count(self) -> int:
        """Number of observations (Prometheus ``_count`` series)."""
        return self._total

    def snapshot(self) -> Tuple[int, float]:
        """(count, sum) pair — diff two snapshots to attribute time to a
        bounded region (the worker-cycle breakdown does this, since the
        registry's histograms are process-shared)."""
        with self._lock:
            return self._total, self._sum

    def snapshot_full(self) -> Tuple[List[int], float, int]:
        """(bucket counts, sum, count) read under ONE lock hold —
        exposition must use this: reading ``_counts``/``_sum``/``_total``
        field-by-field races ``observe`` and renders torn series (a
        bucket incremented but the matching ``_count`` not yet, or
        vice versa)."""
        with self._lock:
            return list(self._counts), self._sum, self._total

    def percentile(self, q: float) -> float:
        """Approximate quantile from the bucket counts (linear
        interpolation inside the winning bucket, Prometheus
        histogram_quantile-style). The overflow bucket clamps to the
        top finite bound — serving dashboards prefer a pessimistic
        finite p99 over +Inf."""
        with self._lock:
            counts = list(self._counts)
            total = self._total
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        cum = 0
        lo = 0.0
        for b, c in zip(self.buckets, counts):
            if c and cum + c >= rank:
                frac = min(max((rank - cum) / c, 0.0), 1.0)
                return lo + (b - lo) * frac
            cum += c
            lo = b
        return self.buckets[-1]


# Purpose-shaped bucket sets for the repo's non-latency histograms.
# STEP_BUCKETS: staleness measured in whole steps/update batches (the
# async pipeline's bounded-staleness observable — sub-second latency
# bounds would put every observation in one bucket).
STEP_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
# AGE_BUCKETS: freshness lags in seconds (train->serve sync runs
# seconds-to-minutes; DEFAULT_BUCKETS top out at 10s).
AGE_BUCKETS = (0.5, 1.0, 2.5, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0,
               600.0, 1800.0, 3600.0)
# COUNT_BUCKETS: size/count distributions (entries per packet, rows
# per batch, sketch candidate counts) — log-spaced integers.
COUNT_BUCKETS = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                 10_000, 50_000, 250_000, 1_000_000)


class _Timer:
    def __init__(self, hist: Histogram):
        self.hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Named metrics with optional labels, shared process-wide."""

    def __init__(self, const_labels: Optional[Dict[str, str]] = None):
        self.const_labels = const_labels or {}
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._kinds: Dict[str, str] = {}
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    def _get(self, kind: str, name: str, labels: Optional[Dict[str, str]],
             factory, help_text: Optional[str] = None):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            existing = self._kinds.setdefault(name, kind)
            if existing != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {existing}"
                )
            if help_text:
                self._help.setdefault(name, help_text)
            if key not in self._metrics:
                self._metrics[key] = factory()
            return self._metrics[key]

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None,
                help_text: Optional[str] = None) -> Counter:
        return self._get("counter", name, labels, Counter, help_text)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None,
              help_text: Optional[str] = None) -> Gauge:
        return self._get("gauge", name, labels, Gauge, help_text)

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  help_text: Optional[str] = None,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        """``buckets`` lets a call site shape the boundaries to the
        quantity it observes (STEP_BUCKETS/AGE_BUCKETS/COUNT_BUCKETS
        above). Only the first registration of a (name, labels) series
        sizes it — every family should pass the same boundaries, or
        the exposition's `le` sets diverge across label values."""
        factory = (Histogram if buckets is None
                   else (lambda: Histogram(buckets)))
        return self._get("histogram", name, labels, factory, help_text)

    def render(self) -> str:
        """Prometheus text exposition format, with ``# TYPE`` (and
        ``# HELP`` where registered) comment lines per metric family so
        standard parsers (promtool, the fleet federation layer) accept
        the output without heuristics. Histogram series are read through
        ``snapshot_full()`` so a concurrent ``observe`` cannot tear a
        bucket/count pair mid-render."""
        lines: List[str] = []
        with self._lock:
            items = sorted(self._metrics.items())
            kinds = dict(self._kinds)
            helps = dict(self._help)
        last_family = None
        for (name, labels), metric in items:
            all_labels = {**self.const_labels, **dict(labels)}
            kind = kinds[name]
            if name != last_family:
                # family header once, before the family's first series
                if name in helps:
                    lines.append(f"# HELP {name} "
                                 + _escape_help(helps[name]))
                lines.append(f"# TYPE {name} {kind}")
                last_family = name
            if kind == "histogram":
                assert isinstance(metric, Histogram)
                counts, hsum, total = metric.snapshot_full()
                cumulative = 0
                for b, c in zip(metric.buckets, counts):
                    cumulative += c
                    lines.append(
                        f"{name}_bucket{_fmt({**all_labels, 'le': repr(b)})}"
                        f" {cumulative}"
                    )
                cumulative += counts[-1]
                lines.append(
                    f"{name}_bucket{_fmt({**all_labels, 'le': '+Inf'})}"
                    f" {cumulative}"
                )
                lines.append(f"{name}_sum{_fmt(all_labels)} {hsum}")
                lines.append(f"{name}_count{_fmt(all_labels)} {total}")
            else:
                lines.append(f"{name}{_fmt(all_labels)} {metric.value}")
        return "\n".join(lines) + "\n"

    def push_loop(self, job: str, interval_sec: float = 10.0,
                  gateway_addr: Optional[str] = None
                  ) -> Tuple[threading.Thread, threading.Event]:
        """Background pusher to a Prometheus push gateway
        (reference lib.rs:96-144). Returns ``(thread, stop_event)`` —
        set the event to end the loop (it wakes from its interval wait
        immediately), so tests and clean shutdowns don't leak a pusher
        thread for the process lifetime."""
        addr = gateway_addr or get_metrics_gateway_addr()
        if addr is None:
            raise ValueError("no metrics gateway address configured")
        url = f"http://{addr}/metrics/job/{job}"
        stop = threading.Event()

        def run():
            import urllib.request

            while not stop.wait(interval_sec):
                try:
                    req = urllib.request.Request(
                        url, data=self.render().encode(), method="PUT")
                    urllib.request.urlopen(req, timeout=5)
                except Exception as e:
                    _logger.debug("metrics push failed: %s", e)

        t = threading.Thread(target=run, daemon=True, name="metrics-pusher")
        t.start()
        return t, stop


def _escape_help(v: str) -> str:
    """HELP text escaping (backslash and line feed; quotes are legal)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def parse_exposition(text: str):
    """Parse Prometheus text exposition back into samples — the inverse
    of :meth:`MetricsRegistry.render`, used by the fleet federation
    layer and the SLO engine (and by the parse-back tests that pin the
    exposition's validity).

    Returns ``(samples, families)``: ``samples`` is a list of
    ``(name, labels_dict, value)`` tuples in document order;
    ``families`` maps metric family name -> ``{"type": ..., "help":
    ...}`` (missing keys omitted). Unparseable lines raise ValueError —
    a scraper that wants to tolerate garbage catches it at the call
    site and marks the target down."""
    samples = []
    families: Dict[str, Dict[str, str]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                families.setdefault(parts[2], {})["type"] = parts[3]
            elif len(parts) >= 3 and parts[1] == "HELP":
                families.setdefault(parts[2], {})["help"] = (
                    parts[3] if len(parts) > 3 else "")
            continue
        name, labels, value = _parse_sample_line(line)
        samples.append((name, labels, value))
    return samples, families


def _parse_sample_line(line: str):
    """One sample line: ``name[{k="v",...}] value [timestamp]`` with
    the text-format label-value escapes (\\\\ \\" \\n) honored."""
    brace = line.find("{")
    sp = line.find(" ")
    if brace != -1 and (sp == -1 or brace < sp):
        name = line[:brace]
        labels: Dict[str, str] = {}
        i = brace + 1
        while i < len(line) and line[i] != "}":
            eq = line.index("=", i)
            key = line[i:eq].strip().lstrip(",").strip()
            if line[eq + 1] != '"':
                raise ValueError(f"unquoted label value in {line!r}")
            j = eq + 2
            buf = []
            while True:
                c = line[j]
                if c == "\\":
                    nxt = line[j + 1]
                    buf.append({"n": "\n", '"': '"', "\\": "\\"}
                               .get(nxt, "\\" + nxt))
                    j += 2
                elif c == '"':
                    j += 1
                    break
                else:
                    buf.append(c)
                    j += 1
            labels[key] = "".join(buf)
            i = j
        rest = line[i + 1:].strip()
    else:
        if sp == -1:
            raise ValueError(f"no value on sample line {line!r}")
        name, rest = line[:sp], line[sp + 1:].strip()
        labels = {}
    value_str = rest.split()[0]
    if value_str == "+Inf":
        value = float("inf")
    elif value_str == "-Inf":
        value = float("-inf")
    else:
        value = float(value_str)
    return name, labels, value


def _escape_label_value(v) -> str:
    """Prometheus text-format escaping for label VALUES: backslash,
    double quote, and line feed. Without it an adversarial value (an
    address, a user-supplied job name) terminates the quoted string and
    injects arbitrary series into the exposition."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
