"""ctypes binding to the C++ embedding store (native/build/libpersia_native.so).

``NativeEmbeddingHolder`` exposes the same interface as the pure-Python
:class:`persia_tpu.ps.store.EmbeddingHolder`; semantics and serialization
(PSD1) are identical, and the deterministic init RNG is bit-compatible, so
the two are interchangeable (tests/test_native_parity.py enforces this).
Use :func:`make_holder` to get the fastest available backend.
"""

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_CANDIDATES = [
    os.path.join(_REPO_ROOT, "native", "build", "libpersia_native.so"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "native_bin",
                 "libpersia_native.so"),
]

_INIT_METHOD_CODES = {
    "bounded_uniform": 0,
    "bounded_gamma": 1,
    "bounded_poisson": 2,
    "normal": 3,
    "truncated_normal": 4,
    "zero": 5,
}

_lib = None


def _build_native() -> bool:
    makefile = os.path.join(_REPO_ROOT, "native", "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native"), "-j", "8"],
            check=True, capture_output=True,
        )
        return True
    except (subprocess.CalledProcessError, OSError) as e:
        _logger.warning("native build failed: %s", e)
        return False


def load_native_lib(build_if_missing: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    # explicit override first: the ASan parity hook (and any operator
    # pinning a specific build) names the .so directly. A missing
    # override raises instead of silently falling back to the default
    # candidates — the operator believes a SPECIFIC build is loaded
    override = knobs.get("PERSIA_NATIVE_LIB")
    if override and not os.path.exists(override):
        raise FileNotFoundError(
            f"PERSIA_NATIVE_LIB={override!r} does not exist; unset it "
            "or rebuild (e.g. `make -C native sanitize`)")
    candidates = ([override] if override else []) + _LIB_CANDIDATES
    path = next((p for p in candidates if os.path.exists(p)), None)
    if path is None and build_if_missing and _build_native():
        path = next((p for p in candidates if os.path.exists(p)), None)
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    u64, u32, i32, i64 = (ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int,
                          ctypes.c_int64)
    fptr = ctypes.c_float
    lib.ptps_new.restype = ctypes.c_void_p
    lib.ptps_new.argtypes = [u64, u32]
    lib.ptps_free.argtypes = [ctypes.c_void_p]
    lib.ptps_configure.argtypes = [
        ctypes.c_void_p, i32, ctypes.POINTER(ctypes.c_double), fptr, fptr, i32]
    lib.ptps_register_optimizer.restype = i32
    lib.ptps_register_optimizer.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_lookup.restype = i32
    lib.ptps_lookup.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64), u64, u32,
                                i32, ctypes.POINTER(fptr)]
    lib.ptps_update.restype = i32
    lib.ptps_update.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64), u64, u32,
                                ctypes.POINTER(fptr)]
    lib.ptps_len.restype = u64
    lib.ptps_len.argtypes = [ctypes.c_void_p]
    lib.ptps_clear.argtypes = [ctypes.c_void_p]
    lib.ptps_index_miss_count.restype = u64
    lib.ptps_index_miss_count.argtypes = [ctypes.c_void_p]
    lib.ptps_gradient_id_miss_count.restype = u64
    lib.ptps_gradient_id_miss_count.argtypes = [ctypes.c_void_p]
    lib.ptps_get_entry.restype = i64
    lib.ptps_get_entry.argtypes = [ctypes.c_void_p, u64, ctypes.POINTER(fptr),
                                   u32, ctypes.POINTER(u32)]
    lib.ptps_set_entry.restype = i32
    lib.ptps_set_entry.argtypes = [ctypes.c_void_p, u64, u32,
                                   ctypes.POINTER(fptr), u32]
    lib.ptps_dump.restype = i32
    lib.ptps_dump.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_load.restype = i32
    lib.ptps_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i32]
    lib.ptps_farmhash64.restype = u64
    lib.ptps_farmhash64.argtypes = [u64]
    lib.ptps_farmhash64_batch.argtypes = [ctypes.POINTER(u64), u64,
                                          ctypes.POINTER(u64)]
    lib.ptps_init_entry.argtypes = [u64, u32, i32,
                                    ctypes.POINTER(ctypes.c_double),
                                    ctypes.POINTER(fptr)]
    _lib = lib
    return lib


def _f32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _params_array(params: dict):
    vals = [params.get("lower", -0.01), params.get("upper", 0.01),
            params.get("mean", 0.0), params.get("standard_deviation", 0.01),
            params.get("shape", 1.0), params.get("scale", 1.0),
            params.get("lambda", 1.0)]
    return (ctypes.c_double * 7)(*vals)


def optimizer_config_to_wire(config: dict, feature_index_prefix_bit: int = 0) -> str:
    """Serialize an optimizer config dict to the native wire string
    (parsed by OptimizerConfig::parse in native/src/optim.h)."""
    kind = config["type"]
    if kind == "sgd":
        return f"sgd {config['lr']} {config.get('wd', 0.0)}"
    if kind == "adagrad":
        return (
            f"adagrad {config.get('lr', 1e-2)} {config.get('wd', 0.0)} "
            f"{config.get('g_square_momentum', 1.0)} "
            f"{config.get('initialization', 1e-2)} {config.get('eps', 1e-10)} "
            f"{1 if config.get('vectorwise_shared', False) else 0}"
        )
    if kind == "adam":
        return (
            f"adam {config.get('lr', 1e-3)} {config.get('beta1', 0.9)} "
            f"{config.get('beta2', 0.999)} {config.get('eps', 1e-8)} "
            f"{feature_index_prefix_bit}"
        )
    raise ValueError(f"unknown optimizer type {kind!r}")


class NativeEmbeddingHolder:
    """Drop-in replacement for :class:`persia_tpu.ps.store.EmbeddingHolder`
    backed by the C++ store."""

    # ctypes drops the GIL for the duration of every foreign call, so
    # the service tier's shard-parallel dispatch gets real parallelism
    # from one process (ps_service.ShardParallelDispatcher keys on this)
    releases_gil = True
    # parity-gated: the C++ store keeps every row fp32 (make_holder
    # rejects any other policy while this backend is active)
    row_dtype = "fp32"

    def __init__(self, capacity: int = 1_000_000_000, num_internal_shards: int = 8,
                 hotness=None):
        lib = load_native_lib()
        if lib is None:
            raise RuntimeError(
                "native library not available; run `make -C native` or use "
                "persia_tpu.ps.store.EmbeddingHolder"
            )
        self._lib = lib
        self._h = lib.ptps_new(capacity, num_internal_shards)
        self.capacity = capacity
        self.num_internal_shards = num_internal_shards
        # Mirrors EmbeddingHolder.optimizer being None until registered:
        # readiness checks (PS _ready -> worker recovery re-arm) must see
        # an unarmed native holder as NOT ready for training.
        self.optimizer = None
        # workload hotness sketches live in this Python wrapper (the
        # C++ store never sees them): the tracker owns its own leaf
        # locks, so observing before the ctypes call races nothing
        from persia_tpu import hotness as _hotness

        self.hotness = _hotness.make_tracker(num_internal_shards,
                                             enabled=hotness)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ptps_free(h)
            self._h = None

    def configure(self, init_method: str, init_params: dict,
                  admit_probability: float = 1.0, weight_bound: float = 10.0,
                  enable_weight_bound: bool = True):
        self._lib.ptps_configure(
            self._h, _INIT_METHOD_CODES[init_method], _params_array(init_params),
            admit_probability, weight_bound, 1 if enable_weight_bound else 0,
        )

    def register_optimizer(self, config: dict, feature_index_prefix_bit: int = 0):
        wire = optimizer_config_to_wire(config, feature_index_prefix_bit)
        if self._lib.ptps_register_optimizer(self._h, wire.encode()) != 0:
            raise ValueError(f"native optimizer rejected config {config}")
        self.optimizer = dict(config)

    def lookup(self, signs: np.ndarray, dim: int, training: bool) -> np.ndarray:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        out = np.empty((len(signs), dim), dtype=np.float32)
        if len(signs) == 0:
            return out
        if self.hotness is not None:
            self.hotness.observe(dim, signs)
        rc = self._lib.ptps_lookup(self._h, _u64_ptr(signs), len(signs), dim,
                                   1 if training else 0, _f32_ptr(out))
        if rc != 0:
            raise RuntimeError(
                "native lookup failed (optimizer not registered or store "
                "not configured)"
            )
        return out

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, dim: int):
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        if len(signs) == 0:
            return
        rc = self._lib.ptps_update(self._h, _u64_ptr(signs), len(signs), dim,
                                   _f32_ptr(grads))
        if rc != 0:
            raise RuntimeError("native update failed (optimizer not registered)")

    def get_entry(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        dim_out = ctypes.c_uint32(0)
        length = self._lib.ptps_get_entry(self._h, sign, None, 0,
                                          ctypes.byref(dim_out))
        if length < 0:
            return None
        buf = np.empty(length, dtype=np.float32)
        self._lib.ptps_get_entry(self._h, sign, _f32_ptr(buf), length,
                                 ctypes.byref(dim_out))
        return int(dim_out.value), buf

    def set_entry(self, sign: int, dim: int, vec: np.ndarray):
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        self._lib.ptps_set_entry(self._h, sign, dim, _f32_ptr(vec), len(vec))

    def get_entries(self, signs: np.ndarray, width: int):
        """Batched get_entry (uniform width; absent/mismatched width =>
        not found). One ctypes call per sign locally — the point of the
        batch shape is the RPC twin, where it collapses to ONE round
        trip (ps_service get_entries)."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        found = np.zeros(n, dtype=bool)
        vecs = np.zeros((n, width), dtype=np.float32)
        dim_out = ctypes.c_uint32(0)
        buf = np.empty(width, dtype=np.float32)
        for i in range(n):
            length = self._lib.ptps_get_entry(
                self._h, int(signs[i]), _f32_ptr(buf), width,
                ctypes.byref(dim_out))
            if length == width:
                found[i] = True
                vecs[i] = buf
        return found, vecs

    def set_entries(self, signs: np.ndarray, dim: int, vecs: np.ndarray):
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)
        for i in range(len(signs)):
            self._lib.ptps_set_entry(self._h, int(signs[i]), dim,
                                     _f32_ptr(vecs[i]), vecs.shape[1])

    def clear(self):
        self._lib.ptps_clear(self._h)

    def __len__(self) -> int:
        return int(self._lib.ptps_len(self._h))

    @property
    def index_miss_count(self) -> int:
        return int(self._lib.ptps_index_miss_count(self._h))

    @property
    def gradient_id_miss_count(self) -> int:
        return int(self._lib.ptps_gradient_id_miss_count(self._h))

    def hotness_snapshot(self) -> dict:
        from persia_tpu import hotness as _hotness

        if self.hotness is None:
            return _hotness.disabled_snapshot()
        snap = self.hotness.snapshot()
        # the native store is fp32-only; stamp the live bytes/row so
        # planner_report budgets against the real layout (same contract
        # as the Python holder's row_dtype-aware stamp)
        for table, t in snap.get("tables", {}).items():
            t["row_bytes"] = int(table) * 4
        return snap

    def dump_file(self, path: str):
        if self._lib.ptps_dump(self._h, path.encode()) != 0:
            raise IOError(f"native dump to {path} failed")

    def load_file(self, path: str, clear: bool = True):
        # The C++ loader reads the (fp32) v1 layout only. A v2 dump —
        # written by a half-precision PYTHON holder (e.g. an fp16 train
        # tier handing a checkpoint to a native fp32 serving tier) —
        # is decoded record-by-record here instead: widen to f32, store
        # through set_entry. Keeps the "any holder loads either
        # version" contract without teaching store.h the v2 framing.
        from persia_tpu.ps.store import iter_psd_records, read_psd_header

        with open(path, "rb") as f:
            version, count = read_psd_header(f, path)
            if version == 1:
                pass  # fast path below: one C++ call
            else:
                if clear:
                    self.clear()
                for sign, dim, vec in iter_psd_records(f.read, version,
                                                       count):
                    self.set_entry(sign, dim, vec)
                return
        if self._lib.ptps_load(self._h, path.encode(), 1 if clear else 0) != 0:
            raise IOError(f"native load from {path} failed")


def lint_row_dtype(row_dtype: str = "fp32", prefer_native: bool = True,
                   capacity_bytes=None, spill_dir=None):
    """Config lint for the Python-only store policies: the native C++
    store (store.h/capi.cc) is **fp32-only** with row-count eviction —
    it implements neither ``row_dtype`` narrowing, byte-accounted
    capacity, nor the disk spill tier. Selecting any of them while the
    native backend would be the active one is a silent-downgrade hazard
    (rows would quietly stay fp32-wide / evictions would quietly DROP
    instead of spill), so it is rejected LOUDLY here instead. Raises
    ``ValueError``; a no-op when the policy is plain fp32 with no spill,
    the native backend is not preferred/forced off, or the library
    simply is not built (the numpy holder serves then).
    ``capacity_bytes`` falsy — including the config-default 0 — means
    the byte policy is OFF."""
    if (row_dtype in (None, "fp32")) and not capacity_bytes \
            and not spill_dir:
        return
    if not prefer_native or knobs.get("PERSIA_FORCE_PYTHON_PS"):
        return
    if load_native_lib(build_if_missing=False) is None:
        return
    if row_dtype not in (None, "fp32"):
        policy = f"row_dtype={row_dtype!r}"
    elif capacity_bytes:
        policy = f"capacity_bytes={capacity_bytes}"
    else:
        policy = f"spill_dir={spill_dir!r}"
    raise ValueError(
        f"{policy} is not supported by the native C++ store (fp32 rows, "
        f"row-count eviction, no spill tier) and the native backend is "
        f"active on this host. Either drop the policy for native parity, "
        f"or set PERSIA_FORCE_PYTHON_PS=1 to run this replica on the "
        f"numpy holder, which implements it.")


def make_holder(capacity: int, num_internal_shards: int,
                prefer_native: bool = True, row_dtype: str = "fp32",
                capacity_bytes=None, hotness=None, spill_dir=None,
                spill_bytes=None):
    """Fastest available holder honoring the storage policy: native C++
    store for plain fp32, else the numpy one. Non-fp32 ``row_dtype``,
    byte-accounted capacity, and the disk spill tier are
    Python-holder-only; asking for any while the native backend is
    active fails loudly (:func:`lint_row_dtype`) rather than silently
    downgrading the policy. ``hotness`` arms the workload sketches on
    either backend (None = the PERSIA_HOTNESS knob)."""
    capacity_bytes = capacity_bytes or None  # 0 (config default) = off
    spill_dir = spill_dir or None
    lint_row_dtype(row_dtype, prefer_native, capacity_bytes, spill_dir)
    want_python = (row_dtype not in (None, "fp32")
                   or capacity_bytes is not None
                   or spill_dir is not None)
    if (prefer_native and not want_python
            and not knobs.get("PERSIA_FORCE_PYTHON_PS")):
        try:
            return NativeEmbeddingHolder(capacity, num_internal_shards,
                                         hotness=hotness)
        except RuntimeError:
            _logger.warning("native store unavailable; using numpy holder")
    from persia_tpu.ps.store import EmbeddingHolder

    return EmbeddingHolder(capacity, num_internal_shards,
                           row_dtype=row_dtype or "fp32",
                           capacity_bytes=capacity_bytes, hotness=hotness,
                           spill_dir=spill_dir,
                           spill_bytes=spill_bytes or None)
