"""ctypes binding to the C++ embedding store (native/build/libpersia_native.so).

``NativeEmbeddingHolder`` exposes the same interface as the Python
holders (:class:`persia_tpu.ps.arena.ArenaEmbeddingHolder` and the
legacy per-entry :class:`persia_tpu.ps.store.EmbeddingHolder`);
semantics and serialization (PSD v1/v2) are identical, and the
deterministic init RNG is bit-compatible, so the backends are
interchangeable (tests/test_native_parity.py enforces this — including
fp16/bf16 row storage and byte-accounted eviction, which the native
arena store implements over the SAME record byte layout as the Python
side since PR 10).

Capability negotiation: the arena-era C ABI (``ptps_new2`` + friends)
is probed per loaded library. An OLD ``.so`` (pre-arena) still serves
plain-fp32 row-count-capacity stores; asking it for fp16/bf16 rows, a
byte budget, or the spill tier makes :func:`make_holder` negotiate
DOWN to the Python arena holder with a loud warning (or raise, under
``PERSIA_PS_BACKEND=native``) — never a silent policy downgrade.

The disk spill tier stays implemented once, in Python
(:mod:`persia_tpu.ps.spill`): the native store RETAINS evicted rows in
a drain buffer (``ptps_set_retain_evicted``) and this wrapper demotes
the drained records — the identical logical ``[emb bytes | f32 state]``
byte image the Python holders spill — and faults spilled rows back in
ahead of the native call.

Use :func:`make_holder` to get the right backend for a storage policy
(also steerable via the ``PERSIA_PS_BACKEND`` knob).
"""

import contextlib
import ctypes
import os
import struct
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_CANDIDATES = [
    os.path.join(_REPO_ROOT, "native", "build", "libpersia_native.so"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "native_bin",
                 "libpersia_native.so"),
]

_INIT_METHOD_CODES = {
    "bounded_uniform": 0,
    "bounded_gamma": 1,
    "bounded_poisson": 2,
    "normal": 3,
    "truncated_normal": 4,
    "zero": 5,
}

_ROW_DTYPE_CODES = {"fp32": 0, "fp16": 1, "bf16": 2}

# every symbol of the arena-era ABI; all present <=> the .so implements
# row_dtype narrowing, byte-accounted eviction, PSD v2, the eviction
# drain (spill), and the arena stats surface
_ARENA_SYMBOLS = (
    "ptps_new2", "ptps_row_dtype", "ptps_resident_bytes",
    "ptps_resident_emb_bytes", "ptps_shard_resident_bytes",
    "ptps_arena_stats", "ptps_set_retain_evicted", "ptps_evicted_bytes",
    "ptps_drain_evicted", "ptps_contains",
)

# the SIMD-era ABI (a second, independent capability set: an arena-era
# .so without these still serves every storage policy — only the SIMD
# kernels, tunable shard-parallelism, and batched entry calls are
# missing, and the service tier negotiates down to its legacy constants)
_SIMD_SYMBOLS = (
    "ptps_simd_path", "ptps_simd_force", "ptps_narrow_rows",
    "ptps_widen_rows", "ptps_set_parallel", "ptps_get_parallel",
    "ptps_set_entries", "ptps_get_entries",
)

_lib = None


def _build_native() -> bool:
    makefile = os.path.join(_REPO_ROOT, "native", "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native"), "-j", "8"],
            check=True, capture_output=True,
        )
        return True
    except (subprocess.CalledProcessError, OSError) as e:
        _logger.warning("native build failed: %s", e)
        return False


def load_native_lib(build_if_missing: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    # explicit override first: the ASan parity hook (and any operator
    # pinning a specific build) names the .so directly. A missing
    # override raises instead of silently falling back to the default
    # candidates — the operator believes a SPECIFIC build is loaded
    override = knobs.get("PERSIA_NATIVE_LIB")
    if override and not os.path.exists(override):
        raise FileNotFoundError(
            f"PERSIA_NATIVE_LIB={override!r} does not exist; unset it "
            "or rebuild (e.g. `make -C native sanitize`)")
    candidates = ([override] if override else []) + _LIB_CANDIDATES
    path = next((p for p in candidates if os.path.exists(p)), None)
    if path is None and build_if_missing and _build_native():
        path = next((p for p in candidates if os.path.exists(p)), None)
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    u64, u32, i32, i64 = (ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int,
                          ctypes.c_int64)
    fptr = ctypes.c_float
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ptps_new.restype = ctypes.c_void_p
    lib.ptps_new.argtypes = [u64, u32]
    lib.ptps_free.argtypes = [ctypes.c_void_p]
    lib.ptps_configure.argtypes = [
        ctypes.c_void_p, i32, ctypes.POINTER(ctypes.c_double), fptr, fptr, i32]
    lib.ptps_register_optimizer.restype = i32
    lib.ptps_register_optimizer.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_lookup.restype = i32
    lib.ptps_lookup.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64), u64, u32,
                                i32, ctypes.POINTER(fptr)]
    lib.ptps_update.restype = i32
    lib.ptps_update.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64), u64, u32,
                                ctypes.POINTER(fptr)]
    lib.ptps_len.restype = u64
    lib.ptps_len.argtypes = [ctypes.c_void_p]
    lib.ptps_clear.argtypes = [ctypes.c_void_p]
    lib.ptps_index_miss_count.restype = u64
    lib.ptps_index_miss_count.argtypes = [ctypes.c_void_p]
    lib.ptps_gradient_id_miss_count.restype = u64
    lib.ptps_gradient_id_miss_count.argtypes = [ctypes.c_void_p]
    lib.ptps_get_entry.restype = i64
    lib.ptps_get_entry.argtypes = [ctypes.c_void_p, u64, ctypes.POINTER(fptr),
                                   u32, ctypes.POINTER(u32)]
    lib.ptps_set_entry.restype = i32
    lib.ptps_set_entry.argtypes = [ctypes.c_void_p, u64, u32,
                                   ctypes.POINTER(fptr), u32]
    lib.ptps_dump.restype = i32
    lib.ptps_dump.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ptps_load.restype = i32
    lib.ptps_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i32]
    lib.ptps_farmhash64.restype = u64
    lib.ptps_farmhash64.argtypes = [u64]
    lib.ptps_farmhash64_batch.argtypes = [ctypes.POINTER(u64), u64,
                                          ctypes.POINTER(u64)]
    lib.ptps_init_entry.argtypes = [u64, u32, i32,
                                    ctypes.POINTER(ctypes.c_double),
                                    ctypes.POINTER(fptr)]
    # arena-era ABI (declared only when the .so exports it — an older
    # library simply lacks the symbols and the capability probe says so)
    if all(hasattr(lib, s) for s in _ARENA_SYMBOLS):
        lib.ptps_new2.restype = ctypes.c_void_p
        lib.ptps_new2.argtypes = [u64, u32, i32, u64]
        lib.ptps_row_dtype.restype = i32
        lib.ptps_row_dtype.argtypes = [ctypes.c_void_p]
        lib.ptps_resident_bytes.restype = u64
        lib.ptps_resident_bytes.argtypes = [ctypes.c_void_p]
        lib.ptps_resident_emb_bytes.restype = u64
        lib.ptps_resident_emb_bytes.argtypes = [ctypes.c_void_p]
        lib.ptps_shard_resident_bytes.argtypes = [ctypes.c_void_p,
                                                  ctypes.POINTER(u64)]
        lib.ptps_arena_stats.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(u64)]
        lib.ptps_set_retain_evicted.argtypes = [ctypes.c_void_p, i32]
        lib.ptps_evicted_bytes.restype = u64
        lib.ptps_evicted_bytes.argtypes = [ctypes.c_void_p]
        lib.ptps_drain_evicted.restype = u64
        lib.ptps_drain_evicted.argtypes = [ctypes.c_void_p, u8p, u64]
        lib.ptps_contains.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64),
                                      u64, u8p]
    # SIMD-era ABI (independent probe: negotiate-down keeps working on a
    # library that predates it)
    if all(hasattr(lib, s) for s in _SIMD_SYMBOLS):
        lib.ptps_simd_path.restype = ctypes.c_char_p
        lib.ptps_simd_path.argtypes = []
        lib.ptps_simd_force.restype = i32
        lib.ptps_simd_force.argtypes = [ctypes.c_char_p]
        lib.ptps_narrow_rows.argtypes = [i32, ctypes.POINTER(fptr), u64, u8p,
                                         i32]
        lib.ptps_widen_rows.argtypes = [i32, u8p, u64, ctypes.POINTER(fptr),
                                        i32]
        lib.ptps_set_parallel.argtypes = [ctypes.c_void_p, u32, u64]
        lib.ptps_get_parallel.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(u64)]
        lib.ptps_set_entries.restype = i32
        lib.ptps_set_entries.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64),
                                         u64, u32, ctypes.POINTER(fptr), u32]
        lib.ptps_get_entries.restype = i64
        lib.ptps_get_entries.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64),
                                         u64, u32, ctypes.POINTER(fptr),
                                         ctypes.POINTER(i64)]
    _lib = lib
    return lib


def native_capabilities(lib=None) -> frozenset:
    """Storage-policy capabilities of the loaded native library. The
    arena-era ABI implements them as one indivisible set; an older
    ``.so`` (plain fp32, row-count eviction, PSD v1) reports empty —
    the make_holder negotiation keys on this, never on versions."""
    if lib is None:
        lib = load_native_lib(build_if_missing=False)
    if lib is None:
        return frozenset()
    caps = set()
    if all(hasattr(lib, s) for s in _ARENA_SYMBOLS):
        caps.update({"row_dtype", "capacity_bytes", "psd_v2",
                     "spill", "arena_stats"})
    if all(hasattr(lib, s) for s in _SIMD_SYMBOLS):
        caps.update({"simd", "parallel_tuning", "batched_entries"})
    return frozenset(caps)


def required_capabilities(row_dtype=None, capacity_bytes=None,
                          spill_dir=None) -> frozenset:
    """The native capabilities a storage policy needs (empty = any
    ``.so`` ever shipped can serve it)."""
    need = set()
    if row_dtype not in (None, "fp32"):
        need.update({"row_dtype", "psd_v2"})
    if capacity_bytes:
        need.add("capacity_bytes")
    if spill_dir:
        need.add("spill")
    return frozenset(need)


def _f32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _u8_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def native_simd_path(lib=None) -> Optional[str]:
    """Kernel path the loaded native library selected ("avx2" | "neon" |
    "scalar"), honoring the PERSIA_NATIVE_SIMD knob; None when no
    library is loaded or it predates the SIMD ABI."""
    if lib is None:
        lib = load_native_lib(build_if_missing=False)
    if lib is None or "simd" not in native_capabilities(lib):
        return None
    return lib.ptps_simd_path().decode()


def _params_array(params: dict):
    vals = [params.get("lower", -0.01), params.get("upper", 0.01),
            params.get("mean", 0.0), params.get("standard_deviation", 0.01),
            params.get("shape", 1.0), params.get("scale", 1.0),
            params.get("lambda", 1.0)]
    return (ctypes.c_double * 7)(*vals)


def optimizer_config_to_wire(config: dict, feature_index_prefix_bit: int = 0) -> str:
    """Serialize an optimizer config dict to the native wire string
    (parsed by OptimizerConfig::parse in native/src/optim.h)."""
    kind = config["type"]
    if kind == "sgd":
        return f"sgd {config['lr']} {config.get('wd', 0.0)}"
    if kind == "adagrad":
        return (
            f"adagrad {config.get('lr', 1e-2)} {config.get('wd', 0.0)} "
            f"{config.get('g_square_momentum', 1.0)} "
            f"{config.get('initialization', 1e-2)} {config.get('eps', 1e-10)} "
            f"{1 if config.get('vectorwise_shared', False) else 0}"
        )
    if kind == "adam":
        return (
            f"adam {config.get('lr', 1e-3)} {config.get('beta1', 0.9)} "
            f"{config.get('beta2', 0.999)} {config.get('eps', 1e-8)} "
            f"{feature_index_prefix_bit}"
        )
    raise ValueError(f"unknown optimizer type {kind!r}")


# spill/drain record framing: sign u64 | dim u32 | stored nbytes u32
_DRAIN_REC = struct.Struct("<QII")


class NativeEmbeddingHolder:
    """Drop-in replacement for the Python holders backed by the C++
    arena store. ``row_dtype``/``capacity_bytes`` require the arena-era
    library (RuntimeError otherwise — make_holder negotiates down
    instead); ``spill_dir`` arms the shared Python SpillStore fed by
    the store's retained-eviction drain."""

    # ctypes drops the GIL for the duration of every foreign call, so
    # the service tier's shard-parallel dispatch gets real parallelism
    # from one process (ps_service.ShardParallelDispatcher keys on this)
    releases_gil = True

    def __init__(self, capacity: int = 1_000_000_000, num_internal_shards: int = 8,
                 hotness=None, row_dtype: str = "fp32",
                 capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 spill_bytes: Optional[int] = None):
        lib = load_native_lib()
        if lib is None:
            raise RuntimeError(
                "native library not available; run `make -C native` or use "
                "persia_tpu.ps.arena.ArenaEmbeddingHolder"
            )
        row_dtype = row_dtype or "fp32"
        capacity_bytes = capacity_bytes or None
        spill_dir = spill_dir or None
        self._caps = native_capabilities(lib)
        missing = required_capabilities(row_dtype, capacity_bytes,
                                        spill_dir) - self._caps
        if missing:
            raise RuntimeError(
                f"loaded native library lacks {sorted(missing)} needed by "
                f"this storage policy (row_dtype={row_dtype!r}, "
                f"capacity_bytes={capacity_bytes}, spill_dir={spill_dir!r})"
                " — rebuild `make -C native`, or let make_holder negotiate "
                "down to the Python arena holder")
        self._lib = lib
        if self._caps:
            self._h = lib.ptps_new2(capacity, num_internal_shards,
                                    _ROW_DTYPE_CODES[row_dtype],
                                    capacity_bytes or 0)
        else:
            self._h = lib.ptps_new(capacity, num_internal_shards)
        self.capacity = capacity
        self.capacity_bytes = capacity_bytes
        self.num_internal_shards = num_internal_shards
        self.row_dtype = row_dtype
        # LOUD: name the engaged kernel path at init so a replica that
        # silently degraded to scalar (bad knob value, older CPU) is
        # visible in logs — and exported via /healthz + fleet gauges
        self.simd_path = (lib.ptps_simd_path().decode()
                          if "simd" in self._caps else None)
        if self.simd_path is not None:
            _logger.info(
                "native store SIMD kernel path: %s "
                "(PERSIA_NATIVE_SIMD=%s, row_dtype=%s)",
                self.simd_path, knobs.get("PERSIA_NATIVE_SIMD") or "auto",
                row_dtype)
        else:
            _logger.info(
                "native store predates the SIMD ABI: scalar kernels, no "
                "parallel tuning (rebuild `make -C native`)")
        # widen/narrow policy of the logical record bytes (drain + spill)
        from persia_tpu.ps.optim import RowPrecision

        self._rp = RowPrecision(row_dtype)
        # Mirrors the Python holders' optimizer being None until
        # registered: readiness checks (PS _ready -> worker recovery
        # re-arm) must see an unarmed native holder as NOT ready.
        self.optimizer = None
        # workload hotness sketches live in this Python wrapper (the
        # C++ store never sees them): the tracker owns its own leaf
        # locks, so observing before the ctypes call races nothing
        from persia_tpu import hotness as _hotness

        self.hotness = _hotness.make_tracker(num_internal_shards,
                                             enabled=hotness)
        # disk spill tier: shared Python implementation over the same
        # logical record bytes; the store retains evictions for us
        if spill_dir:
            from persia_tpu.ps.spill import SpillStore

            self.spill: Optional["SpillStore"] = SpillStore(
                spill_dir, max_bytes=spill_bytes or None)
            lib.ptps_set_retain_evicted(self._h, 1)
            # SPILL-ARMED CALLS SERIALIZE at the wrapper: the
            # drain -> resident-filter -> SpillStore handoff spans
            # several unlocked steps, and a concurrent training lookup
            # landing in the neither-tier window would silently
            # reinitialize a demoted row. The Python holders demote
            # under their shard locks; this lock is the wrapper's
            # equivalent (the C++ store still shard-parallelizes
            # WITHIN each call, and unarmed holders stay lock-free).
            self._mu: Optional[threading.RLock] = threading.RLock()
        else:
            self.spill = None
            self._mu = None

    def _guard(self):
        return self._mu if self._mu is not None else (
            contextlib.nullcontext())

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ptps_free(h)
            self._h = None

    def parallel_info(self) -> Optional[dict]:
        """Capability probe for the service-tier dispatcher: the native
        store's resolved shard-parallel worker count and the batch size
        below which it stays serial. None when the loaded ``.so``
        predates tunable parallelism (the dispatcher then falls back to
        its legacy constants — negotiate-down, never a crash)."""
        if "parallel_tuning" not in self._caps:
            return None
        out = np.zeros(2, np.uint64)
        self._lib.ptps_get_parallel(self._h, _u64_ptr(out))
        return {"threads": int(out[0]), "min_batch": int(out[1])}

    def set_parallel(self, threads: int = 0, min_batch: int = 0) -> bool:
        """Tune the native shard-parallel engine (threads=0 restores
        auto; min_batch=0 leaves the serial threshold unchanged).
        Returns False on a pre-SIMD-ABI library."""
        if "parallel_tuning" not in self._caps:
            return False
        self._lib.ptps_set_parallel(self._h, int(threads), int(min_batch))
        return True

    def configure(self, init_method: str, init_params: dict,
                  admit_probability: float = 1.0, weight_bound: float = 10.0,
                  enable_weight_bound: bool = True):
        self._lib.ptps_configure(
            self._h, _INIT_METHOD_CODES[init_method], _params_array(init_params),
            admit_probability, weight_bound, 1 if enable_weight_bound else 0,
        )

    def register_optimizer(self, config: dict, feature_index_prefix_bit: int = 0):
        wire = optimizer_config_to_wire(config, feature_index_prefix_bit)
        if self._lib.ptps_register_optimizer(self._h, wire.encode()) != 0:
            raise ValueError(f"native optimizer rejected config {config}")
        self.optimizer = dict(config)

    # --- spill plumbing ---------------------------------------------------

    def _drain_evictions(self):
        """Demote the store's retained evictions to the disk tier.
        Records carry the logical stored bytes, so the spill round trip
        is bit-identical across backends. A sign that was evicted and
        re-admitted within the same call is filtered out (a resident
        row must never shadow a stale disk copy)."""
        lib = self._lib
        while True:
            need = int(lib.ptps_evicted_bytes(self._h))
            if not need:
                return
            buf = np.empty(need, np.uint8)
            got = int(lib.ptps_drain_evicted(self._h, _u8_ptr(buf), need))
            if not got:
                return
            # parse the shard-concatenated records, grouped per
            # (dim, nbytes) for the batched (slab-slice) spill path;
            # the header walk stays a (cheap) loop — record lengths are
            # data-dependent — but the payload copy is ONE fancy-index
            # gather per group instead of per-record slices + np.stack
            groups = {}
            off = 0
            while off + _DRAIN_REC.size <= got:
                sign, dim, nbytes = _DRAIN_REC.unpack_from(buf, off)
                off += _DRAIN_REC.size
                g = groups.setdefault((dim, nbytes), ([], []))
                g[0].append(sign)
                g[1].append(off)
                off += nbytes
            for (dim, nbytes), (signs, offs) in groups.items():
                signs = np.array(signs, np.uint64)
                starts = np.asarray(offs, np.int64)
                mat = buf[starts[:, None]
                          + np.arange(nbytes, dtype=np.int64)[None, :]]
                resident = np.zeros(len(signs), np.uint8)
                lib.ptps_contains(self._h, _u64_ptr(signs), len(signs),
                                  _u8_ptr(resident))
                keep = resident == 0
                if keep.any():
                    self.spill.put_batch(signs[keep], dim, mat[keep])

    def _fault_in(self, signs: np.ndarray, training: bool) -> np.ndarray:
        """Promote any spilled batch signs back into the native store
        (training) or report which are spilled (read paths). Returns
        the spilled-sign mask."""
        mask = self.spill.contains_batch(signs)
        if training and mask.any():
            for s in signs[mask].tolist():
                got = self.spill.take(s)
                if got is None:
                    continue
                dim0, raw = got
                vec = self._widen_raw(dim0, raw)
                self._lib.ptps_set_entry(self._h, s, dim0, _f32_ptr(vec),
                                         len(vec))
            # deliberately NOT drained here: rows these promotions evict
            # stay in the store's drain buffer through the upcoming data
            # call, whose misses fault them back from there (the
            # intra-batch evict-then-reaccess case); the caller drains
            # after its native call
        return mask

    def _widen_raw(self, dim: int, raw: np.ndarray) -> np.ndarray:
        rp = self._rp
        vec = np.empty(dim + (len(raw) - dim * rp.itemsize) // 4,
                       np.float32)
        vec[:dim] = raw[: dim * rp.itemsize].view(rp.np_dtype) \
            .astype(np.float32)
        vec[dim:] = raw[dim * rp.itemsize:].view(np.float32)
        return vec

    # --- data plane -------------------------------------------------------

    def lookup(self, signs: np.ndarray, dim: int, training: bool) -> np.ndarray:
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._lookup_impl(signs=signs, dim=dim, training=training)

    def _lookup_impl(self, signs: np.ndarray, dim: int, training: bool) -> np.ndarray:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        out = np.empty((len(signs), dim), dtype=np.float32)
        if len(signs) == 0:
            return out
        if self.hotness is not None:
            self.hotness.observe(dim, signs)
        spilled = None
        if self.spill is not None and len(self.spill):
            spilled = self._fault_in(signs, training)
        if not training and spilled is not None and spilled.any():
            # read-only lookups PEEK the disk tier (residency must not
            # change); the native call sees only the resident signs
            sub = np.ascontiguousarray(signs[~spilled])
            sub_out = np.empty((len(sub), dim), np.float32)
            if len(sub):
                rc = self._lib.ptps_lookup(self._h, _u64_ptr(sub), len(sub),
                                           dim, 0, _f32_ptr(sub_out))
                if rc != 0:
                    raise RuntimeError("native lookup failed")
            out[~spilled] = sub_out
            for j in np.nonzero(spilled)[0]:
                got = self.spill.peek(int(signs[j]))
                if got is not None and got[0] == dim:
                    out[j] = self._widen_raw(dim, got[1])[:dim]
                else:
                    out[j] = 0.0
            return out
        rc = self._lib.ptps_lookup(self._h, _u64_ptr(signs), len(signs), dim,
                                   1 if training else 0, _f32_ptr(out))
        if rc != 0:
            raise RuntimeError(
                "native lookup failed (optimizer not registered or store "
                "not configured)"
            )
        if training and self.spill is not None:
            self._drain_evictions()
        return out

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, dim: int):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._update_gradients_impl(signs=signs, grads=grads, dim=dim)

    def _update_gradients_impl(self, signs: np.ndarray, grads: np.ndarray, dim: int):
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        if len(signs) == 0:
            return
        if self.spill is not None and len(self.spill):
            # a gradient for a spilled row faults it in first — a
            # demotion must not turn updates into misses
            self._fault_in(signs, True)
        rc = self._lib.ptps_update(self._h, _u64_ptr(signs), len(signs), dim,
                                   _f32_ptr(grads))
        if rc != 0:
            raise RuntimeError("native update failed (optimizer not registered)")
        if self.spill is not None:
            self._drain_evictions()

    def get_entry(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._get_entry_impl(sign=sign)

    def _get_entry_impl(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        dim_out = ctypes.c_uint32(0)
        length = self._lib.ptps_get_entry(self._h, sign, None, 0,
                                          ctypes.byref(dim_out))
        if length < 0:
            if self.spill is not None:
                got = self.spill.peek(int(sign))
                if got is not None:
                    dim0, raw = got
                    return dim0, self._widen_raw(dim0, raw)
            return None
        buf = np.empty(length, dtype=np.float32)
        self._lib.ptps_get_entry(self._h, sign, _f32_ptr(buf), length,
                                 ctypes.byref(dim_out))
        return int(dim_out.value), buf

    def set_entry(self, sign: int, dim: int, vec: np.ndarray):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._set_entry_impl(sign=sign, dim=dim, vec=vec)

    def _set_entry_impl(self, sign: int, dim: int, vec: np.ndarray):
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        if self.spill is not None:
            self.spill.discard(int(sign))
        self._lib.ptps_set_entry(self._h, sign, dim, _f32_ptr(vec), len(vec))
        if self.spill is not None:
            self._drain_evictions()

    def get_entries(self, signs: np.ndarray, width: int):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._get_entries_impl(signs=signs, width=width)

    def _get_entries_impl(self, signs: np.ndarray, width: int):
        """Batched get_entry (uniform width; absent/mismatched width =>
        not found). With the SIMD-era ABI this is ONE GIL-released
        foreign call (ptps_get_entries) that widens straight out of the
        slabs; a pre-SIMD library falls back to the per-sign loop."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        found = np.zeros(n, dtype=bool)
        vecs = np.zeros((n, width), dtype=np.float32)
        if n == 0:
            return found, vecs
        if "batched_entries" in self._caps:
            lens = np.empty(n, dtype=np.int64)
            self._lib.ptps_get_entries(self._h, _u64_ptr(signs), n, width,
                                       _f32_ptr(vecs), _i64_ptr(lens))
            found = lens == width
            # a resident row of the wrong width counts as not-found and
            # must come back zero (the native call wrote its prefix)
            mismatched = (lens >= 0) & ~found
            if mismatched.any():
                vecs[mismatched] = 0.0
            if self.spill is not None and len(self.spill):
                for i in np.nonzero(lens < 0)[0]:
                    got = self.spill.peek(int(signs[i]))
                    if got is None:
                        continue
                    dim0, raw = got
                    vec = self._widen_raw(dim0, raw)
                    if len(vec) == width:
                        found[i] = True
                        vecs[i] = vec
            return found, vecs
        dim_out = ctypes.c_uint32(0)
        buf = np.empty(width, dtype=np.float32)
        for i in range(n):
            length = self._lib.ptps_get_entry(
                self._h, int(signs[i]), _f32_ptr(buf), width,
                ctypes.byref(dim_out))
            if length == width:
                found[i] = True
                vecs[i] = buf
            elif length < 0 and self.spill is not None:
                got = self.spill.peek(int(signs[i]))
                if got is None:
                    continue
                dim0, raw = got
                vec = self._widen_raw(dim0, raw)
                if len(vec) == width:
                    found[i] = True
                    vecs[i] = vec
        return found, vecs

    def set_entries(self, signs: np.ndarray, dim: int, vecs: np.ndarray):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._set_entries_impl(signs=signs, dim=dim, vecs=vecs)

    def _set_entries_impl(self, signs: np.ndarray, dim: int, vecs: np.ndarray):
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)
        if len(signs) == 0:
            return
        if "batched_entries" in self._caps:
            # ONE GIL-released foreign call narrows the whole group
            # straight into the slabs (the reshard-install hot path)
            if self.spill is not None:
                for s in signs.tolist():
                    self.spill.discard(int(s))
            rc = self._lib.ptps_set_entries(self._h, _u64_ptr(signs),
                                            len(signs), dim, _f32_ptr(vecs),
                                            vecs.shape[1])
            if rc != 0:
                raise RuntimeError("native set_entries failed (len < dim)")
        else:
            for i in range(len(signs)):
                if self.spill is not None:
                    self.spill.discard(int(signs[i]))
                self._lib.ptps_set_entry(self._h, int(signs[i]), dim,
                                         _f32_ptr(vecs[i]), vecs.shape[1])
        if self.spill is not None:
            self._drain_evictions()

    def clear(self):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._clear_impl()

    def _clear_impl(self):
        self._lib.ptps_clear(self._h)
        if self.spill is not None:
            self.spill.clear()

    def __len__(self) -> int:
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._len_impl()

    def _len_impl(self) -> int:
        n = int(self._lib.ptps_len(self._h))
        if self.spill is not None:
            n += len(self.spill)
        return n

    # --- observables ------------------------------------------------------

    @property
    def index_miss_count(self) -> int:
        return int(self._lib.ptps_index_miss_count(self._h))

    @property
    def gradient_id_miss_count(self) -> int:
        return int(self._lib.ptps_gradient_id_miss_count(self._h))

    @property
    def resident_bytes(self) -> int:
        if not self._caps:
            return -1  # pre-arena .so: no byte accounting
        return int(self._lib.ptps_resident_bytes(self._h))

    @property
    def resident_emb_bytes(self) -> int:
        if not self._caps:
            return -1
        return int(self._lib.ptps_resident_emb_bytes(self._h))

    def resident_bytes_per_shard(self):
        if not self._caps:
            return []
        out = np.zeros(self.num_internal_shards, np.uint64)
        self._lib.ptps_shard_resident_bytes(self._h, _u64_ptr(out))
        return [int(b) for b in out]

    def arena_stats(self):
        if not self._caps:
            return {}
        out = np.zeros(4, np.uint64)
        self._lib.ptps_arena_stats(self._h, _u64_ptr(out))
        slab, free_slots, live, logical = (int(x) for x in out)
        alloc = free_slots + live
        return {"slab_bytes": slab, "free_slots": free_slots,
                "live_rows": live, "resident_bytes": logical,
                "fragmentation_ratio": (round(free_slots / alloc, 6)
                                        if alloc else 0.0)}

    def row_nbytes(self, dim: int) -> int:
        from persia_tpu.ps.optim import SparseOptimizer

        space = 0
        if self.optimizer is not None:
            space = SparseOptimizer.from_config(
                dict(self.optimizer)).require_space(dim)
        return self._rp.entry_nbytes(dim, space)

    def spill_stats(self) -> dict:
        return self.spill.stats() if self.spill is not None else {}

    def hotness_snapshot(self) -> dict:
        from persia_tpu import hotness as _hotness

        if self.hotness is None:
            return _hotness.disabled_snapshot()
        snap = self.hotness.snapshot()
        # stamp the LIVE bytes/row so planner_report budgets against the
        # real storage width (same contract as the Python holders)
        for table, t in snap.get("tables", {}).items():
            t["row_bytes"] = int(table) * self._rp.itemsize
        return snap

    # --- serialization ----------------------------------------------------

    def dump_file(self, path: str):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._dump_file_impl(path=path)

    def _dump_file_impl(self, path: str):
        if self.spill is None:
            if self._lib.ptps_dump(self._h, path.encode()) != 0:
                raise IOError(f"native dump to {path} failed")
            return
        # spill-armed: a checkpoint is the LOGICAL table. The store
        # dumps its resident rows; spill records append behind them and
        # dump-window capture records (rows that LEFT the disk tier
        # mid-dump) prepend with lowest load priority — the same
        # shards-then-spill-with-capture discipline as the Python
        # holders, over the same record encodings.
        rp = self._rp
        self.spill.start_dump_capture()
        tmp = path + ".native_part"
        try:
            if self._lib.ptps_dump(self._h, tmp.encode()) != 0:
                raise IOError(f"native dump to {tmp} failed")
            code = _ROW_DTYPE_CODES[self.row_dtype]

            def rec(version, sign, dim, raw):
                if version == 1:
                    return (struct.pack("<QII", sign, dim, len(raw) // 4)
                            + raw.tobytes())
                return (struct.pack("<QIBI", sign, dim, code,
                                    rp.state_len_of(raw, dim))
                        + raw.tobytes())

            import shutil

            head_len = 4 + struct.calcsize("<IQ")
            spill_tmp = path + ".spill_part"
            with open(tmp, "rb") as src, open(path, "wb") as dst:
                head = src.read(head_len)
                version, count = struct.unpack_from("<IQ", head, 4)
                dst.write(head)
                # spill records serialize FIRST (to a side temp, with
                # the capture window still armed — a row faulting in
                # mid-iteration must land in the capture); they append
                # behind the native body in the final file. Capture
                # records prepend with lowest load priority (any
                # shard/spill record of the same sign is newer and wins
                # on the sequential reload). The count patches into the
                # header afterwards, so the native body streams through
                # in bounded chunks instead of materializing a multi-GB
                # store in memory.
                with open(spill_tmp, "wb") as sp:
                    for sign, dim, raw in self.spill.items():
                        sp.write(rec(version, sign, dim, raw))
                        count += 1
                for sign, (dim, raw) in \
                        self.spill.stop_dump_capture().items():
                    dst.write(rec(version, sign, dim, raw))
                    count += 1
                shutil.copyfileobj(src, dst, 4 << 20)
                with open(spill_tmp, "rb") as sp:
                    shutil.copyfileobj(sp, dst, 4 << 20)
                dst.seek(8)
                dst.write(struct.pack("<Q", count))
        finally:
            self.spill.stop_dump_capture()
            for t in (tmp, path + ".spill_part"):
                try:
                    os.remove(t)
                except OSError:
                    pass

    def load_file(self, path: str, clear: bool = True):
        # serialized while spill-armed (see _mu); no-op guard else
        with self._guard():
            return self._load_file_impl(path=path, clear=clear)

    def _load_file_impl(self, path: str, clear: bool = True):
        if self._caps:
            if self.spill is not None:
                if clear:
                    # both tiers restart empty; rows the load itself
                    # evicts drain into the (fresh) spill below
                    self.spill.clear()
                else:
                    # merge-load: every loaded sign must discard any
                    # stale spilled copy (the Python holders get this
                    # from set_entry) — take the record-by-record path
                    from persia_tpu.ps.store import (iter_psd_records,
                                                     read_psd_header)

                    with open(path, "rb") as f:
                        version, count = read_psd_header(f, path)
                        for sign, dim, vec in iter_psd_records(
                                f.read, version, count):
                            self.set_entry(sign, dim, vec)
                    return
            # the arena-era store decodes both PSD versions in-tree
            if self._lib.ptps_load(self._h, path.encode(),
                                   1 if clear else 0) != 0:
                raise IOError(f"native load from {path} failed")
            if self.spill is not None:
                self._drain_evictions()
            return
        # pre-arena .so: C++ reads the (fp32) v1 layout only; decode v2
        # record-by-record here (widen to f32, store through set_entry)
        from persia_tpu.ps.store import iter_psd_records, read_psd_header

        with open(path, "rb") as f:
            version, count = read_psd_header(f, path)
            if version != 1:
                if clear:
                    self.clear()
                for sign, dim, vec in iter_psd_records(f.read, version,
                                                       count):
                    self.set_entry(sign, dim, vec)
                return
        if self._lib.ptps_load(self._h, path.encode(), 1 if clear else 0) != 0:
            raise IOError(f"native load from {path} failed")


def make_holder(capacity: int, num_internal_shards: int,
                prefer_native: bool = True, row_dtype: str = "fp32",
                capacity_bytes=None, hotness=None, spill_dir=None,
                spill_bytes=None, backend: Optional[str] = None):
    """The right holder for a storage policy, by capability negotiation
    (never by silent downgrade):

    - ``auto`` (default): the native C++ arena store when the loaded
      library's capabilities cover the policy; otherwise the Python
      arena holder, announced LOUDLY (an old pre-arena ``.so`` asked
      for fp16/byte-budget/spill lands here).
    - ``native``: require the native store (RuntimeError when the
      library is missing a needed capability).
    - ``arena``: force the Python arena holder.
    - ``python-legacy``: force the per-entry OrderedDict holder (the
      bench's A/B baseline).

    ``backend=None`` reads the ``PERSIA_PS_BACKEND`` knob;
    ``prefer_native=False`` maps ``auto`` to the Python arena holder.
    ``hotness`` arms the workload sketches on any backend (None = the
    PERSIA_HOTNESS knob)."""
    capacity_bytes = capacity_bytes or None  # 0 (config default) = off
    spill_dir = spill_dir or None
    row_dtype = row_dtype or "fp32"
    backend = backend or knobs.get("PERSIA_PS_BACKEND") or "auto"
    if backend not in ("auto", "native", "arena", "python-legacy"):
        raise ValueError(f"unknown PS backend {backend!r} (expected "
                         "auto|native|arena|python-legacy)")
    if backend == "auto" and not prefer_native:
        backend = "arena"

    def python_holder(cls):
        return cls(capacity, num_internal_shards, row_dtype=row_dtype,
                   capacity_bytes=capacity_bytes, hotness=hotness,
                   spill_dir=spill_dir, spill_bytes=spill_bytes or None)

    if backend == "python-legacy":
        from persia_tpu.ps.store import EmbeddingHolder

        return python_holder(EmbeddingHolder)
    from persia_tpu.ps.arena import ArenaEmbeddingHolder

    if backend == "arena":
        return python_holder(ArenaEmbeddingHolder)
    lib = load_native_lib()
    if lib is None:
        if backend == "native":
            raise RuntimeError(
                "PERSIA_PS_BACKEND=native but the native library is not "
                "available; run `make -C native`")
        _logger.warning("native store unavailable; using the Python arena "
                        "holder")
        return python_holder(ArenaEmbeddingHolder)
    missing = (required_capabilities(row_dtype, capacity_bytes, spill_dir)
               - native_capabilities(lib))
    if missing:
        msg = (f"loaded native library lacks {sorted(missing)} required by "
               f"the storage policy (row_dtype={row_dtype!r}, "
               f"capacity_bytes={capacity_bytes}, spill_dir={spill_dir!r})"
               " — rebuild `make -C native` for the arena-era store")
        if backend == "native":
            raise RuntimeError(msg)
        # negotiate down LOUDLY: the policy is honored, on the Python
        # arena holder — never silently dropped
        _logger.warning("%s; negotiating down to the Python arena holder",
                        msg)
        return python_holder(ArenaEmbeddingHolder)
    try:
        return NativeEmbeddingHolder(capacity, num_internal_shards,
                                     hotness=hotness, row_dtype=row_dtype,
                                     capacity_bytes=capacity_bytes,
                                     spill_dir=spill_dir,
                                     spill_bytes=spill_bytes or None)
    except RuntimeError:
        if backend == "native":
            raise
        _logger.warning("native store unavailable; using the Python arena "
                        "holder")
        return python_holder(ArenaEmbeddingHolder)
