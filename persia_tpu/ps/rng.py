"""Deterministic, seeded-by-sign entry initialization.

The reference initializes each new embedding entry from
``SmallRng::seed_from_u64(sign)`` (emb_entry.rs:35-57, seeded at
embedding_parameter_service/mod.rs:190-198) so a given sign always starts
from the same vector. We keep the seeded-by-sign contract but define our own
portable RNG spec so the Python (numpy) and C++ (native/src/rng.h) backends
produce **bit-identical** streams:

- state stream: ``state_k = sign + k * 0x9E3779B97F4A7C15`` (k >= 1)
- output: splitmix64 finalizer of ``state_k``
- u01: ``(output >> 11) * 2**-53`` (uniform in [0, 1), 53-bit)
- bounded_uniform(l, u): ``l + (u - l) * u01``
- normal: Box-Muller on consecutive (u1, u2) pairs, u1 clamped to 2**-53
- gamma: Marsaglia-Tsang (shape >= 1; boost by u**(1/shape) otherwise)
- poisson: Knuth product-of-uniforms

Admission control (admit_probability) also derives from the sign —
``u01(mix(sign ^ ADMIT_SALT)) < p`` — making admission deterministic and
replica-independent, where the reference used a thread-local RNG
(mod.rs:192). This is a deliberate reproducibility improvement.

All integer math is modulo 2**64.
"""

import math

import numpy as np

GOLDEN = 0x9E3779B97F4A7C15
ADMIT_SALT = 0x5851F42D4C957F2D
_U64 = np.uint64


def _mix_np(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized on uint64."""
    with np.errstate(over="ignore"):
        z = x.astype(np.uint64, copy=True)
        z = (z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)
        z ^= z >> _U64(31)
    return z


def _u01(bits: np.ndarray) -> np.ndarray:
    return (bits >> _U64(11)).astype(np.float64) * (2.0**-53)


def raw_stream(signs: np.ndarray, count: int) -> np.ndarray:
    """(n, count) matrix of u01 draws; row i is sign i's stream."""
    signs = signs.astype(np.uint64, copy=False)
    with np.errstate(over="ignore"):
        ks = (np.arange(1, count + 1, dtype=np.uint64)) * _U64(GOLDEN)
        states = signs[:, None] + ks[None, :]
    return _u01(_mix_np(states))


def admit_mask(signs: np.ndarray, admit_probability: float) -> np.ndarray:
    """Deterministic per-sign admission decision."""
    if admit_probability >= 1.0:
        return np.ones(len(signs), dtype=bool)
    with np.errstate(over="ignore"):
        salted = signs.astype(np.uint64) ^ _U64(ADMIT_SALT)
    return _u01(_mix_np(salted)) < admit_probability


def init_bounded_uniform(signs, dim, lower, upper) -> np.ndarray:
    u = raw_stream(signs, dim)
    return (lower + (upper - lower) * u).astype(np.float32)


def init_normal(signs, dim, mean, std) -> np.ndarray:
    pairs = (dim + 1) // 2
    u = raw_stream(signs, pairs * 2)
    u1 = np.maximum(u[:, 0::2], 2.0**-53)
    u2 = u[:, 1::2]
    r = np.sqrt(-2.0 * np.log(u1))
    z0 = r * np.cos(2.0 * math.pi * u2)
    z1 = r * np.sin(2.0 * math.pi * u2)
    z = np.empty((len(signs), pairs * 2))
    z[:, 0::2] = z0
    z[:, 1::2] = z1
    return (mean + std * z[:, :dim]).astype(np.float32)


class _ScalarStream:
    """Scalar view of the same stream, for the rejection-sampling inits."""

    def __init__(self, sign: int):
        self.sign = sign & 0xFFFFFFFFFFFFFFFF
        self.k = 0

    def next_u01(self) -> float:
        self.k += 1
        state = (self.sign + self.k * GOLDEN) & 0xFFFFFFFFFFFFFFFF
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        z ^= z >> 31
        return (z >> 11) * (2.0**-53)

    def next_normal(self) -> float:
        u1 = max(self.next_u01(), 2.0**-53)
        u2 = self.next_u01()
        return math.sqrt(-2.0 * math.log(u1)) * math.cos(2.0 * math.pi * u2)

    def next_gamma(self, shape: float) -> float:
        if shape < 1.0:
            u = max(self.next_u01(), 2.0**-53)
            return self.next_gamma(shape + 1.0) * u ** (1.0 / shape)
        d = shape - 1.0 / 3.0
        c = 1.0 / math.sqrt(9.0 * d)
        while True:
            x = self.next_normal()
            v = (1.0 + c * x) ** 3
            if v <= 0.0:
                continue
            u = max(self.next_u01(), 2.0**-53)
            if u < 1.0 - 0.0331 * x**4:
                return d * v
            if math.log(u) < 0.5 * x * x + d * (1.0 - v + math.log(v)):
                return d * v

    def next_poisson(self, lam: float) -> int:
        limit = math.exp(-lam)
        k = 0
        p = 1.0
        while True:
            k += 1
            p *= self.next_u01()
            if p <= limit:
                return k - 1


def init_gamma(signs, dim, shape, scale) -> np.ndarray:
    out = np.empty((len(signs), dim), dtype=np.float32)
    for i, s in enumerate(np.asarray(signs, dtype=np.uint64)):
        st = _ScalarStream(int(s))
        out[i] = [st.next_gamma(shape) * scale for _ in range(dim)]
    return out


def init_poisson(signs, dim, lam) -> np.ndarray:
    out = np.empty((len(signs), dim), dtype=np.float32)
    for i, s in enumerate(np.asarray(signs, dtype=np.uint64)):
        st = _ScalarStream(int(s))
        out[i] = [float(st.next_poisson(lam)) for _ in range(dim)]
    return out


def initialize_entries(signs: np.ndarray, dim: int, method: str, params: dict) -> np.ndarray:
    """Dispatch on the initialization method name (config.InitializationMethod)."""
    if method == "bounded_uniform":
        return init_bounded_uniform(signs, dim, params["lower"], params["upper"])
    if method == "normal" or method == "truncated_normal":
        # truncated_normal currently falls back to normal; the reference has
        # no truncated variant either (lib.rs:26-97).
        return init_normal(signs, dim, params["mean"], params["standard_deviation"])
    if method == "bounded_gamma":
        return init_gamma(signs, dim, params["shape"], params["scale"])
    if method == "bounded_poisson":
        return init_poisson(signs, dim, params["lambda"])
    if method == "zero":
        return np.zeros((len(signs), dim), dtype=np.float32)
    raise ValueError(f"unknown initialization method {method!r}")


def internal_shard_of(signs: np.ndarray, num_shards: int) -> np.ndarray:
    """Internal (in-process) shard pick — independent of the FarmHash
    process-level sharding (reference uses ahash here, sharded.rs:10-27)."""
    return (_mix_np(signs.astype(np.uint64)) % _U64(num_shards)).astype(np.int64)
