"""Arena-backed embedding parameter store: one contiguous row arena.

The per-entry :class:`~persia_tpu.ps.store.EmbeddingHolder` keeps every
row as its own numpy object inside an OrderedDict — at 10^7..10^9 rows
that is 10^7..10^9 tracked Python objects (the gen2 GC walks that forced
the ``PERSIA_PS_GC_TUNE`` workaround), ~100 bytes of per-entry overhead
on top of the data, and a per-sign interpreter loop on every batched
call. This module stores rows the way "Tensor Casting" (PAPERS.md)
treats embedding access — as a byte-addressed, layout-co-designed path:

- **Record classes.** Rows live in fixed-stride records grouped per
  ``(dim, optimizer state width)`` class. A record is ``[emb bytes
  (row_dtype) | pad to 4 | f32 optimizer state | pad to 8]``; the
  LOGICAL record (what PSD v2, the spill tier, and cross-backend parity
  see) is the unpadded ``[emb | state]`` — byte-identical with
  :class:`~persia_tpu.ps.optim.RowPrecision`'s layout and with
  ``native/src/store.h``'s arena, so all storage policies are
  implemented once over one byte layout.
- **Slab arena.** Each class owns ONE contiguous uint8 buffer grown in
  ``PERSIA_ARENA_SLAB_ROWS`` quanta (amortized-doubling realloc), with
  a free list recycling evicted slots. Strided numpy views expose the
  emb/state fields of ALL rows at once, so a batched lookup is one
  fancy-index gather and a batched update is one gather + one
  vectorized optimizer call + one scatter — no per-sign Python objects
  anywhere on the hot path. The buffers are plain (GC-invisible)
  ndarrays: a full GC walk costs the same whether the arena holds 10^3
  or 10^9 rows, and a shard is one memcpy-able byte range for live
  migration.
- **Flat sign index.** An open-addressing hash per shard maps sign ->
  packed ``(class, slot)``, probed for a whole batch in a handful of
  vectorized passes (the device-cache mapper's idiom); tombstoned
  deletes, rebuilt tombstone-free past 3/4 fill.
- **Exact LRU by stamp.** Every training access writes a per-shard
  monotone stamp; eviction pops the minimum-stamp row through a
  batch-frozen victim queue (cursor-skip on stale stamps,
  rebuild-on-exhaustion). Stamp order IS the OrderedDict recency order,
  so semantics — and the PSD v1 dump byte stream of an fp32 holder —
  match the per-entry holder exactly. When one batch could wrap a
  shard's whole row/byte budget (capacity smaller than a batch: the
  only case where batched insert-then-evict could diverge from the
  reference's per-sign sequence), the shard falls back to an exact
  sequential path.

Interface, semantics, serialization (PSD v1/v2), spill demotion, and
telemetry are all those of ``EmbeddingHolder`` — the two are
interchangeable, and ``ps.native.make_holder`` returns this holder for
the Python backend (``PERSIA_PS_BACKEND=python-legacy`` restores the
per-entry holder as an A/B lever).

Lock discipline: the holder owns nothing mutable; each ``_ArenaShard``
carries its own ``lock`` and every mutating shard method is suffixed
``_locked`` (caller holds ``shard.lock``) — the per-shard lock
convention persialint's lock pass checks.
"""

import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from persia_tpu.ps.optim import (
    RowPrecision,
    SparseOptimizer,
    apply_weight_bound,
)
from persia_tpu.ps.rng import admit_mask, initialize_entries, internal_shard_of
from persia_tpu.ps.store import DUMP_MAGIC, _DTYPE_CODES, iter_psd_records, \
    read_psd_header

_H_MULT = 0x9E3779B97F4A7C15  # fibonacci multiplier, splits u64 keys
_SLOT_BITS = 44  # packed index value: (class << 44) | slot
_SLOT_MASK = (1 << _SLOT_BITS) - 1


def _slab_rows() -> int:
    from persia_tpu import knobs

    return max(1024, int(knobs.get("PERSIA_ARENA_SLAB_ROWS")))


class _RowClass:
    """One record class: all rows of one ``(dim, state space)`` shape in
    one contiguous strided buffer plus parallel metadata arrays. All
    mutation happens under the owning shard's lock."""

    __slots__ = ("dim", "space", "np_dtype", "itemsize", "emb_bytes",
                 "emb_pad", "stride", "logical_bytes", "cap", "data", "emb",
                 "state", "signs", "stamps", "free", "next_fresh", "live",
                 "slab_rows")

    def __init__(self, dim: int, space: int, rp: RowPrecision,
                 slab_rows: int):
        self.dim = dim
        self.space = space
        self.np_dtype = rp.np_dtype
        self.itemsize = rp.itemsize
        self.emb_bytes = dim * rp.itemsize
        self.emb_pad = (self.emb_bytes + 3) & ~3
        self.stride = (self.emb_pad + 4 * space + 7) & ~7
        self.logical_bytes = self.emb_bytes + 4 * space
        self.slab_rows = slab_rows
        self.cap = 0
        self.data: Optional[np.ndarray] = None
        self.emb: Optional[np.ndarray] = None
        self.state: Optional[np.ndarray] = None
        self.signs: Optional[np.ndarray] = None
        self.stamps: Optional[np.ndarray] = None
        self.free: List[int] = []
        self.next_fresh = 0
        self.live = 0

    def _grow(self, need_rows: int):
        new_cap = max(self.cap * 2, self.slab_rows)
        while new_cap < need_rows:
            new_cap += self.slab_rows
        data = np.zeros(new_cap * self.stride, np.uint8)
        signs = np.zeros(new_cap, np.uint64)
        stamps = np.full(new_cap, -1, np.int64)
        if self.cap:
            data[: self.cap * self.stride] = self.data
            signs[: self.cap] = self.signs
            stamps[: self.cap] = self.stamps
        self.cap = new_cap
        self.data = data
        self.signs = signs
        self.stamps = stamps
        self.emb = np.ndarray((new_cap, self.dim), dtype=self.np_dtype,
                              buffer=data, strides=(self.stride,
                                                    self.itemsize))
        self.state = (np.ndarray((new_cap, self.space), dtype=np.float32,
                                 buffer=data, offset=self.emb_pad,
                                 strides=(self.stride, 4))
                      if self.space else None)

    def alloc_locked(self, k: int) -> np.ndarray:
        """k fresh/recycled slot ids (free list LIFO first)."""
        out = np.empty(k, np.int64)
        reuse = min(k, len(self.free))
        for i in range(reuse):
            out[i] = self.free.pop()
        fresh = k - reuse
        if fresh:
            if self.next_fresh + fresh > self.cap:
                self._grow(self.next_fresh + fresh)
            out[reuse:] = np.arange(self.next_fresh,
                                    self.next_fresh + fresh)
            self.next_fresh += fresh
        self.live += k
        return out

    def free_locked(self, slot: int):
        self.stamps[slot] = -1
        self.free.append(slot)
        self.live -= 1

    def logical_rows_locked(self, slots: np.ndarray) -> np.ndarray:
        """Extract the logical ``[emb bytes | state f32 bytes]`` records
        of ``slots`` as one (k, logical_bytes) uint8 matrix (two
        vectorized field copies — the spill tier's slab-slice demotion
        path and the checkpoint's record source)."""
        k = len(slots)
        out = np.empty((k, self.logical_bytes), np.uint8)
        out[:, : self.emb_bytes] = (
            np.ascontiguousarray(self.emb[slots]).view(np.uint8))
        if self.space:
            out[:, self.emb_bytes:] = (
                np.ascontiguousarray(self.state[slots]).view(np.uint8))
        return out

    def write_raw_locked(self, slot: int, raw: np.ndarray):
        """Store a logical record byte-exactly (spill fault-in /
        cross-backend record import)."""
        self.emb[slot] = raw[: self.emb_bytes].view(self.np_dtype)
        if self.space:
            self.state[slot] = raw[self.emb_bytes:].view(np.float32)

    def slab_bytes(self) -> int:
        return self.cap * self.stride


class _ArenaShard:
    """One internal shard: its record classes, flat sign index, stamp
    clock, victim queue, and byte accounting. ``lock`` is acquired by
    the HOLDER around every ``*_locked`` call (the arena's per-shard
    lock convention)."""

    def __init__(self, capacity: int, byte_capacity: Optional[int],
                 rp: RowPrecision, slab_rows: int, index_slots: int):
        self.lock = threading.Lock()
        self.capacity = capacity
        self.byte_capacity = byte_capacity
        self.rp = rp
        self.slab_rows = slab_rows
        self.classes: List[_RowClass] = []
        self._class_of: Dict[Tuple[int, int], int] = {}
        self.resident_bytes = 0
        self.emb_bytes = 0
        self.clock = 0
        # open-addressing sign -> packed (class << 44 | slot); value -1
        # empty, -2 tombstone (sign 0 is a legal key)
        size = 8
        while size < index_slots:
            size <<= 1
        self._h_size = size
        self._h_mask = size - 1
        self._h_shift = 65 - size.bit_length()
        self._h_sign = np.zeros(size, np.uint64)
        self._h_val = np.full(size, -1, np.int64)
        self._h_fill = 0  # occupied + tombstones (bounds probe chains)
        # batch-frozen victim queue (stamp-ascending), cursor-skip on
        # stale stamps, rebuilt on exhaustion
        self._vq_cls: Optional[np.ndarray] = None
        self._vq_slot: Optional[np.ndarray] = None
        self._vq_stamp: Optional[np.ndarray] = None
        self._vq_cursor = 0

    # --- record classes -------------------------------------------------

    def class_id_locked(self, dim: int, space: int,
                        create: bool = True) -> Optional[int]:
        cid = self._class_of.get((dim, space))
        if cid is None and create:
            cid = len(self.classes)
            self.classes.append(_RowClass(dim, space, self.rp,
                                          self.slab_rows))
            self._class_of[(dim, space)] = cid
        return cid

    def live_rows(self) -> int:
        return sum(c.live for c in self.classes)

    # --- flat sign index ------------------------------------------------

    def probe_locked(self, keys: np.ndarray) -> np.ndarray:
        """Bulk lookup: packed int64 value per key, -1 for absent. Each
        round resolves every key whose probe cell is a hit or a virgin
        empty; mismatches and tombstones advance one cell."""
        mask = self._h_mask
        out = np.full(len(keys), -1, np.int64)
        idx = ((keys * np.uint64(_H_MULT))
               >> np.uint64(self._h_shift)).astype(np.int64)
        pend = np.arange(len(keys))
        kp = keys
        h_val, h_sign = self._h_val, self._h_sign
        while len(pend):
            v = h_val[idx]
            found = (v >= 0) & (h_sign[idx] == kp)
            if found.any():
                out[pend[found]] = v[found]
            cont = ~found & (v != -1)
            pend = pend[cont]
            kp = kp[cont]
            idx = (idx[cont] + 1) & mask
        return out

    def _h_find(self, sign: int) -> int:
        mask = self._h_mask
        h_val, h_sign = self._h_val, self._h_sign
        i = ((sign * _H_MULT) & 0xFFFFFFFFFFFFFFFF) >> self._h_shift
        while True:
            v = h_val[i]
            if v == -1:
                return -1
            if v >= 0 and h_sign[i] == sign:
                return i
            i = (i + 1) & mask

    def index_put_locked(self, sign: int, packed: int):
        """Insert/overwrite one index entry (scalar; callers loop —
        insert batches are the cold fill/eviction paths)."""
        i = self._h_find(sign)
        if i >= 0:
            self._h_val[i] = packed
            return
        mask = self._h_mask
        h_val = self._h_val
        i = ((sign * _H_MULT) & 0xFFFFFFFFFFFFFFFF) >> self._h_shift
        while h_val[i] >= 0:
            i = (i + 1) & mask
        if h_val[i] == -1:
            self._h_fill += 1
        self._h_sign[i] = sign
        h_val[i] = packed
        if 4 * self._h_fill > 3 * self._h_size:
            self._h_rebuild_locked()

    def index_del_locked(self, sign: int):
        i = self._h_find(sign)
        if i >= 0:
            self._h_val[i] = -2  # tombstone

    def _h_rebuild_locked(self):
        """Grow/compact the index from its own LIVE entries — never
        from stamps: the batched insert path stamps rows only after
        all its index inserts, so a mid-batch rebuild keyed on stamps
        would silently drop every row inserted earlier in that batch
        (ghost rows: allocated + accounted but unreachable)."""
        old_sign, old_val = self._h_sign, self._h_val
        sel = np.nonzero(old_val >= 0)[0]
        live = len(sel)
        size = self._h_size
        while size < 4 * max(live, 1):
            size <<= 1
        self._h_size = size
        self._h_mask = size - 1
        self._h_shift = 65 - size.bit_length()
        self._h_sign = np.zeros(size, np.uint64)
        self._h_val = np.full(size, -1, np.int64)
        h_sign, h_val = self._h_sign, self._h_val
        mask = self._h_mask
        for sign, val in zip(old_sign[sel].tolist(),
                             old_val[sel].tolist()):
            i = ((sign * _H_MULT) & 0xFFFFFFFFFFFFFFFF) \
                >> self._h_shift
            while h_val[i] >= 0:
                i = (i + 1) & mask
            h_sign[i] = sign
            h_val[i] = val
        self._h_fill = live

    # --- stamps / eviction ----------------------------------------------

    def stamp_batch_locked(self, cls_ids: np.ndarray, slots: np.ndarray,
                           has_dups: bool):
        """Refresh recency for the accessed rows, in access order (the
        OrderedDict move-to-end sequence). Duplicate positions keep the
        LAST occurrence's stamp via maximum.at (stamps grow with batch
        position)."""
        n = len(slots)
        if n == 0:
            return
        stamps = np.arange(self.clock, self.clock + n, dtype=np.int64)
        self.clock += n
        for cid in np.unique(cls_ids):
            m = cls_ids == cid
            cls = self.classes[cid]
            if has_dups:
                np.maximum.at(cls.stamps, slots[m], stamps[m])
            else:
                cls.stamps[slots[m]] = stamps[m]

    def stamp_one_locked(self, cls_id: int, slot: int):
        self.classes[cls_id].stamps[slot] = self.clock
        self.clock += 1

    def _vq_rebuild_locked(self):
        parts = []
        for cid, cls in enumerate(self.classes):
            rows = np.nonzero(cls.stamps[: cls.next_fresh] >= 0)[0]
            if len(rows):
                parts.append((np.full(len(rows), cid, np.int64), rows,
                              cls.stamps[rows]))
        if not parts:
            self._vq_cls = self._vq_slot = self._vq_stamp = \
                np.empty(0, np.int64)
            self._vq_cursor = 0
            return
        cls_ids = np.concatenate([p[0] for p in parts])
        slots = np.concatenate([p[1] for p in parts])
        stamps = np.concatenate([p[2] for p in parts])
        order = np.argsort(stamps, kind="stable")
        self._vq_cls = cls_ids[order]
        self._vq_slot = slots[order]
        self._vq_stamp = stamps[order]
        self._vq_cursor = 0

    def pop_victim_locked(self) -> Optional[Tuple[int, int]]:
        """(class, slot) of the least-recently-stamped live row; None
        when the shard is empty. Stale queue entries (row refreshed or
        freed since the freeze) are skipped by stamp comparison."""
        for _ in range(2):  # current queue, then one rebuild
            if self._vq_stamp is not None:
                vq_stamp, vq_cls, vq_slot = (self._vq_stamp, self._vq_cls,
                                             self._vq_slot)
                i = self._vq_cursor
                n = len(vq_stamp)
                while i < n:
                    cid = vq_cls[i]
                    slot = vq_slot[i]
                    if self.classes[cid].stamps[slot] == vq_stamp[i]:
                        self._vq_cursor = i + 1
                        return int(cid), int(slot)
                    i += 1
                self._vq_cursor = n
            if self.live_rows() == 0:
                return None
            self._vq_rebuild_locked()
        return None

    def over_budget_locked(self, floor_rows: int = 0) -> bool:
        live = self.live_rows()
        return live > self.capacity or (
            self.byte_capacity is not None
            and self.resident_bytes > self.byte_capacity
            and live > max(1, floor_rows))

    def evict_locked(self, spill_rows: Optional[List]) -> int:
        """Restore the row/byte budget; returns rows evicted. With
        ``spill_rows`` a list, evicted rows are appended as
        ``(sign, dim, cls_id, slot)`` for the caller's grouped spill
        demotion (``extract_spill_locked``) — a freed slot keeps its
        bytes until reallocated, so extraction right after is exact."""
        evicted = 0
        while self.over_budget_locked():
            victim = self.pop_victim_locked()
            if victim is None:
                break
            cid, slot = victim
            cls = self.classes[cid]
            sign = int(cls.signs[slot])
            self.index_del_locked(sign)
            self.resident_bytes -= cls.logical_bytes
            self.emb_bytes -= cls.emb_bytes
            if spill_rows is not None:
                spill_rows.append((sign, cls.dim, cid, slot))
            cls.free_locked(slot)
            evicted += 1
        return evicted

    def free_entry_locked(self, cid: int, slot: int):
        """Release one live row (dim-mismatch reinit path)."""
        cls = self.classes[cid]
        self.resident_bytes -= cls.logical_bytes
        self.emb_bytes -= cls.emb_bytes
        cls.free_locked(slot)

    def extract_spill_locked(self, spill_rows: List):
        """Group the rows ``evict_locked`` collected per class and
        extract their logical bytes in one vectorized pass per class:
        [(signs u64 array, dim, (k, logical) uint8 matrix), ...].
        Valid only immediately after eviction — freed slots keep their
        bytes until reallocated."""
        out = []
        by_class: Dict[int, List[Tuple[int, int]]] = {}
        for sign, dim, cid, slot in spill_rows:
            by_class.setdefault(cid, []).append((sign, slot))
        for cid, pairs in by_class.items():
            cls = self.classes[cid]
            signs = np.array([p[0] for p in pairs], np.uint64)
            slots = np.array([p[1] for p in pairs], np.int64)
            out.append((signs, cls.dim, cls.logical_rows_locked(slots)))
        return out

    # --- scalar row ops (fallback / debug paths) ------------------------

    def get_locked(self, sign: int) -> Optional[Tuple[int, int]]:
        packed = self._h_find(sign)
        if packed < 0:
            return None
        v = int(self._h_val[packed])
        return v >> _SLOT_BITS, v & _SLOT_MASK

    def insert_row_locked(self, sign: int, dim: int, full_f32: np.ndarray,
                          raw: Optional[np.ndarray] = None) -> Tuple[int,
                                                                     int]:
        """Insert/replace one row (refreshing recency), WITHOUT budget
        enforcement — the caller runs eviction after. ``raw`` given
        stores logical bytes exactly; else ``full_f32`` narrows in."""
        space = (len(raw) - dim * self.rp.itemsize) // 4 if raw is not None \
            else len(full_f32) - dim
        cid = self.class_id_locked(dim, space)
        cls = self.classes[cid]
        existing = self.get_locked(sign)
        if existing is not None and existing[0] == cid:
            slot = existing[1]
        else:
            if existing is not None:
                ocls = self.classes[existing[0]]
                self.resident_bytes -= ocls.logical_bytes
                self.emb_bytes -= ocls.emb_bytes
                ocls.free_locked(existing[1])
            slot = int(cls.alloc_locked(1)[0])
            cls.signs[slot] = sign
            self.index_put_locked(sign, (cid << _SLOT_BITS) | slot)
            self.resident_bytes += cls.logical_bytes
            self.emb_bytes += cls.emb_bytes
        if raw is not None:
            cls.write_raw_locked(slot, raw)
        else:
            cls.emb[slot] = full_f32[:dim]
            if cls.space:
                cls.state[slot] = full_f32[dim:]
        self.stamp_one_locked(cid, slot)
        return cid, slot

    def stats_locked(self) -> Dict[str, int]:
        allocated = sum(c.next_fresh for c in self.classes)
        return {
            "slab_bytes": sum(c.slab_bytes() for c in self.classes),
            "free_slots": sum(len(c.free) for c in self.classes),
            "live_rows": self.live_rows(),
            "allocated_rows": allocated,
            "resident_bytes": self.resident_bytes,
        }


class ArenaEmbeddingHolder:
    """Drop-in twin of :class:`~persia_tpu.ps.store.EmbeddingHolder`
    over the contiguous row arena (module docstring has the layout).
    Same constructor policy surface: ``row_dtype`` narrows the stored
    embedding slice, ``capacity_bytes`` arms byte-accounted eviction,
    ``spill_dir`` demotes evictions to the disk tier, ``hotness`` arms
    the workload sketches."""

    releases_gil = False

    def __init__(self, capacity: int = 1_000_000_000,
                 num_internal_shards: int = 8, row_dtype: str = "fp32",
                 capacity_bytes: Optional[int] = None,
                 hotness: Optional[bool] = None,
                 spill_dir: Optional[str] = None,
                 spill_bytes: Optional[int] = None):
        if num_internal_shards <= 0:
            raise ValueError("num_internal_shards must be positive")
        from persia_tpu import knobs

        capacity_bytes = capacity_bytes or None
        self.capacity = capacity
        self.capacity_bytes = capacity_bytes
        self.num_internal_shards = num_internal_shards
        self._rp = RowPrecision(row_dtype)
        per_shard = max(1, capacity // num_internal_shards)
        per_shard_bytes = (
            max(1, capacity_bytes // num_internal_shards)
            if capacity_bytes is not None else None)
        slab_rows = _slab_rows()
        index_slots = max(8, int(knobs.get("PERSIA_ARENA_INDEX_SLOTS")))
        self._shards = [
            _ArenaShard(per_shard, per_shard_bytes, self._rp, slab_rows,
                        index_slots)
            for _ in range(num_internal_shards)
        ]
        self.optimizer: Optional[SparseOptimizer] = None
        self.init_method: str = "bounded_uniform"
        self.init_params: dict = {"lower": -0.01, "upper": 0.01}
        self.admit_probability: float = 1.0
        self.weight_bound: float = 10.0
        self.enable_weight_bound: bool = True
        self.configured = False
        self._index_miss = [0] * num_internal_shards
        self._gradient_id_miss = [0] * num_internal_shards
        self._miss_counters: Dict[Tuple[str, int], object] = {}
        from persia_tpu import hotness as _hotness

        self.hotness = _hotness.make_tracker(num_internal_shards,
                                             enabled=hotness)
        if spill_dir:
            from persia_tpu.ps.spill import SpillStore

            self.spill: Optional["SpillStore"] = SpillStore(
                spill_dir, max_bytes=spill_bytes or None)
        else:
            self.spill = None

    # --- mirrored observables -------------------------------------------

    @property
    def row_dtype(self) -> str:
        return self._rp.name

    @property
    def resident_bytes(self) -> int:
        return sum(s.resident_bytes for s in self._shards)

    @property
    def resident_emb_bytes(self) -> int:
        return sum(s.emb_bytes for s in self._shards)

    def resident_bytes_per_shard(self) -> List[int]:
        return [s.resident_bytes for s in self._shards]

    def row_nbytes(self, dim: int) -> int:
        space = self.optimizer.require_space(dim) if self.optimizer else 0
        return self._rp.entry_nbytes(dim, space)

    @property
    def index_miss_count(self) -> int:
        return sum(self._index_miss)

    @property
    def gradient_id_miss_count(self) -> int:
        return sum(self._gradient_id_miss)

    def arena_stats(self) -> Dict[str, int]:
        """Aggregated slab accounting for the ``ps_arena_*`` gauges:
        allocated slab bytes, reusable free slots, live rows, logical
        resident bytes, and the fragmentation ratio (1 - live/allocated
        rows — eviction-churned slots not yet refilled)."""
        totals = {"slab_bytes": 0, "free_slots": 0, "live_rows": 0,
                  "allocated_rows": 0, "resident_bytes": 0}
        for shard in self._shards:
            with shard.lock:
                for k, v in shard.stats_locked().items():
                    totals[k] += v
        alloc = totals.pop("allocated_rows")
        totals["fragmentation_ratio"] = (
            round(1.0 - totals["live_rows"] / alloc, 6) if alloc else 0.0)
        return totals

    def _bump_miss(self, kind: str, dim: int, n: int):
        # racing first-use builds the cell twice; the registry dedups by
        # (name, labels), so both writers land on the same Counter
        key = (kind, dim)
        c = self._miss_counters.get(key)
        if c is None:
            from persia_tpu.metrics import default_registry

            c = self._miss_counters[key] = default_registry().counter(
                f"ps_{kind}_total", {"table": str(dim)},
                help_text=(
                    "eval/unadmitted/cold lookups that read zeros, per "
                    "embedding table (dim)" if kind == "index_miss" else
                    "gradient updates whose sign was absent or "
                    "re-laid-out, per embedding table (dim)"))
        c.inc(n)

    def hotness_snapshot(self) -> dict:
        from persia_tpu import hotness as _hotness

        if self.hotness is None:
            return _hotness.disabled_snapshot()
        snap = self.hotness.snapshot()
        for table, t in snap.get("tables", {}).items():
            t["row_bytes"] = int(table) * self._rp.itemsize
        return snap

    def spill_stats(self) -> dict:
        return self.spill.stats() if self.spill is not None else {}

    # --- control plane ---------------------------------------------------

    def configure(self, init_method: str, init_params: dict,
                  admit_probability: float = 1.0, weight_bound: float = 10.0,
                  enable_weight_bound: bool = True):
        self.init_method = init_method
        self.init_params = dict(init_params)
        self.admit_probability = admit_probability
        self.weight_bound = weight_bound
        self.enable_weight_bound = enable_weight_bound
        self.configured = True

    def register_optimizer(self, config: dict,
                           feature_index_prefix_bit: int = 0):
        self.optimizer = SparseOptimizer.from_config(
            config, feature_index_prefix_bit=feature_index_prefix_bit)

    # --- spill helpers ---------------------------------------------------

    def _demote_locked(self, shard: _ArenaShard, spill_rows: List):
        """Push the rows eviction collected down to the disk tier
        (slab-slice extraction, one vectorized pass per class)."""
        if not spill_rows:
            return
        for signs, dim, rows in shard.extract_spill_locked(spill_rows):
            self.spill.put_batch(signs, dim, rows)

    def _evict_and_spill_locked(self, shard: _ArenaShard):
        if self.spill is None:
            shard.evict_locked(None)
            return
        spill_rows: List = []
        shard.evict_locked(spill_rows)
        self._demote_locked(shard, spill_rows)

    def _fault_in_locked(self, shard: _ArenaShard, sign: int,
                         training: bool):
        """Transparent fault-in of a spilled row (same contract as the
        per-entry holder: training TAKES and re-inserts resident,
        read-only PEEKS). Returns ``(dim, raw logical bytes)`` or
        None."""
        got = (self.spill.take(sign) if training
               else self.spill.peek(sign))
        if got is None:
            return None
        dim0, raw = got
        if training:
            shard.insert_row_locked(sign, dim0, None, raw=raw)
            self._evict_and_spill_locked(shard)
        return dim0, raw

    # --- data plane -------------------------------------------------------

    def lookup(self, signs: np.ndarray, dim: int,
               training: bool) -> np.ndarray:
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        out = np.zeros((n, dim), dtype=np.float32)
        if n == 0:
            return out
        if training:
            if self.optimizer is None:
                raise RuntimeError(
                    "optimizer not registered on parameter server")
            if not self.configured:
                raise RuntimeError("parameter server not configured")
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        if self.hotness is not None:
            # outside the shard locks: the tracker owns its own leaf
            # locks, so lookup hold times and lock order are untouched
            self.hotness.observe(dim, signs)
        if training:
            space = self.optimizer.require_space(dim)
            admitted = admit_mask(signs, self.admit_probability)
            init_vecs = np.zeros((n, dim + space), dtype=np.float32)
            init_vecs[:, :dim] = initialize_entries(
                signs, dim, self.init_method, self.init_params)
            if space:
                self.optimizer.state_initialization(init_vecs, dim)
        else:
            space = 0
            admitted = init_vecs = None
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            with shard.lock:
                if training:
                    n_miss = self._lookup_train_locked(
                        shard, signs[sel], sel, dim, space, init_vecs,
                        admitted, out)
                else:
                    n_miss = self._lookup_eval_locked(
                        shard, signs[sel], sel, dim, out)
            if n_miss:
                self._index_miss[shard_idx] += n_miss
                self._bump_miss("index_miss", dim, n_miss)
        return out

    def _lookup_train_locked(self, shard, ssigns, sel, dim, space,
                             init_vecs, admitted, out) -> int:
        cid = shard.class_id_locked(dim, space)
        cls = shard.classes[cid]
        # duplicate signs must see each other's inserts: exact
        # sequential path
        if len(np.unique(ssigns)) != len(ssigns):
            return self._lookup_train_seq_locked(
                shard, ssigns, sel, dim, space, init_vecs, admitted, out)
        packed = shard.probe_locked(ssigns)
        p_cls = packed >> _SLOT_BITS
        p_slot = packed & _SLOT_MASK
        # a hit is any resident class of the SAME dim (state width may
        # differ under an older optimizer layout — still a read hit,
        # like the per-entry holder's `entry[0] == dim` check)
        hit = np.zeros(len(ssigns), bool)
        for ocid in np.unique(p_cls[packed >= 0]):
            ocls = shard.classes[ocid]
            if ocls.dim != dim:
                continue
            m = (packed >= 0) & (p_cls == ocid)
            out[sel[m]] = ocls.emb[p_slot[m]]
            hit |= m
        # Batched insert-then-evict is only sequence-exact while the
        # batch evicts NOTHING: a mid-batch eviction in the reference's
        # per-sign order can claim a row this batch reads later (turning
        # its hit into a reinit). Pessimistic pre-check — any possible
        # insert pushing past the row/byte budget — reruns the shard's
        # batch on the exact sequential path instead (nothing has been
        # stamped or inserted yet; hit rows were only read). Hit-only
        # steady batches and the pre-capacity fill never take this.
        n_nonhit = int((~hit).sum())
        # byte pessimism covers spill fault-ins too: a faulted row may
        # belong to a WIDER class than this lookup's inserts
        worst_row = cls.logical_bytes
        if self.spill is not None and shard.byte_capacity is not None:
            worst_row = max(worst_row,
                            max((c.logical_bytes
                                 for c in shard.classes), default=0))
        if n_nonhit and (
                shard.live_rows() + n_nonhit > shard.capacity
                or (shard.byte_capacity is not None
                    and shard.resident_bytes + n_nonhit * worst_row
                    > shard.byte_capacity)):
            return self._lookup_train_seq_locked(
                shard, ssigns, sel, dim, space, init_vecs, admitted, out)
        # resident under another dim: reference semantics reinitialize
        # unconditionally (admission does not apply to dim mismatches)
        stale = (packed >= 0) & ~hit
        if self.spill is not None and (~hit & ~stale).any():
            # fault spilled rows back in BEFORE deciding miss-init; a
            # faulted row of the right dim becomes a plain (read) hit
            for j in np.nonzero(~hit & ~stale)[0]:
                got = self._fault_in_locked(shard, int(ssigns[j]), True)
                if got is None:
                    continue
                dim0, _raw = got
                loc = shard.get_locked(int(ssigns[j]))
                if loc is None:
                    continue
                if dim0 == dim:
                    hit[j] = True
                    p_cls[j], p_slot[j] = loc
                    packed[j] = (loc[0] << _SLOT_BITS) | loc[1]
                    out[sel[j]] = shard.classes[loc[0]].emb[loc[1]]
                else:  # spilled under another dim: reinitialize
                    stale[j] = True
                    p_cls[j], p_slot[j] = loc
                    packed[j] = (loc[0] << _SLOT_BITS) | loc[1]
        miss = ~hit & (admitted[sel] | stale)
        zeros = ~hit & ~miss
        n_miss = 0
        miss_idx = np.nonzero(miss)[0]
        if len(miss_idx):
            n_miss += len(miss_idx)
            if self.spill is not None:
                # the about-to-be-resident signs must not shadow stale
                # disk copies (ladder invariant)
                for s in ssigns[miss_idx].tolist():
                    self.spill.discard(s)
            # dim-mismatched residents release their old slots first
            for j in np.nonzero(stale)[0].tolist():
                shard.free_entry_locked(int(p_cls[j]), int(p_slot[j]))
            rows = cls.alloc_locked(len(miss_idx))
            cls.emb[rows] = init_vecs[sel[miss_idx], :dim]
            if space:
                cls.state[rows] = init_vecs[sel[miss_idx], dim:]
            cls.signs[rows] = ssigns[miss_idx]
            base = cid << _SLOT_BITS
            for s, r in zip(ssigns[miss_idx].tolist(), rows.tolist()):
                shard.index_put_locked(s, base | r)
            shard.resident_bytes += len(miss_idx) * cls.logical_bytes
            shard.emb_bytes += len(miss_idx) * cls.emb_bytes
            # caller reads the STORED value (narrow-then-widen), so a
            # lookup right after the miss reads what later lookups will
            out[sel[miss_idx]] = cls.emb[rows]
            p_cls[miss_idx] = cid
            p_slot[miss_idx] = rows
        n_miss += int(zeros.sum())
        touched = hit | miss
        shard.stamp_batch_locked(p_cls[touched], p_slot[touched],
                                 has_dups=False)
        self._evict_and_spill_locked(shard)
        return n_miss

    def _lookup_train_seq_locked(self, shard, ssigns, sel, dim, space,
                                 init_vecs, admitted, out) -> int:
        """Exact per-sign sequence (duplicates and batch-wraps-capacity
        cases): each access sees every earlier access's insertions and
        evictions, like the per-entry and native stores."""
        cid = shard.class_id_locked(dim, space)
        cls = shard.classes[cid]
        n_miss = 0
        for j, pos in enumerate(sel.tolist()):
            sign = int(ssigns[j])
            loc = shard.get_locked(sign)
            if loc is None and self.spill is not None:
                if self._fault_in_locked(shard, sign, True) is not None:
                    loc = shard.get_locked(sign)
            if loc is not None and shard.classes[loc[0]].dim == dim:
                out[pos] = shard.classes[loc[0]].emb[loc[1]]
                shard.stamp_one_locked(loc[0], loc[1])
            elif loc is None and not admitted[pos]:
                n_miss += 1
            else:
                if self.spill is not None:
                    self.spill.discard(sign)
                shard.insert_row_locked(sign, dim, init_vecs[pos])
                loc = shard.get_locked(sign)
                out[pos] = cls.emb[loc[1]]
                self._evict_and_spill_locked(shard)
                n_miss += 1
        return n_miss

    def _lookup_eval_locked(self, shard, ssigns, sel, dim, out) -> int:
        packed = shard.probe_locked(ssigns)
        p_cls = packed >> _SLOT_BITS
        p_slot = packed & _SLOT_MASK
        n_miss = 0
        hits_by_cls: Dict[int, np.ndarray] = {}
        for cid in np.unique(p_cls[packed >= 0]):
            cls = shard.classes[cid]
            if cls.dim != dim:
                continue
            m = (packed >= 0) & (p_cls == cid)
            out[sel[m]] = cls.emb[p_slot[m]]
            hits_by_cls[int(cid)] = m
        hit_any = np.zeros(len(ssigns), bool)
        for m in hits_by_cls.values():
            hit_any |= m
        missing = ~hit_any
        if self.spill is not None and missing.any():
            for j in np.nonzero(missing)[0]:
                got = self._fault_in_locked(shard, int(ssigns[j]), False)
                if got is not None and got[0] == dim:
                    raw = got[1]
                    emb = raw[: dim * self._rp.itemsize] \
                        .view(self._rp.np_dtype)
                    out[sel[j]] = emb.astype(np.float32, copy=False)
                    missing[j] = False
        n_miss += int(missing.sum())
        return n_miss

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray,
                         dim: int):
        if self.optimizer is None:
            raise RuntimeError("optimizer not registered on parameter server")
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        if n == 0:
            return
        batch_state = self.optimizer.batch_level_state(signs)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        space = self.optimizer.require_space(dim)
        width = dim + space
        has_dups = len(np.unique(signs)) != len(signs)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            with shard.lock:
                n_miss = self._update_locked(
                    shard, signs[sel], sel, grads, dim, space, width,
                    batch_state, has_dups)
            if n_miss:
                self._gradient_id_miss[shard_idx] += n_miss
                self._bump_miss("gradient_id_miss", dim, n_miss)

    def _update_locked(self, shard, ssigns, sel, grads, dim, space, width,
                       batch_state, has_dups) -> int:
        packed = shard.probe_locked(ssigns)
        n_miss = 0
        if self.spill is not None:
            # gradient for a spilled row: fault it in and apply — a
            # demotion must not turn updates into misses. Each fault-in
            # may EVICT other rows (whose freed slots can be
            # reallocated), so the whole batch re-probes afterwards —
            # a slot gathered through the pre-fault probe could belong
            # to a different row by now. Two rounds: a fault-in's own
            # eviction can demote a sign later in this batch (the
            # sequential reference faults it back at its position).
            for _ in range(2):
                missing = np.nonzero(packed < 0)[0]
                faulted = False
                for j in missing:
                    if self._fault_in_locked(shard, int(ssigns[j]),
                                             True) is not None:
                        faulted = True
                if not faulted:
                    break
                packed = shard.probe_locked(ssigns)
        cid = shard.class_id_locked(dim, space, create=False)
        if cid is None:
            return len(ssigns)
        found = (packed >= 0) & ((packed >> _SLOT_BITS) == cid)
        n_miss += int((~found).sum())
        if not found.any():
            return n_miss
        cls = shard.classes[cid]
        rows = (packed & _SLOT_MASK)[found]
        pos = sel[found]
        if has_dups:
            # duplicates apply sequentially (each step sees the
            # previous one's result, like the reference)
            mat = np.empty((1, width), np.float32)
            for r, p in zip(rows.tolist(), pos.tolist()):
                mat[0, :dim] = cls.emb[r]
                if space:
                    mat[0, dim:] = cls.state[r]
                st = (batch_state[p: p + 1]
                      if batch_state is not None else None)
                self.optimizer.update(mat, grads[p: p + 1], dim, st)
                if self.enable_weight_bound:
                    apply_weight_bound(mat[:, :dim], self.weight_bound)
                cls.emb[r] = mat[0, :dim]
                if space:
                    cls.state[r] = mat[0, dim:]
            return n_miss
        # fast path: one gather, one batched optimizer call, one
        # scatter — all strided-vectorized over the slab
        mat = np.empty((len(rows), width), np.float32)
        mat[:, :dim] = cls.emb[rows]
        if space:
            mat[:, dim:] = cls.state[rows]
        sub_state = (batch_state[pos]
                     if batch_state is not None else None)
        self.optimizer.update(mat, grads[pos], dim, sub_state)
        if self.enable_weight_bound:
            apply_weight_bound(mat[:, :dim], self.weight_bound)
        cls.emb[rows] = mat[:, :dim]
        if space:
            cls.state[rows] = mat[:, dim:]
        return n_miss

    # --- debug / checkpoint ----------------------------------------------

    def get_entry(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        """(dim, f32 [emb|state]) or None — widened fresh copy (half)
        or a live f32 view over the arena record (fp32, the legacy
        mutate-in-place contract; like the native store's Entry
        pointer, the view is valid until the next insert — arena
        growth reallocates the slab). Spilled rows read through
        (peek)."""
        shard_idx = int(internal_shard_of(
            np.array([sign], dtype=np.uint64), self.num_internal_shards)[0])
        shard = self._shards[shard_idx]
        with shard.lock:
            loc = shard.get_locked(int(sign))
            if loc is None and self.spill is not None:
                got = self._fault_in_locked(shard, int(sign), False)
                if got is not None:
                    dim0, raw = got
                    rp = self._rp
                    vec = np.empty(dim0 + (len(raw) - dim0 * rp.itemsize)
                                   // 4, np.float32)
                    vec[:dim0] = raw[: dim0 * rp.itemsize] \
                        .view(rp.np_dtype).astype(np.float32)
                    vec[dim0:] = raw[dim0 * rp.itemsize:].view(np.float32)
                    return dim0, vec
                return None
            if loc is None:
                return None
            cid, slot = loc
            cls = shard.classes[cid]
            if self._rp.is_fp32:
                # fp32 records are contiguous f32 [emb | state]: hand
                # out the live arena row, like the per-entry holder
                vec = np.ndarray((cls.dim + cls.space,), np.float32,
                                 buffer=cls.data,
                                 offset=slot * cls.stride)
                return cls.dim, vec
            vec = np.empty(cls.dim + cls.space, np.float32)
            vec[: cls.dim] = cls.emb[slot]
            if cls.space:
                vec[cls.dim:] = cls.state[slot]
            return cls.dim, vec

    def set_entry(self, sign: int, dim: int, vec: np.ndarray):
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        shard_idx = int(internal_shard_of(
            np.array([sign], dtype=np.uint64), self.num_internal_shards)[0])
        shard = self._shards[shard_idx]
        with shard.lock:
            if self.spill is not None:
                self.spill.discard(int(sign))
            shard.insert_row_locked(int(sign), dim, vec)
            self._evict_and_spill_locked(shard)

    def get_entries(self, signs: np.ndarray, width: int):
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        found = np.zeros(n, dtype=bool)
        vecs = np.zeros((n, width), dtype=np.float32)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            with shard.lock:
                packed = shard.probe_locked(signs[sel])
                p_cls = packed >> _SLOT_BITS
                p_slot = packed & _SLOT_MASK
                for cid in np.unique(p_cls[packed >= 0]):
                    cls = shard.classes[cid]
                    if cls.dim + cls.space != width:
                        continue  # absent or different layout: not found
                    m = (packed >= 0) & (p_cls == cid)
                    rows = p_slot[m]
                    vecs[sel[m], : cls.dim] = cls.emb[rows]
                    if cls.space:
                        vecs[sel[m], cls.dim:] = cls.state[rows]
                    found[sel[m]] = True
                if self.spill is not None:
                    for j in np.nonzero(packed < 0)[0]:
                        got = self._fault_in_locked(shard,
                                                    int(signs[sel[j]]),
                                                    False)
                        if got is None:
                            continue
                        dim0, raw = got
                        state_len = (len(raw) - dim0 * self._rp.itemsize) \
                            // 4
                        if dim0 + state_len != width:
                            continue
                        vecs[sel[j], :dim0] = raw[: dim0 * self._rp
                                                  .itemsize] \
                            .view(self._rp.np_dtype).astype(np.float32)
                        if state_len:
                            vecs[sel[j], dim0:] = \
                                raw[dim0 * self._rp.itemsize:] \
                                .view(np.float32)
                        found[sel[j]] = True
        return found, vecs

    def set_entries(self, signs: np.ndarray, dim: int, vecs: np.ndarray):
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            with shard.lock:
                for pos in sel.tolist():
                    if self.spill is not None:
                        self.spill.discard(int(signs[pos]))
                    shard.insert_row_locked(int(signs[pos]), dim,
                                            vecs[pos])
                    self._evict_and_spill_locked(shard)

    def clear(self):
        for shard in self._shards:
            with shard.lock:
                shard.classes = []
                shard._class_of = {}
                shard.resident_bytes = 0
                shard.emb_bytes = 0
                shard.clock = 0
                shard._h_sign = np.zeros(shard._h_size, np.uint64)
                shard._h_val = np.full(shard._h_size, -1, np.int64)
                shard._h_fill = 0
                shard._vq_cls = shard._vq_slot = shard._vq_stamp = None
                shard._vq_cursor = 0
        if self.spill is not None:
            self.spill.clear()

    def __len__(self) -> int:
        n = sum(s.live_rows() for s in self._shards)
        if self.spill is not None:
            n += len(self.spill)
        return n

    # --- serialization (PSD1/PSD2, shared with store.py + store.h) -------

    def _iter_records_locked(self, shard: _ArenaShard):
        """Yield ``(sign, dim, state_len, logical bytes)`` in stamp
        (LRU) order — the OrderedDict dump order, so fp32 dumps stay
        byte-identical with the per-entry holder's."""
        parts = []
        for cid, cls in enumerate(shard.classes):
            rows = np.nonzero(cls.stamps[: cls.next_fresh] >= 0)[0]
            if len(rows):
                parts.append((cid, rows, cls.stamps[rows]))
        if not parts:
            return
        cls_ids = np.concatenate(
            [np.full(len(p[1]), p[0], np.int64) for p in parts])
        slots = np.concatenate([p[1] for p in parts])
        stamps = np.concatenate([p[2] for p in parts])
        order = np.argsort(stamps, kind="stable")
        cls_ids, slots = cls_ids[order], slots[order]
        # extract per class in slab order, then emit in stamp order
        mats: Dict[int, np.ndarray] = {}
        row_pos: Dict[int, Dict[int, int]] = {}
        for cid in np.unique(cls_ids):
            m = cls_ids == cid
            rows = slots[m]
            mats[cid] = shard.classes[cid].logical_rows_locked(rows)
            row_pos[cid] = {int(r): i for i, r in enumerate(rows)}
        for cid, slot in zip(cls_ids.tolist(), slots.tolist()):
            cls = shard.classes[cid]
            yield (int(cls.signs[slot]), cls.dim, cls.space,
                   mats[cid][row_pos[cid][slot]])

    def dump_bytes(self) -> bytes:
        rp = self._rp
        chunks = []
        count = 0
        if self.spill is not None:
            self.spill.start_dump_capture()
        try:
            if rp.is_fp32:
                for shard in self._shards:
                    with shard.lock:
                        for sign, dim, state_len, raw in \
                                self._iter_records_locked(shard):
                            chunks.append(struct.pack(
                                "<QII", sign, dim, dim + state_len))
                            chunks.append(raw.tobytes())
                            count += 1
                front = []
                if self.spill is not None:
                    for sign, dim, raw in self.spill.items():
                        chunks.append(struct.pack("<QII", sign, dim,
                                                  len(raw) // 4))
                        chunks.append(raw.tobytes())
                        count += 1
                    for sign, (dim, raw) in \
                            self.spill.stop_dump_capture().items():
                        front.append(struct.pack("<QII", sign, dim,
                                                 len(raw) // 4))
                        front.append(raw.tobytes())
                        count += 1
                return b"".join(
                    [DUMP_MAGIC, struct.pack("<IQ", 1, count)]
                    + front + chunks)
            code = _DTYPE_CODES[rp.name]
            for shard in self._shards:
                with shard.lock:
                    for sign, dim, state_len, raw in \
                            self._iter_records_locked(shard):
                        chunks.append(struct.pack("<QIBI", sign, dim, code,
                                                  state_len))
                        chunks.append(raw.tobytes())
                        count += 1
            front = []
            if self.spill is not None:
                for sign, dim, raw in self.spill.items():
                    chunks.append(struct.pack(
                        "<QIBI", sign, dim, code,
                        rp.state_len_of(raw, dim)))
                    chunks.append(raw.tobytes())
                    count += 1
                for sign, (dim, raw) in \
                        self.spill.stop_dump_capture().items():
                    front.append(struct.pack(
                        "<QIBI", sign, dim, code,
                        rp.state_len_of(raw, dim)))
                    front.append(raw.tobytes())
                    count += 1
            return b"".join(
                [DUMP_MAGIC, struct.pack("<IQ", 2, count)] + front + chunks)
        finally:
            if self.spill is not None:
                self.spill.stop_dump_capture()

    def load_bytes(self, buf: bytes, clear: bool = True):
        import io

        reader = io.BytesIO(buf)
        version, count = read_psd_header(reader, "<load_bytes>")
        if clear:
            self.clear()
        for sign, dim, vec in iter_psd_records(reader.read, version, count):
            self.set_entry(sign, dim, vec)

    def dump_file(self, path: str):
        with open(path, "wb") as f:
            f.write(self.dump_bytes())

    def load_file(self, path: str, clear: bool = True):
        with open(path, "rb") as f:
            self.load_bytes(f.read(), clear=clear)
