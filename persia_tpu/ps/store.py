"""The embedding parameter store: sharded LRU map of embedding entries.

This is the Python (numpy) implementation of the storage tier the reference
builds in Rust (persia-embedding-holder + the lookup/update paths of
embedding_parameter_service/mod.rs:162-262, :359-427). A C++ backend with
identical semantics lives in ``native/`` and is selected automatically when
built (see :mod:`persia_tpu.ps.native`).

Semantics kept from the reference:

- **LRU eviction at capacity** per store (eviction_map.rs:11-111): training
  lookups refresh recency; inserting at capacity evicts the least recently
  used entry.
- **Entry layout** ``[embedding | optimizer state]`` in one f32 vector
  (emb_entry.rs:17-158), with per-entry dim.
- **Training lookup** (mod.rs:186-230): miss → admission-gated seeded init +
  optimizer state init + insert; non-admitted miss reads zeros and leaves no
  entry; dim-mismatch hit is re-initialized.
- **Eval lookup** (mod.rs:232-250): read-only, zeros on miss.
- **Gradient update** (mod.rs:359-427): per-sign optimizer step + optional
  weight-bound clamp; missing signs are skipped (counted).

TPU-first deviations:

- Lookups/updates are **batched per dim** (the worker groups signs by slot
  dim), so the hot path is vectorized numpy / a single C++ call rather than
  a per-sign virtual dispatch.
- Admission decisions are deterministic per sign (rng.py ADMIT_SALT) rather
  than drawn from a thread-local RNG.

NOTE (PR 10): this per-entry holder is the LEGACY Python backend,
kept as the semantic reference and the ``PERSIA_PS_BACKEND=
python-legacy`` A/B lever; :class:`persia_tpu.ps.arena.
ArenaEmbeddingHolder` (contiguous slab rows, vectorized batch paths,
GC-invisible storage) is what ``make_holder`` returns for the Python
backend, with identical semantics — the parity suites pin the two
against each other.

Mixed-precision rows:
``row_dtype`` ∈ {fp32, fp16, bf16} stores the embedding slice in half
precision while keeping the appended optimizer state fp32; all update
math runs through :class:`~persia_tpu.ps.optim.RowPrecision`'s
widen-on-read / narrow-on-write path so the arithmetic stays fp32-exact.
``capacity_bytes`` switches eviction to byte accounting, so an fp16
table genuinely admits ~2x the rows of an fp32 one before evicting.
Half-precision holders dump the PSD **v2** record layout (per-record
dtype tag, emb bytes + f32 state bytes); v1 files still load into any
holder, and v2 files load into fp32 holders (widen on read).

Disk spill tier (``spill_dir``, Python backend only, like row_dtype):
capacity evictions demote rows to :class:`~persia_tpu.ps.spill.
SpillStore` packets instead of dropping them, and any later access
faults them back in transparently (training accesses promote the row
resident; read-only accesses peek). ``len``, ``get_entry``/
``get_entries``, gradient updates, and ``dump_bytes`` all see ONE
logical table regardless of which rung a row occupies.
"""

import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from persia_tpu.ps.optim import (
    RowPrecision,
    SparseOptimizer,
    apply_weight_bound,
)
from persia_tpu.ps.rng import admit_mask, initialize_entries, internal_shard_of

DUMP_MAGIC = b"PSD1"
# PSD v2 per-record embedding dtype tags
_DTYPE_CODES = {"fp32": 0, "fp16": 1, "bf16": 2}
_DTYPE_NAMES = {v: k for k, v in _DTYPE_CODES.items()}


class EvictionMap:
    """Insertion/recency-ordered map with LRU eviction at capacity.

    Mirrors eviction_map.rs semantics on top of an OrderedDict (which is
    exactly a hashmap + doubly-linked list, the same structure the
    reference builds from a hashmap + ArrayLinkedList).
    Values are ``(dim, vec)`` with ``vec = [emb | opt_state]`` float32
    (fp32 holders) or the :class:`RowPrecision` byte layout.

    Eviction accounts ROWS by default (the reference semantics). With
    ``byte_capacity`` set it ALSO accounts resident DATA bytes — the fix
    for capacity meaning "rows" regardless of row width: a byte budget
    admits ~2x the rows once the embedding slice is fp16.
    ``emb_itemsize`` tells the byte accounting how much of each entry is
    embedding (``dim * emb_itemsize``) so the emb/state split is exact.
    """

    def __init__(self, capacity: int, byte_capacity: Optional[int] = None,
                 emb_itemsize: int = 4):
        self.capacity = capacity
        self.byte_capacity = byte_capacity
        self.emb_itemsize = emb_itemsize
        self.resident_bytes = 0  # data bytes of all stored vecs
        self.emb_bytes = 0  # the embedding-portion share of the above
        self._map: "OrderedDict[int, Tuple[int, np.ndarray]]" = OrderedDict()

    def get(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        return self._map.get(sign)

    def get_refresh(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        v = self._map.get(sign)
        if v is not None:
            self._map.move_to_end(sign)
        return v

    def _account(self, entry: Tuple[int, np.ndarray], sign_mult: int):
        dim, vec = entry
        self.resident_bytes += sign_mult * vec.nbytes
        self.emb_bytes += sign_mult * min(dim * self.emb_itemsize, vec.nbytes)

    def insert(self, sign: int, dim: int,
               vec: np.ndarray) -> List[Tuple[int, Tuple[int, np.ndarray]]]:
        """Insert/replace; returns the ``(sign, (dim, vec))`` entries
        evicted to restore the row/byte budget (empty when nothing
        overflowed) — a spill-armed holder demotes them to the disk
        tier instead of letting them die."""
        old = self._map.pop(sign, None)
        if old is not None:
            self._account(old, -1)
        entry = (dim, vec)
        self._map[sign] = entry
        self._account(entry, +1)
        evicted: List[Tuple[int, Tuple[int, np.ndarray]]] = []
        while len(self._map) > self.capacity or (
            self.byte_capacity is not None
            and self.resident_bytes > self.byte_capacity
            and len(self._map) > 1
        ):
            evicted_sign, old = self._map.popitem(last=False)
            self._account(old, -1)
            evicted.append((evicted_sign, old))
        return evicted

    def items_in_lru_order(self):
        return self._map.items()

    def clear(self):
        self._map.clear()
        self.resident_bytes = 0
        self.emb_bytes = 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, sign: int) -> bool:
        return sign in self._map


class EmbeddingHolder:
    """Sharded LRU store + inline sparse optimizer application.

    One process-level PS replica owns one holder; ``num_internal_shards``
    independently-locked shards bound lock contention
    (reference: persia-embedding-holder/src/lib.rs:28-101).
    """

    # Python-level data-plane calls hold the GIL throughout, so the
    # service tier's shard-parallel dispatch gains nothing here (the
    # native holder sets True and releases the GIL in ctypes calls)
    releases_gil = False

    def __init__(self, capacity: int = 1_000_000_000,
                 num_internal_shards: int = 8, row_dtype: str = "fp32",
                 capacity_bytes: Optional[int] = None,
                 hotness: Optional[bool] = None,
                 spill_dir: Optional[str] = None,
                 spill_bytes: Optional[int] = None):
        if num_internal_shards <= 0:
            raise ValueError("num_internal_shards must be positive")
        # 0/falsy means "row-count capacity only" (the config default),
        # NOT an active zero-byte budget
        capacity_bytes = capacity_bytes or None
        self.capacity = capacity
        self.capacity_bytes = capacity_bytes
        self.num_internal_shards = num_internal_shards
        # per-table storage precision: the embedding slice of every
        # entry is stored in row_dtype, the optimizer state stays f32
        # (see RowPrecision); fp32 keeps the legacy layout bit-identically
        self._rp = RowPrecision(row_dtype)
        per_shard = max(1, capacity // num_internal_shards)
        per_shard_bytes = (
            max(1, capacity_bytes // num_internal_shards)
            if capacity_bytes is not None else None)
        self._shards = [
            EvictionMap(per_shard, byte_capacity=per_shard_bytes,
                        emb_itemsize=self._rp.itemsize)
            for _ in range(num_internal_shards)
        ]
        self._locks = [threading.Lock() for _ in range(num_internal_shards)]
        self.optimizer: Optional[SparseOptimizer] = None
        # hyperparameters (configure(), reference mod.rs:429-451)
        self.init_method: str = "bounded_uniform"
        self.init_params: dict = {"lower": -0.01, "upper": 0.01}
        self.admit_probability: float = 1.0
        self.weight_bound: float = 10.0
        self.enable_weight_bound: bool = True
        self.configured = False
        # metrics: per-shard cells, each only ever written under its
        # shard's lock (a single shared int was += 1'd under DIFFERENT
        # shard locks — concurrent increments lost updates); readers sum
        self._index_miss = [0] * num_internal_shards
        self._gradient_id_miss = [0] * num_internal_shards
        # per-table (dim-labeled) registry twins of the counters above:
        # the health RPC keeps the aggregate ints, /metrics and the
        # fleet federation get attribution. Cached per dim — the
        # registry's own lookup locks on every call otherwise.
        self._miss_counters: Dict[Tuple[str, int], object] = {}
        # workload hotness sketches (persia_tpu.hotness): None (the
        # default) is the zero-overhead disabled path — one `is not
        # None` test per lookup call. `hotness=None` consults the
        # PERSIA_HOTNESS knob at construction time.
        from persia_tpu import hotness as _hotness

        self.hotness = _hotness.make_tracker(num_internal_shards,
                                             enabled=hotness)
        # disk spill tier (the cold rung of the storage ladder): armed,
        # budget evictions demote rows to spill packets instead of
        # dropping them, and any later access faults them back in. The
        # spill lock is a leaf under the shard locks (spill never calls
        # back into the holder). None (the default) keeps every path
        # at one `is not None` test of overhead.
        if spill_dir:
            from persia_tpu.ps.spill import SpillStore

            self.spill: Optional[SpillStore] = SpillStore(
                spill_dir, max_bytes=spill_bytes or None)
        else:
            self.spill = None

    @property
    def row_dtype(self) -> str:
        return self._rp.name

    @property
    def resident_bytes(self) -> int:
        """Stored DATA bytes across all shards (emb + optimizer state).
        Shard counters are ints mutated under their shard's lock; the
        sum is a consistent-enough snapshot for gauges/health."""
        return sum(s.resident_bytes for s in self._shards)

    @property
    def resident_emb_bytes(self) -> int:
        return sum(s.emb_bytes for s in self._shards)

    def resident_bytes_per_shard(self) -> List[int]:
        return [s.resident_bytes for s in self._shards]

    def row_nbytes(self, dim: int) -> int:
        """Predicted stored data bytes/row at ``dim`` under the current
        policy (embedding + the registered optimizer's state) — the
        capacity-planning number the memory-budget test checks RSS
        against."""
        space = self.optimizer.require_space(dim) if self.optimizer else 0
        return self._rp.entry_nbytes(dim, space)

    @property
    def index_miss_count(self) -> int:
        return sum(self._index_miss)

    @property
    def gradient_id_miss_count(self) -> int:
        return sum(self._gradient_id_miss)

    def _bump_miss(self, kind: str, dim: int, n: int):
        """Batched increment of the table-labeled registry counter
        (`ps_index_miss_total` / `ps_gradient_id_miss_total`): one
        locked inc per (call, shard) instead of one per miss. A racing
        first-use builds the cell twice; the registry dedups by
        (name, labels), so both writers land on the same Counter."""
        key = (kind, dim)
        c = self._miss_counters.get(key)
        if c is None:
            from persia_tpu.metrics import default_registry

            c = self._miss_counters[key] = default_registry().counter(
                f"ps_{kind}_total", {"table": str(dim)},
                help_text=(
                    "eval/unadmitted/cold lookups that read zeros, per "
                    "embedding table (dim)" if kind == "index_miss" else
                    "gradient updates whose sign was absent or "
                    "re-laid-out, per embedding table (dim)"))
        c.inc(n)

    def hotness_snapshot(self) -> dict:
        """Serialized workload-hotness snapshot (persia_tpu.hotness
        format); the disabled marker when sketches are unarmed. Each
        table carries this holder's LIVE bytes/row (``row_bytes`` =
        dim x the storage precision's itemsize) so downstream budget
        math sees the real storage width instead of assuming fp32 —
        note hotness.planner_report floors it at ``dim * 4`` for HBM
        plans, because the device cache imports rows as f32 values
        regardless of what the PS tier stores."""
        from persia_tpu import hotness as _hotness

        if self.hotness is None:
            return _hotness.disabled_snapshot()
        snap = self.hotness.snapshot()
        for table, t in snap.get("tables", {}).items():
            t["row_bytes"] = int(table) * self._rp.itemsize
        return snap

    # --- disk spill tier -------------------------------------------------

    def _spill_evicted(self, evicted):
        """Demote entries a shard insert evicted (runs under that
        shard's lock; the spill lock is a leaf below it)."""
        for sign, (dim, vec) in evicted:
            self.spill.put(sign, dim, vec)

    def _insert_locked(self, shard, sign: int, dim: int, vec: np.ndarray):
        """Shard insert that keeps the ladder invariant — a resident
        sign never also has a (stale) spill copy — and demotes whatever
        the insert evicted instead of dropping it."""
        if self.spill is None:
            shard.insert(sign, dim, vec)
            return
        self.spill.discard(sign)
        self._spill_evicted(shard.insert(sign, dim, vec))

    def _fault_in_locked(self, shard, sign: int, training: bool):
        """Transparent fault-in of a spilled row on a shard miss (under
        the shard's lock). Training accesses TAKE the row and re-insert
        it resident — promotion back up the ladder, which may demote
        other rows in turn; read-only accesses PEEK, so eval/serving
        lookups never mutate tier residency. Returns the ``(dim, vec)``
        entry, or None when the sign is not spilled either. A missing/
        truncated packet raises :class:`~persia_tpu.ps.spill.
        SpillReadError` — loud, with the holder untouched."""
        got = (self.spill.take(sign) if training
               else self.spill.peek(sign))
        if got is None:
            return None
        dim0, raw = got
        vec = raw.view(np.float32) if self._rp.is_fp32 else raw
        if training:
            self._spill_evicted(shard.insert(sign, dim0, vec))
        return (dim0, vec)

    def spill_stats(self) -> dict:
        """The disk tier's health counters (empty when unarmed)."""
        return self.spill.stats() if self.spill is not None else {}

    # --- control plane -------------------------------------------------

    def configure(
        self,
        init_method: str,
        init_params: dict,
        admit_probability: float = 1.0,
        weight_bound: float = 10.0,
        enable_weight_bound: bool = True,
    ):
        self.init_method = init_method
        self.init_params = dict(init_params)
        self.admit_probability = admit_probability
        self.weight_bound = weight_bound
        self.enable_weight_bound = enable_weight_bound
        self.configured = True

    def register_optimizer(self, config: dict, feature_index_prefix_bit: int = 0):
        # persialint: ok[lock-discipline] arm-time reference swap; the shard locks guard entry buffers (which optimizer.update mutates in place), not the optimizer binding itself
        self.optimizer = SparseOptimizer.from_config(
            config, feature_index_prefix_bit=feature_index_prefix_bit
        )

    # --- data plane -----------------------------------------------------

    def lookup(self, signs: np.ndarray, dim: int, training: bool) -> np.ndarray:
        """Batched lookup of ``len(signs)`` embeddings of width ``dim``.

        Returns an (n, dim) float32 matrix. Signs within the batch are
        normally distinct (the worker dedups before calling); duplicates
        are handled sequentially — the first occurrence initializes, later
        ones hit the fresh entry.
        """
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        out = np.zeros((n, dim), dtype=np.float32)
        if n == 0:
            return out
        if training:
            if self.optimizer is None:
                raise RuntimeError("optimizer not registered on parameter server")
            if not self.configured:
                raise RuntimeError("parameter server not configured")
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        if self.hotness is not None:
            # outside the shard locks: the tracker owns its own
            # per-shard (leaf) locks, so lookup's hold times and lock
            # order are untouched by telemetry
            self.hotness.observe(dim, signs)
        # Precompute admission + the full init matrix for ALL signs
        # (vectorized, deterministic per sign — hits just ignore their
        # row); insertion then happens sequentially per sign so
        # intra-batch eviction and duplicate signs behave exactly like
        # the sequential reference/native path.
        if training:
            space = self.optimizer.require_space(dim)
            admitted = admit_mask(signs, self.admit_probability)
            init_vecs = np.zeros((n, dim + space), dtype=np.float32)
            init_vecs[:, :dim] = initialize_entries(
                signs, dim, self.init_method, self.init_params)
            if space:
                self.optimizer.state_initialization(init_vecs, dim)
        if not self._rp.is_fp32:
            return self._lookup_half(signs, dim, training, shard_ids,
                                     init_vecs if training else None,
                                     admitted if training else None, out)
        spill = self.spill
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            n_miss = 0
            with self._locks[shard_idx]:
                for pos in sel:
                    sign = int(signs[pos])
                    entry = (
                        shard.get_refresh(sign) if training else shard.get(sign)
                    )
                    if entry is None and spill is not None:
                        entry = self._fault_in_locked(shard, sign,
                                                      training)
                    if entry is not None and entry[0] == dim:
                        out[pos] = entry[1][:dim]
                    elif not training:
                        self._index_miss[shard_idx] += 1
                        n_miss += 1
                    elif entry is None and not admitted[pos]:
                        self._index_miss[shard_idx] += 1
                        n_miss += 1
                    else:
                        # admitted miss, or dim mismatch (reinitialized
                        # unconditionally, reference mod.rs:213-228)
                        vec = init_vecs[pos].copy()
                        out[pos] = vec[:dim]
                        self._insert_locked(shard, sign, dim, vec)
                        self._index_miss[shard_idx] += 1
                        n_miss += 1
            if n_miss:
                self._bump_miss("index_miss", dim, n_miss)
        return out

    def _lookup_half(self, signs, dim, training, shard_ids, init_vecs,
                     admitted, out):
        """Half-precision twin of the lookup loop. Same per-sign
        LRU/admission/insert sequence; the narrow happens once,
        vectorized, for the whole init matrix, and hit rows widen in one
        vectorized astype per shard (under that shard's lock — the
        stored buffers race concurrent updates otherwise). The returned
        rows are the STORED values (narrow-then-widen), so a lookup
        right after the miss-insert reads exactly what later lookups
        will."""
        rp = self._rp
        esz = dim * rp.itemsize
        # the narrowed init rows are only needed on the MISS path; a
        # steady-state (all-hit) lookup must not pay the full-matrix
        # casts for them, so they materialize lazily on the first miss:
        # one (n, stored_len) byte matrix (per-sign insert is then a
        # single row copy, same cost as the fp32 path's .copy()) plus
        # the widened rows the caller reads back
        narrowed = [None]

        def narrow_inits():
            if narrowed[0] is None:
                stored_rows = rp.narrow_matrix(init_vecs, dim)
                widened = (np.ascontiguousarray(stored_rows[:, :esz])
                           .view(rp.np_dtype).astype(np.float32))
                narrowed[0] = (stored_rows, widened)
            return narrowed[0]

        spill = self.spill
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            hit_pos: List[int] = []
            hit_vecs: List[np.ndarray] = []
            n_miss = 0
            with self._locks[shard_idx]:
                for pos in sel:
                    sign = int(signs[pos])
                    entry = (
                        shard.get_refresh(sign) if training else shard.get(sign)
                    )
                    if entry is None and spill is not None:
                        entry = self._fault_in_locked(shard, sign,
                                                      training)
                    if entry is not None and entry[0] == dim:
                        hit_pos.append(pos)
                        hit_vecs.append(entry[1])
                    elif not training:
                        self._index_miss[shard_idx] += 1
                        n_miss += 1
                    elif entry is None and not admitted[pos]:
                        self._index_miss[shard_idx] += 1
                        n_miss += 1
                    else:
                        stored_rows, widened = narrow_inits()
                        out[pos] = widened[pos]
                        self._insert_locked(shard, sign, dim,
                                            stored_rows[pos].copy())
                        self._index_miss[shard_idx] += 1
                        n_miss += 1
                if hit_pos:
                    # entries of the right dim may still differ in state
                    # width (older optimizer layouts) — copy just the
                    # emb bytes row-wise, widen in one astype
                    raw = np.empty((len(hit_vecs), esz), np.uint8)
                    for i, v in enumerate(hit_vecs):
                        raw[i] = v[:esz]
                    out[np.asarray(hit_pos)] = (
                        raw.view(rp.np_dtype).astype(np.float32))
            if n_miss:
                self._bump_miss("index_miss", dim, n_miss)
        return out

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, dim: int):
        """Batched optimizer step for ``signs`` with grads (n, dim)."""
        if self.optimizer is None:
            raise RuntimeError("optimizer not registered on parameter server")
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        if n == 0:
            return
        batch_state = self.optimizer.batch_level_state(signs)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        space = self.optimizer.require_space(dim)
        width = dim + space
        rp = self._rp
        # width check below also skips entries created under a different
        # optimizer's state layout; for half rows it compares the stored
        # BYTE length (RowPrecision.stored_len)
        stored_len = rp.stored_len(dim, space)
        # Duplicate signs must apply sequentially (each step sees the
        # previous one's result, like the reference); a batched
        # gather/update/scatter would drop all but the last duplicate.
        has_dups = len(np.unique(signs)) != len(signs)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            # the whole gather/update/write-back runs under this shard's
            # lock — mutating stored buffers after releasing it races with
            # concurrent eviction + re-admission of the same sign
            n_miss = 0
            with self._locks[shard_idx]:
                found_pos: List[int] = []
                found_entries: List[np.ndarray] = []
                for pos in sel:
                    entry = shard.get(int(signs[pos]))
                    if entry is None and self.spill is not None:
                        # gradient for a spilled row: fault it in and
                        # apply — the ladder is one logical table, a
                        # demotion must not turn updates into misses
                        entry = self._fault_in_locked(
                            shard, int(signs[pos]), True)
                    if entry is not None and entry[0] == dim and \
                            len(entry[1]) == stored_len:
                        if has_dups:
                            # widen-on-read, fp32-exact update,
                            # narrow-on-write (fp32: in-place, no copy)
                            st = (batch_state[pos : pos + 1]
                                  if batch_state is not None else None)
                            if rp.is_fp32:
                                row = entry[1][None, :]
                            else:
                                row = rp.unpack(entry[1], dim)[None, :]
                            self.optimizer.update(
                                row, grads[pos : pos + 1], dim, st)
                            if self.enable_weight_bound:
                                apply_weight_bound(row[:, :dim],
                                                   self.weight_bound)
                            rp.pack_into(row[0], entry[1], dim)
                        else:
                            found_pos.append(pos)
                            found_entries.append(entry[1])
                    else:
                        self._gradient_id_miss[shard_idx] += 1
                        n_miss += 1
                if found_pos:
                    # fast path (no duplicates): one batched optimizer
                    # call on the widened fp32 matrix, narrowed back
                    # row-wise
                    mat = rp.unpack_matrix(found_entries, dim, width)
                    assert mat.shape[1] == width
                    sub_state = (
                        batch_state[np.array(found_pos)]
                        if batch_state is not None else None
                    )
                    self.optimizer.update(mat, grads[np.array(found_pos)],
                                          dim, sub_state)
                    if self.enable_weight_bound:
                        apply_weight_bound(mat[:, :dim], self.weight_bound)
                    rp.pack_matrix_into(mat, found_entries, dim)
            if n_miss:
                self._bump_miss("gradient_id_miss", dim, n_miss)

    # --- debug / checkpoint --------------------------------------------

    def get_entry(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        """(dim, f32 [emb|state]) or None. fp32 holders hand out the
        live stored buffer (legacy semantics); half holders widen into a
        fresh copy. A spilled row reads through (peek — inc-update and
        checkpoint readers must see one logical table without churning
        tier residency)."""
        shard_idx = int(internal_shard_of(np.array([sign], dtype=np.uint64),
                                          self.num_internal_shards)[0])
        with self._locks[shard_idx]:
            entry = self._shards[shard_idx].get(sign)
            if entry is None and self.spill is not None:
                entry = self._fault_in_locked(
                    self._shards[shard_idx], int(sign), False)
            if entry is None or self._rp.is_fp32:
                return entry
            return entry[0], self._rp.unpack(entry[1], entry[0])

    def set_entry(self, sign: int, dim: int, vec: np.ndarray):
        shard_idx = int(internal_shard_of(np.array([sign], dtype=np.uint64),
                                          self.num_internal_shards)[0])
        stored = self._rp.pack(
            np.ascontiguousarray(vec, dtype=np.float32), dim)
        with self._locks[shard_idx]:
            self._insert_locked(self._shards[shard_idx], sign, dim, stored)

    def get_entries(self, signs: np.ndarray, width: int):
        """Batched ``get_entry`` for uniform-width entries (value + opt
        state): one call — and on the RPC twin ONE round trip — instead
        of n. Entries absent or of a different width read as not-found.
        Returns (found (n,) bool, vecs (n, width) f32)."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        found = np.zeros(n, dtype=bool)
        vecs = np.zeros((n, width), dtype=np.float32)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        rp = self._rp
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            with self._locks[shard_idx]:
                shard = self._shards[shard_idx]
                for pos in sel:
                    entry = shard.get(int(signs[pos]))
                    if entry is None and self.spill is not None:
                        # read-only reach into the disk tier (the
                        # device cache's miss import follows a training
                        # lookup, so the row is usually resident by
                        # now; direct readers still see one table)
                        entry = self._fault_in_locked(
                            shard, int(signs[pos]), False)
                    if entry is None:
                        continue
                    if rp.is_fp32:
                        if len(entry[1]) == width:
                            found[pos] = True
                            vecs[pos] = entry[1]
                        continue
                    # half layout: a dim-d entry with state s is width
                    # d + s in f32 units — match on that, widen on read
                    state_len = rp.state_len_of(entry[1], entry[0])
                    if (state_len is not None
                            and entry[0] + state_len == width):
                        found[pos] = True
                        rp.unpack_into(entry[1], entry[0], vecs[pos])
        return found, vecs

    def set_entries(self, signs: np.ndarray, dim: int, vecs: np.ndarray):
        """Batched ``set_entry`` (uniform dim): the device cache's
        write-back path."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        rp = self._rp
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            with self._locks[shard_idx]:
                shard = self._shards[shard_idx]
                for pos in sel:
                    stored = (vecs[pos].copy() if rp.is_fp32
                              else rp.pack(vecs[pos], dim))
                    self._insert_locked(shard, int(signs[pos]), dim,
                                        stored)

    def clear(self):
        for lock, shard in zip(self._locks, self._shards):
            with lock:
                shard.clear()
        if self.spill is not None:
            # persialint: ok[lock-discipline] SpillStore guards its own state with its leaf lock; shard locks never guard the spill binding
            self.spill.clear()

    def __len__(self) -> int:
        """Rows in the LOGICAL table: resident plus spilled (the ladder
        demotes, it does not delete)."""
        n = sum(len(s) for s in self._shards)
        if self.spill is not None:
            n += len(self.spill)
        return n

    # --- serialization (PSD1, shared with native/src/store.h) -----------

    def dump_bytes(self) -> bytes:
        """Serialize all entries (LRU order per shard).

        fp32 holders write the legacy **v1** layout bit-identically
        (shared with native/src/store.h and every pre-existing reader).
        Half-precision holders write **v2**: same magic, version field
        2, and per-record ``sign u64 | dim u32 | emb-dtype u8 |
        state_len u32 | emb bytes (dim * itemsize) | state f32 bytes`` —
        half the embedding bytes on disk, f32 state exact, and a
        dtype-tagged record so any holder (including fp32) can widen it
        back on load.

        The header count is derived from the records actually serialized
        (each shard under its own lock) — never from an unlocked size
        snapshot, which concurrent inserts/evictions could invalidate and
        leave the checkpoint unloadable.

        A spill-armed holder serializes the disk tier too — a checkpoint
        is the LOGICAL table, regardless of which rung a row occupies
        (spilled rows were serialized in their stored byte form, so the
        round trip is exact). Shards serialize first and the spill
        index last, so a row DEMOTED mid-dump is always caught by one
        of the two passes; the reverse migration (fault-in/discard
        removing a spilled row after its destination shard was already
        serialized) is covered by the spill store's dump capture,
        whose records are prepended so any newer shard/spill record of
        the same sign wins on load."""
        rp = self._rp
        chunks = []
        count = 0
        if self.spill is not None:
            self.spill.start_dump_capture()
        try:
            if rp.is_fp32:
                for lock, shard in zip(self._locks, self._shards):
                    with lock:
                        for sign, (dim, vec) in shard.items_in_lru_order():
                            chunks.append(
                                struct.pack("<QII", sign, dim, len(vec)))
                            chunks.append(np.ascontiguousarray(
                                vec, dtype=np.float32).tobytes())
                            count += 1
                front = []
                if self.spill is not None:
                    for sign, dim, raw in self.spill.items():
                        chunks.append(struct.pack("<QII", sign, dim,
                                                  len(raw) // 4))
                        chunks.append(raw.tobytes())
                        count += 1
                    for sign, (dim, raw) in \
                            self.spill.stop_dump_capture().items():
                        front.append(struct.pack("<QII", sign, dim,
                                                 len(raw) // 4))
                        front.append(raw.tobytes())
                        count += 1
                return b"".join(
                    [DUMP_MAGIC, struct.pack("<IQ", 1, count)]
                    + front + chunks)
            code = _DTYPE_CODES[rp.name]
            for lock, shard in zip(self._locks, self._shards):
                with lock:
                    for sign, (dim, vec) in shard.items_in_lru_order():
                        state_len = rp.state_len_of(vec, dim)
                        chunks.append(struct.pack("<QIBI", sign, dim, code,
                                                  state_len))
                        chunks.append(vec.tobytes())
                        count += 1
            front = []
            if self.spill is not None:
                for sign, dim, raw in self.spill.items():
                    chunks.append(struct.pack("<QIBI", sign, dim, code,
                                              rp.state_len_of(raw, dim)))
                    chunks.append(raw.tobytes())
                    count += 1
                for sign, (dim, raw) in \
                        self.spill.stop_dump_capture().items():
                    front.append(struct.pack("<QIBI", sign, dim, code,
                                             rp.state_len_of(raw, dim)))
                    front.append(raw.tobytes())
                    count += 1
            return b"".join(
                [DUMP_MAGIC, struct.pack("<IQ", 2, count)] + front + chunks)
        finally:
            if self.spill is not None:
                self.spill.stop_dump_capture()

    def load_bytes(self, buf: bytes, clear: bool = True):
        import io

        reader = io.BytesIO(buf)
        version, count = read_psd_header(reader, "<load_bytes>")
        if clear:
            self.clear()
        for sign, dim, vec in iter_psd_records(reader.read, version, count):
            self.set_entry(sign, dim, vec)

    def dump_file(self, path: str):
        with open(path, "wb") as f:
            f.write(self.dump_bytes())

    def load_file(self, path: str, clear: bool = True):
        with open(path, "rb") as f:
            self.load_bytes(f.read(), clear=clear)


def read_psd_header(f, name: str = "<psd>"):
    """Validate magic + version off a file-like; returns (version,
    count)."""
    head = f.read(4 + struct.calcsize("<IQ"))
    if head[:4] != DUMP_MAGIC:
        raise ValueError(f"{name}: bad PSD1 magic")
    version, count = struct.unpack_from("<IQ", head, 4)
    if version not in (1, 2):
        raise ValueError(f"{name}: unsupported PSD version {version}")
    return version, count


def iter_psd_records(read, version: int, count: int):
    """Yield ``(sign, dim, f32 [emb|state] vec)`` records via a
    ``read(n) -> bytes`` callable — THE one widen-on-read PSD decoder,
    shared by ``load_bytes`` and the streaming checkpoint reader
    (``checkpoint.iter_psd_entries``), so a format change cannot fork.
    v2 embedding slices widen from their tagged dtype, so any holder
    consumes any version (it re-narrows per its own policy on
    ``set_entry``). Yielded vecs are fresh WRITABLE arrays — holders
    store the buffer they are handed and mutate it in place on update."""
    rec1 = struct.calcsize("<QII")
    rec2 = struct.calcsize("<QIBI")
    rp_by_code: Dict[int, RowPrecision] = {}
    for _ in range(count):
        if version == 1:
            sign, dim, total = struct.unpack("<QII", read(rec1))
            vec = np.frombuffer(read(4 * total), dtype=np.float32).copy()
        else:
            sign, dim, code, state_len = struct.unpack("<QIBI", read(rec2))
            rp = rp_by_code.get(code)
            if rp is None:
                name = _DTYPE_NAMES.get(code)
                if name is None:
                    raise ValueError(f"unknown PSD2 dtype code {code}")
                rp = rp_by_code[code] = RowPrecision(name)
            raw = np.frombuffer(read(rp.entry_nbytes(dim, state_len)),
                                dtype=np.uint8)
            if rp.is_fp32:
                # dtype code 0 (fp32) is legal in a v2 record even
                # though in-repo writers never emit it: the bytes ARE
                # f32, so reinterpret — unpack() would VALUE-convert
                # each byte into a float
                vec = raw.view(np.float32).copy()
            else:
                vec = rp.unpack(raw, dim)
        yield sign, dim, vec
