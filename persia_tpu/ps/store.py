"""The embedding parameter store: sharded LRU map of embedding entries.

This is the Python (numpy) implementation of the storage tier the reference
builds in Rust (persia-embedding-holder + the lookup/update paths of
embedding_parameter_service/mod.rs:162-262, :359-427). A C++ backend with
identical semantics lives in ``native/`` and is selected automatically when
built (see :mod:`persia_tpu.ps.native`).

Semantics kept from the reference:

- **LRU eviction at capacity** per store (eviction_map.rs:11-111): training
  lookups refresh recency; inserting at capacity evicts the least recently
  used entry.
- **Entry layout** ``[embedding | optimizer state]`` in one f32 vector
  (emb_entry.rs:17-158), with per-entry dim.
- **Training lookup** (mod.rs:186-230): miss → admission-gated seeded init +
  optimizer state init + insert; non-admitted miss reads zeros and leaves no
  entry; dim-mismatch hit is re-initialized.
- **Eval lookup** (mod.rs:232-250): read-only, zeros on miss.
- **Gradient update** (mod.rs:359-427): per-sign optimizer step + optional
  weight-bound clamp; missing signs are skipped (counted).

TPU-first deviations:

- Lookups/updates are **batched per dim** (the worker groups signs by slot
  dim), so the hot path is vectorized numpy / a single C++ call rather than
  a per-sign virtual dispatch.
- Admission decisions are deterministic per sign (rng.py ADMIT_SALT) rather
  than drawn from a thread-local RNG.
"""

import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from persia_tpu.ps.optim import SparseOptimizer, apply_weight_bound
from persia_tpu.ps.rng import admit_mask, initialize_entries, internal_shard_of

DUMP_MAGIC = b"PSD1"


class EvictionMap:
    """Insertion/recency-ordered map with LRU eviction at capacity.

    Mirrors eviction_map.rs semantics on top of an OrderedDict (which is
    exactly a hashmap + doubly-linked list, the same structure the
    reference builds from a hashmap + ArrayLinkedList).
    Values are ``(dim, vec)`` with ``vec = [emb | opt_state]`` float32.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._map: "OrderedDict[int, Tuple[int, np.ndarray]]" = OrderedDict()

    def get(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        return self._map.get(sign)

    def get_refresh(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        v = self._map.get(sign)
        if v is not None:
            self._map.move_to_end(sign)
        return v

    def insert(self, sign: int, dim: int, vec: np.ndarray) -> Optional[int]:
        """Insert/replace; returns the evicted sign if capacity overflowed."""
        if sign in self._map:
            del self._map[sign]
        self._map[sign] = (dim, vec)
        if len(self._map) > self.capacity:
            evicted_sign, _ = self._map.popitem(last=False)
            return evicted_sign
        return None

    def items_in_lru_order(self):
        return self._map.items()

    def clear(self):
        self._map.clear()

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, sign: int) -> bool:
        return sign in self._map


class EmbeddingHolder:
    """Sharded LRU store + inline sparse optimizer application.

    One process-level PS replica owns one holder; ``num_internal_shards``
    independently-locked shards bound lock contention
    (reference: persia-embedding-holder/src/lib.rs:28-101).
    """

    # Python-level data-plane calls hold the GIL throughout, so the
    # service tier's shard-parallel dispatch gains nothing here (the
    # native holder sets True and releases the GIL in ctypes calls)
    releases_gil = False

    def __init__(self, capacity: int = 1_000_000_000, num_internal_shards: int = 8):
        if num_internal_shards <= 0:
            raise ValueError("num_internal_shards must be positive")
        self.capacity = capacity
        self.num_internal_shards = num_internal_shards
        per_shard = max(1, capacity // num_internal_shards)
        self._shards = [EvictionMap(per_shard) for _ in range(num_internal_shards)]
        self._locks = [threading.Lock() for _ in range(num_internal_shards)]
        self.optimizer: Optional[SparseOptimizer] = None
        # hyperparameters (configure(), reference mod.rs:429-451)
        self.init_method: str = "bounded_uniform"
        self.init_params: dict = {"lower": -0.01, "upper": 0.01}
        self.admit_probability: float = 1.0
        self.weight_bound: float = 10.0
        self.enable_weight_bound: bool = True
        self.configured = False
        # metrics: per-shard cells, each only ever written under its
        # shard's lock (a single shared int was += 1'd under DIFFERENT
        # shard locks — concurrent increments lost updates); readers sum
        self._index_miss = [0] * num_internal_shards
        self._gradient_id_miss = [0] * num_internal_shards

    @property
    def index_miss_count(self) -> int:
        return sum(self._index_miss)

    @property
    def gradient_id_miss_count(self) -> int:
        return sum(self._gradient_id_miss)

    # --- control plane -------------------------------------------------

    def configure(
        self,
        init_method: str,
        init_params: dict,
        admit_probability: float = 1.0,
        weight_bound: float = 10.0,
        enable_weight_bound: bool = True,
    ):
        self.init_method = init_method
        self.init_params = dict(init_params)
        self.admit_probability = admit_probability
        self.weight_bound = weight_bound
        self.enable_weight_bound = enable_weight_bound
        self.configured = True

    def register_optimizer(self, config: dict, feature_index_prefix_bit: int = 0):
        self.optimizer = SparseOptimizer.from_config(
            config, feature_index_prefix_bit=feature_index_prefix_bit
        )

    # --- data plane -----------------------------------------------------

    def lookup(self, signs: np.ndarray, dim: int, training: bool) -> np.ndarray:
        """Batched lookup of ``len(signs)`` embeddings of width ``dim``.

        Returns an (n, dim) float32 matrix. Signs within the batch are
        normally distinct (the worker dedups before calling); duplicates
        are handled sequentially — the first occurrence initializes, later
        ones hit the fresh entry.
        """
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        out = np.zeros((n, dim), dtype=np.float32)
        if n == 0:
            return out
        if training:
            if self.optimizer is None:
                raise RuntimeError("optimizer not registered on parameter server")
            if not self.configured:
                raise RuntimeError("parameter server not configured")
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        # Precompute admission + the full init matrix for ALL signs
        # (vectorized, deterministic per sign — hits just ignore their
        # row); insertion then happens sequentially per sign so
        # intra-batch eviction and duplicate signs behave exactly like
        # the sequential reference/native path.
        if training:
            space = self.optimizer.require_space(dim)
            admitted = admit_mask(signs, self.admit_probability)
            init_vecs = np.zeros((n, dim + space), dtype=np.float32)
            init_vecs[:, :dim] = initialize_entries(
                signs, dim, self.init_method, self.init_params)
            if space:
                self.optimizer.state_initialization(init_vecs, dim)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            with self._locks[shard_idx]:
                for pos in sel:
                    sign = int(signs[pos])
                    entry = (
                        shard.get_refresh(sign) if training else shard.get(sign)
                    )
                    if entry is not None and entry[0] == dim:
                        out[pos] = entry[1][:dim]
                    elif not training:
                        self._index_miss[shard_idx] += 1
                    elif entry is None and not admitted[pos]:
                        self._index_miss[shard_idx] += 1
                    else:
                        # admitted miss, or dim mismatch (reinitialized
                        # unconditionally, reference mod.rs:213-228)
                        vec = init_vecs[pos].copy()
                        out[pos] = vec[:dim]
                        shard.insert(sign, dim, vec)
                        self._index_miss[shard_idx] += 1
        return out

    def update_gradients(self, signs: np.ndarray, grads: np.ndarray, dim: int):
        """Batched optimizer step for ``signs`` with grads (n, dim)."""
        if self.optimizer is None:
            raise RuntimeError("optimizer not registered on parameter server")
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        if n == 0:
            return
        batch_state = self.optimizer.batch_level_state(signs)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        space = self.optimizer.require_space(dim)
        width = dim + space
        # Duplicate signs must apply sequentially (each step sees the
        # previous one's result, like the reference); a batched
        # gather/update/scatter would drop all but the last duplicate.
        has_dups = len(np.unique(signs)) != len(signs)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            shard = self._shards[shard_idx]
            # the whole gather/update/write-back runs under this shard's
            # lock — mutating stored buffers after releasing it races with
            # concurrent eviction + re-admission of the same sign
            with self._locks[shard_idx]:
                found_pos: List[int] = []
                found_entries: List[np.ndarray] = []
                for pos in sel:
                    entry = shard.get(int(signs[pos]))
                    # width check also skips entries created under a
                    # different optimizer's state layout
                    if entry is not None and entry[0] == dim and \
                            len(entry[1]) == width:
                        if has_dups:
                            st = (batch_state[pos : pos + 1]
                                  if batch_state is not None else None)
                            row = entry[1][None, :]
                            self.optimizer.update(
                                row, grads[pos : pos + 1], dim, st)
                            if self.enable_weight_bound:
                                apply_weight_bound(row[:, :dim],
                                                   self.weight_bound)
                            entry[1][:] = row[0]
                        else:
                            found_pos.append(pos)
                            found_entries.append(entry[1])
                    else:
                        self._gradient_id_miss[shard_idx] += 1
                if not found_pos:
                    continue
                # fast path (no duplicates): one batched optimizer call
                mat = np.stack(found_entries).astype(np.float32, copy=False)
                assert mat.shape[1] == width
                sub_state = (
                    batch_state[np.array(found_pos)]
                    if batch_state is not None else None
                )
                self.optimizer.update(mat, grads[np.array(found_pos)], dim,
                                      sub_state)
                if self.enable_weight_bound:
                    apply_weight_bound(mat[:, :dim], self.weight_bound)
                for row, vec in zip(mat, found_entries):
                    vec[:] = row  # write back (vec is the stored buffer)

    # --- debug / checkpoint --------------------------------------------

    def get_entry(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        shard_idx = int(internal_shard_of(np.array([sign], dtype=np.uint64),
                                          self.num_internal_shards)[0])
        with self._locks[shard_idx]:
            return self._shards[shard_idx].get(sign)

    def set_entry(self, sign: int, dim: int, vec: np.ndarray):
        shard_idx = int(internal_shard_of(np.array([sign], dtype=np.uint64),
                                          self.num_internal_shards)[0])
        with self._locks[shard_idx]:
            self._shards[shard_idx].insert(
                sign, dim, np.ascontiguousarray(vec, dtype=np.float32)
            )

    def get_entries(self, signs: np.ndarray, width: int):
        """Batched ``get_entry`` for uniform-width entries (value + opt
        state): one call — and on the RPC twin ONE round trip — instead
        of n. Entries absent or of a different width read as not-found.
        Returns (found (n,) bool, vecs (n, width) f32)."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        n = len(signs)
        found = np.zeros(n, dtype=bool)
        vecs = np.zeros((n, width), dtype=np.float32)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            with self._locks[shard_idx]:
                shard = self._shards[shard_idx]
                for pos in sel:
                    entry = shard.get(int(signs[pos]))
                    if entry is not None and len(entry[1]) == width:
                        found[pos] = True
                        vecs[pos] = entry[1]
        return found, vecs

    def set_entries(self, signs: np.ndarray, dim: int, vecs: np.ndarray):
        """Batched ``set_entry`` (uniform dim): the device cache's
        write-back path."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        vecs = np.ascontiguousarray(vecs, dtype=np.float32)
        shard_ids = internal_shard_of(signs, self.num_internal_shards)
        for shard_idx in np.unique(shard_ids):
            sel = np.nonzero(shard_ids == shard_idx)[0]
            with self._locks[shard_idx]:
                shard = self._shards[shard_idx]
                for pos in sel:
                    shard.insert(int(signs[pos]), dim, vecs[pos].copy())

    def clear(self):
        for lock, shard in zip(self._locks, self._shards):
            with lock:
                shard.clear()

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    # --- serialization (PSD1, shared with native/src/store.h) -----------

    def dump_bytes(self) -> bytes:
        """Serialize all entries (LRU order per shard) to the PSD1 layout.

        The header count is derived from the records actually serialized
        (each shard under its own lock) — never from an unlocked size
        snapshot, which concurrent inserts/evictions could invalidate and
        leave the checkpoint unloadable."""
        chunks = []
        count = 0
        for lock, shard in zip(self._locks, self._shards):
            with lock:
                for sign, (dim, vec) in shard.items_in_lru_order():
                    chunks.append(struct.pack("<QII", sign, dim, len(vec)))
                    chunks.append(
                        np.ascontiguousarray(vec, dtype=np.float32).tobytes())
                    count += 1
        return b"".join([DUMP_MAGIC, struct.pack("<IQ", 1, count)] + chunks)

    def load_bytes(self, buf: bytes, clear: bool = True):
        view = memoryview(buf)
        if bytes(view[:4]) != DUMP_MAGIC:
            raise ValueError("bad PSD1 magic")
        version, count = struct.unpack_from("<IQ", view, 4)
        if version != 1:
            raise ValueError(f"unsupported PSD1 version {version}")
        if clear:
            self.clear()
        pos = 4 + struct.calcsize("<IQ")
        for _ in range(count):
            sign, dim, total = struct.unpack_from("<QII", view, pos)
            pos += struct.calcsize("<QII")
            vec = np.frombuffer(view, dtype=np.float32, count=total, offset=pos).copy()
            pos += 4 * total
            self.set_entry(sign, dim, vec)

    def dump_file(self, path: str):
        with open(path, "wb") as f:
            f.write(self.dump_bytes())

    def load_file(self, path: str, clear: bool = True):
        with open(path, "rb") as f:
            self.load_bytes(f.read(), clear=clear)
