"""Server-side sparse optimizers applied inline on parameter-server entries.

Numerics mirror the reference's `Optimizable` implementations
(rust/persia-common/src/optim.rs:66-307 + rust/persia-simd/src/lib.rs), with
one deliberate deviation: where the reference's AVX2 path uses the hardware
approximate reciprocal square root (`_mm256_rsqrt_ps`, ~3e-4 relative error),
we compute the exact `1/sqrt`. Golden parity tests therefore compare with a
small tolerance instead of bit equality.

Unlike the reference's per-entry trait, every update here is **batched**:
``update(entries, grads, ...)`` operates on an ``(n, dim + space)`` matrix of
entries in place, which is both the numpy-vectorized form and the shape the
C++ kernels consume. Entry layout is ``[embedding | optimizer state]``
(reference: persia-embedding-holder/src/emb_entry.rs:17-158).
"""

from typing import Dict, Optional, Tuple

import numpy as np


class SparseOptimizer:
    """Interface of a server-side optimizer (reference: optim.rs:66-92)."""

    def require_space(self, dim: int) -> int:
        """Extra f32 slots appended to each entry for optimizer state."""
        return 0

    def state_initialization(self, entries: np.ndarray, dim: int) -> None:
        """Initialize the state slice ``entries[:, dim:]`` in place."""

    def batch_level_state(self, signs: np.ndarray) -> Optional[np.ndarray]:
        """Per-sign state computed once per update batch (Adam beta powers)."""
        return None

    def update(
        self,
        entries: np.ndarray,
        grads: np.ndarray,
        dim: int,
        batch_level_state: Optional[np.ndarray] = None,
    ) -> None:
        """Apply one optimizer step to every row of ``entries`` in place."""
        raise NotImplementedError

    def update_lr(self, lr: float) -> None:
        pass

    def to_config(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_config(config: dict, feature_index_prefix_bit: int = 0) -> "SparseOptimizer":
        kind = config["type"]
        kwargs = {k: v for k, v in config.items() if k != "type"}
        if kind == "sgd":
            return SparseSGD(**kwargs)
        if kind == "adagrad":
            return SparseAdagrad(**kwargs)
        if kind == "adam":
            return SparseAdam(
                feature_index_prefix_bit=feature_index_prefix_bit, **kwargs
            )
        raise ValueError(f"unknown sparse optimizer type {kind!r}")


class SparseSGD(SparseOptimizer):
    """Decayed SGD: ``emb -= lr * (grad + wd * emb)``
    (reference: optim.rs:223-244, persia-simd/src/lib.rs:124-144)."""

    def __init__(self, lr: float, wd: float = 0.0):
        self.lr = float(lr)
        self.wd = float(wd)

    def update(self, entries, grads, dim, batch_level_state=None):
        emb = entries[:, :dim]
        emb -= self.lr * (grads + self.wd * emb)

    def update_lr(self, lr: float) -> None:
        self.lr = lr

    def to_config(self) -> dict:
        return {"type": "sgd", "lr": self.lr, "wd": self.wd}


class SparseAdagrad(SparseOptimizer):
    """Decayed Adagrad, optionally with a single accumulator shared across
    the vector (reference: optim.rs:246-307).

    Non-shared: ``emb -= lr * grad / sqrt(acc + eps); acc = acc*g2m + grad²``.
    Shared: the accumulator used for the step is the value *before* this
    batch's gradient is accumulated (simd lib.rs:83-121 note).
    """

    def __init__(
        self,
        lr: float = 1e-2,
        wd: float = 0.0,
        g_square_momentum: float = 1.0,
        initialization: float = 1e-2,
        eps: float = 1e-10,
        vectorwise_shared: bool = False,
    ):
        self.lr = float(lr)
        self.wd = float(wd)
        self.g_square_momentum = float(g_square_momentum)
        self.initialization = float(initialization)
        self.eps = float(eps)
        self.vectorwise_shared = bool(vectorwise_shared)

    def require_space(self, dim: int) -> int:
        return 1 if self.vectorwise_shared else dim

    def state_initialization(self, entries, dim):
        entries[:, dim:] = self.initialization

    def update(self, entries, grads, dim, batch_level_state=None):
        emb = entries[:, :dim]
        if self.vectorwise_shared:
            acc = entries[:, dim]  # (n,)
            scale = self.lr / np.sqrt(acc + self.eps)
            emb -= scale[:, None] * grads
            g2 = np.mean(grads * grads, axis=1)
            entries[:, dim] = acc * self.g_square_momentum + g2
        else:
            acc = entries[:, dim:]
            emb -= self.lr * grads / np.sqrt(acc + self.eps)
            acc *= self.g_square_momentum
            acc += grads * grads

    def update_lr(self, lr: float) -> None:
        self.lr = lr

    def to_config(self) -> dict:
        return {
            "type": "adagrad",
            "lr": self.lr,
            "wd": self.wd,
            "g_square_momentum": self.g_square_momentum,
            "initialization": self.initialization,
            "eps": self.eps,
            "vectorwise_shared": self.vectorwise_shared,
        }


class SparseAdam(SparseOptimizer):
    """Adam with per-feature-group accumulated beta powers
    (reference: optim.rs:94-221).

    The bias-correction powers are tracked per feature group (identified by
    the sign's index-prefix bits) and advanced once per update batch per
    group, mirroring the reference exactly — including its quirk that the
    powers start at β and are advanced *before* first use, so the first
    step corrects with β².
    """

    def __init__(
        self,
        lr: float = 1e-3,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
        feature_index_prefix_bit: int = 0,
    ):
        self.lr = float(lr)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)
        self.eps = float(eps)
        self.feature_index_prefix_bit = int(feature_index_prefix_bit)
        # group prefix -> accumulated (beta1^t, beta2^t), f32 like the reference
        self._accum: Dict[int, Tuple[np.float32, np.float32]] = {}

    def require_space(self, dim: int) -> int:
        return dim * 2

    def batch_level_state(self, signs: np.ndarray) -> np.ndarray:
        if self.feature_index_prefix_bit > 0:
            mask = ~((1 << (64 - self.feature_index_prefix_bit)) - 1) & (
                (1 << 64) - 1
            )
        else:
            mask = 0
        masked = (signs.astype(np.uint64) & np.uint64(mask)).tolist()
        out = np.empty((len(masked), 2), dtype=np.float32)
        stepped: Dict[int, Tuple[np.float32, np.float32]] = {}
        b1 = np.float32(self.beta1)
        b2 = np.float32(self.beta2)
        for i, g in enumerate(masked):
            if g in stepped:
                out[i] = stepped[g]
                continue
            p1, p2 = self._accum.get(g, (b1, b2))
            p1 = np.float32(p1 * b1)
            p2 = np.float32(p2 * b2)
            self._accum[g] = (p1, p2)
            stepped[g] = (p1, p2)
            out[i] = (p1, p2)
        return out

    def update(self, entries, grads, dim, batch_level_state=None):
        if batch_level_state is None:
            raise ValueError("SparseAdam.update requires batch_level_state")
        emb = entries[:, :dim]
        m = entries[:, dim : 2 * dim]
        v = entries[:, 2 * dim : 3 * dim]
        b1p = batch_level_state[:, 0][:, None]
        b2p = batch_level_state[:, 1][:, None]
        m *= self.beta1
        m += (1.0 - self.beta1) * grads
        v *= self.beta2
        v += (1.0 - self.beta2) * grads * grads
        m_hat = m / (1.0 - b1p)
        v_hat = v / (1.0 - b2p)
        emb -= self.lr * m_hat / (self.eps + np.sqrt(v_hat))

    def update_lr(self, lr: float) -> None:
        self.lr = lr

    def to_config(self) -> dict:
        return {
            "type": "adam",
            "lr": self.lr,
            "beta1": self.beta1,
            "beta2": self.beta2,
            "eps": self.eps,
        }


def apply_weight_bound(emb: np.ndarray, bound: float) -> None:
    """Clamp embeddings to [-bound, bound] in place
    (reference: persia-simd/src/lib.rs:231-251, applied at
    embedding_parameter_service/mod.rs:398)."""
    np.clip(emb, -bound, bound, out=emb)
