"""Server-side sparse optimizers applied inline on parameter-server entries.

Numerics mirror the reference's `Optimizable` implementations
(rust/persia-common/src/optim.rs:66-307 + rust/persia-simd/src/lib.rs), with
one deliberate deviation: where the reference's AVX2 path uses the hardware
approximate reciprocal square root (`_mm256_rsqrt_ps`, ~3e-4 relative error),
we compute the exact `1/sqrt`. Golden parity tests therefore compare with a
small tolerance instead of bit equality.

Unlike the reference's per-entry trait, every update here is **batched**:
``update(entries, grads, ...)`` operates on an ``(n, dim + space)`` matrix of
entries in place, which is both the numpy-vectorized form and the shape the
C++ kernels consume. Entry layout is ``[embedding | optimizer state]``
(reference: persia-embedding-holder/src/emb_entry.rs:17-158).
"""

from typing import Dict, List, Optional, Tuple

import numpy as np

try:  # bf16 storage needs ml_dtypes (shipped with jax); fp16/fp32 do not
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover — jax environments always have it
    _BF16 = None


ROW_DTYPES = ("fp32", "fp16", "bf16")


class RowPrecision:
    """Per-table storage-precision policy for the EMBEDDING portion of a
    PS entry — the widen-on-read / narrow-on-write half of the
    mixed-precision store.

    Entries keep the reference's ``[embedding | optimizer state]``
    layout, but under ``fp16``/``bf16`` the embedding slice is stored in
    half precision while the appended optimizer state stays fp32
    (Adagrad/Adam accumulators quantize catastrophically: the
    ``acc += grad²`` read-modify-write underflows in half precision once
    the accumulator outgrows the increment, silently freezing the
    effective LR). The stored form is then ONE contiguous uint8 buffer
    ``[emb as half | state as f32]`` — one ndarray per entry, same
    object-header overhead as the legacy fp32 layout, so the measured
    resident-bytes saving is the data saving.

    All optimizer math runs on widened fp32 matrices (:meth:`unpack` /
    :meth:`unpack_matrix` before ``SparseOptimizer.update``,
    :meth:`pack_into` after), so the update arithmetic is fp32-exact;
    the only precision loss is the final narrow of the embedding slice
    (one rounding per write, ≤ 2^-11 relative for fp16, ≤ 2^-8 for
    bf16). ``fp32`` keeps the legacy single-f32-array layout
    bit-identically."""

    def __init__(self, name: str = "fp32"):
        if name not in ROW_DTYPES:
            raise ValueError(
                f"unknown row_dtype {name!r} (expected one of {ROW_DTYPES})")
        if name == "bf16" and _BF16 is None:
            raise ValueError("row_dtype='bf16' requires ml_dtypes")
        self.name = name
        self.np_dtype = {
            "fp32": np.dtype(np.float32),
            "fp16": np.dtype(np.float16),
            "bf16": _BF16,
        }[name]
        self.itemsize = self.np_dtype.itemsize
        self.is_fp32 = name == "fp32"
        # (dim, space) -> structured dtype viewing one stored row as
        # [emb half | state f32] with ZERO copies — the batched
        # update's widen/narrow then costs one strided cast pass per
        # direction instead of a contiguous-copy chain
        self._struct_cache: Dict[Tuple[int, int], np.dtype] = {}

    def _row_struct(self, dim: int, space: int) -> np.dtype:
        dt = self._struct_cache.get((dim, space))
        if dt is None:
            fields = [("e", self.np_dtype, (dim,))]
            if space:
                fields.append(("s", np.float32, (space,)))
            dt = self._struct_cache[(dim, space)] = np.dtype(fields)
        return dt

    # --- byte math (capacity planning + the byte-accounting eviction) ---

    def emb_nbytes(self, dim: int) -> int:
        return dim * self.itemsize

    def entry_nbytes(self, dim: int, space: int) -> int:
        """Stored DATA bytes of one entry (embedding + optimizer state)."""
        return dim * self.itemsize + space * 4

    def stored_len(self, dim: int, space: int) -> int:
        """``len()`` of the stored array for an entry of this shape —
        f32 elements under fp32, raw bytes under half precision (the
        width check the update path uses in place of ``dim + space``)."""
        if self.is_fp32:
            return dim + space
        return self.entry_nbytes(dim, space)

    def state_len_of(self, vec: np.ndarray, dim: int) -> Optional[int]:
        """Optimizer-state f32 slots of a stored vec, or None if the
        byte length cannot belong to a ``dim``-wide entry."""
        if self.is_fp32:
            return len(vec) - dim if len(vec) >= dim else None
        extra = len(vec) - dim * self.itemsize
        if extra < 0 or extra % 4:
            return None
        return extra // 4

    # --- narrow-on-write --------------------------------------------------

    def pack(self, full: np.ndarray, dim: int) -> np.ndarray:
        """fp32 ``[emb | state]`` -> the stored form (fresh buffer)."""
        if self.is_fp32:
            return np.ascontiguousarray(full, dtype=np.float32)
        emb = np.ascontiguousarray(full[:dim]).astype(self.np_dtype)
        state = np.ascontiguousarray(full[dim:], dtype=np.float32)
        buf = np.empty(emb.nbytes + state.nbytes, np.uint8)
        buf[: emb.nbytes] = emb.view(np.uint8)
        if state.nbytes:
            buf[emb.nbytes:] = state.view(np.uint8)
        return buf

    def pack_into(self, full: np.ndarray, vec: np.ndarray, dim: int):
        """Narrow ``full`` (f32 [emb|state]) into the EXISTING stored
        buffer ``vec`` in place (the update path's write-back)."""
        if self.is_fp32:
            vec[:] = full
            return
        emb = np.ascontiguousarray(full[:dim]).astype(self.np_dtype)
        vec[: emb.nbytes] = emb.view(np.uint8)
        state = np.ascontiguousarray(full[dim:], dtype=np.float32)
        if state.nbytes:
            vec[emb.nbytes:] = state.view(np.uint8)

    # --- widen-on-read ----------------------------------------------------

    def emb_f32(self, vec: np.ndarray, dim: int) -> np.ndarray:
        """The embedding slice of a stored vec, widened to f32."""
        if self.is_fp32:
            return vec[:dim]
        return (np.ascontiguousarray(vec[: dim * self.itemsize])
                .view(self.np_dtype).astype(np.float32))

    def unpack(self, vec: np.ndarray, dim: int) -> np.ndarray:
        """Stored vec -> a fresh fp32 ``[emb | state]`` array."""
        if self.is_fp32:
            return np.array(vec, dtype=np.float32)
        esz = dim * self.itemsize
        out = np.empty(dim + (len(vec) - esz) // 4, np.float32)
        self.unpack_into(vec, dim, out)
        return out

    def unpack_into(self, vec: np.ndarray, dim: int, out: np.ndarray):
        if self.is_fp32:
            out[:] = vec
            return
        esz = dim * self.itemsize
        out[:dim] = (np.ascontiguousarray(vec[:esz]).view(self.np_dtype)
                     .astype(np.float32))
        if len(vec) > esz:
            out[dim:] = np.ascontiguousarray(vec[esz:]).view(np.float32)

    def unpack_matrix(self, vecs: List[np.ndarray], dim: int,
                      width: int) -> np.ndarray:
        """Widen uniform-shape stored vecs into one (n, width) fp32
        matrix for the batched optimizer call. One gather (np.stack)
        plus one strided cast pass per field — the structured-dtype
        view avoids any intermediate contiguous copies."""
        if self.is_fp32:
            return np.stack(vecs).astype(np.float32, copy=False)
        n = len(vecs)
        space = width - dim
        rec = np.stack(vecs).view(self._row_struct(dim, space))  # (n, 1)
        mat = np.empty((n, width), np.float32)
        mat[:, :dim] = rec["e"].reshape(n, dim)
        if space:
            mat[:, dim:] = rec["s"].reshape(n, space)
        return mat

    def narrow_matrix(self, mat: np.ndarray, dim: int) -> np.ndarray:
        """fp32 (n, dim+space) -> the stored byte layout as ONE
        (n, stored_len) uint8 matrix (one strided cast pass per field;
        rows are then copied out per entry)."""
        n, width = mat.shape
        space = width - dim
        stored = np.empty((n, self.entry_nbytes(dim, space)), np.uint8)
        rec = stored.view(self._row_struct(dim, space))
        rec["e"].reshape(n, dim)[...] = mat[:, :dim]
        if space:
            rec["s"].reshape(n, space)[...] = mat[:, dim:]
        return stored

    def pack_matrix_into(self, mat: np.ndarray,
                         vecs: List[np.ndarray], dim: int):
        """Narrow the updated fp32 matrix back into the stored per-entry
        buffers (which stay the live objects in the eviction map). The
        narrow is one vectorized pass; the write-back is ONE assignment
        per row — same per-row cost as the fp32 path."""
        if self.is_fp32:
            for row, vec in zip(mat, vecs):
                vec[:] = row
            return
        stored = self.narrow_matrix(mat, dim)
        for i, vec in enumerate(vecs):
            vec[:] = stored[i]


class SparseOptimizer:
    """Interface of a server-side optimizer (reference: optim.rs:66-92)."""

    def require_space(self, dim: int) -> int:
        """Extra f32 slots appended to each entry for optimizer state."""
        return 0

    def state_initialization(self, entries: np.ndarray, dim: int) -> None:
        """Initialize the state slice ``entries[:, dim:]`` in place."""

    def batch_level_state(self, signs: np.ndarray) -> Optional[np.ndarray]:
        """Per-sign state computed once per update batch (Adam beta powers)."""
        return None

    def update(
        self,
        entries: np.ndarray,
        grads: np.ndarray,
        dim: int,
        batch_level_state: Optional[np.ndarray] = None,
    ) -> None:
        """Apply one optimizer step to every row of ``entries`` in place."""
        raise NotImplementedError

    def update_lr(self, lr: float) -> None:
        pass

    def to_config(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_config(config: dict, feature_index_prefix_bit: int = 0) -> "SparseOptimizer":
        kind = config["type"]
        kwargs = {k: v for k, v in config.items() if k != "type"}
        if kind == "sgd":
            return SparseSGD(**kwargs)
        if kind == "adagrad":
            return SparseAdagrad(**kwargs)
        if kind == "adam":
            return SparseAdam(
                feature_index_prefix_bit=feature_index_prefix_bit, **kwargs
            )
        raise ValueError(f"unknown sparse optimizer type {kind!r}")


class SparseSGD(SparseOptimizer):
    """Decayed SGD: ``emb -= lr * (grad + wd * emb)``
    (reference: optim.rs:223-244, persia-simd/src/lib.rs:124-144)."""

    def __init__(self, lr: float, wd: float = 0.0):
        self.lr = float(lr)
        self.wd = float(wd)

    def update(self, entries, grads, dim, batch_level_state=None):
        emb = entries[:, :dim]
        emb -= self.lr * (grads + self.wd * emb)

    def update_lr(self, lr: float) -> None:
        self.lr = lr

    def to_config(self) -> dict:
        return {"type": "sgd", "lr": self.lr, "wd": self.wd}


class SparseAdagrad(SparseOptimizer):
    """Decayed Adagrad, optionally with a single accumulator shared across
    the vector (reference: optim.rs:246-307).

    Non-shared: ``emb -= lr * grad / sqrt(acc + eps); acc = acc*g2m + grad²``.
    Shared: the accumulator used for the step is the value *before* this
    batch's gradient is accumulated (simd lib.rs:83-121 note).
    """

    def __init__(
        self,
        lr: float = 1e-2,
        wd: float = 0.0,
        g_square_momentum: float = 1.0,
        initialization: float = 1e-2,
        eps: float = 1e-10,
        vectorwise_shared: bool = False,
    ):
        self.lr = float(lr)
        self.wd = float(wd)
        self.g_square_momentum = float(g_square_momentum)
        self.initialization = float(initialization)
        self.eps = float(eps)
        self.vectorwise_shared = bool(vectorwise_shared)

    def require_space(self, dim: int) -> int:
        return 1 if self.vectorwise_shared else dim

    def state_initialization(self, entries, dim):
        entries[:, dim:] = self.initialization

    def update(self, entries, grads, dim, batch_level_state=None):
        emb = entries[:, :dim]
        if self.vectorwise_shared:
            acc = entries[:, dim]  # (n,)
            scale = self.lr / np.sqrt(acc + self.eps)
            emb -= scale[:, None] * grads
            g2 = np.mean(grads * grads, axis=1)
            entries[:, dim] = acc * self.g_square_momentum + g2
        else:
            acc = entries[:, dim:]
            emb -= self.lr * grads / np.sqrt(acc + self.eps)
            acc *= self.g_square_momentum
            acc += grads * grads

    def update_lr(self, lr: float) -> None:
        self.lr = lr

    def to_config(self) -> dict:
        return {
            "type": "adagrad",
            "lr": self.lr,
            "wd": self.wd,
            "g_square_momentum": self.g_square_momentum,
            "initialization": self.initialization,
            "eps": self.eps,
            "vectorwise_shared": self.vectorwise_shared,
        }


class SparseAdam(SparseOptimizer):
    """Adam with per-feature-group accumulated beta powers
    (reference: optim.rs:94-221).

    The bias-correction powers are tracked per feature group (identified by
    the sign's index-prefix bits) and advanced once per update batch per
    group, mirroring the reference exactly — including its quirk that the
    powers start at β and are advanced *before* first use, so the first
    step corrects with β².
    """

    def __init__(
        self,
        lr: float = 1e-3,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
        feature_index_prefix_bit: int = 0,
    ):
        self.lr = float(lr)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)
        self.eps = float(eps)
        self.feature_index_prefix_bit = int(feature_index_prefix_bit)
        # group prefix -> accumulated (beta1^t, beta2^t), f32 like the reference
        self._accum: Dict[int, Tuple[np.float32, np.float32]] = {}

    def require_space(self, dim: int) -> int:
        return dim * 2

    def batch_level_state(self, signs: np.ndarray) -> np.ndarray:
        if self.feature_index_prefix_bit > 0:
            mask = ~((1 << (64 - self.feature_index_prefix_bit)) - 1) & (
                (1 << 64) - 1
            )
        else:
            mask = 0
        masked = (signs.astype(np.uint64) & np.uint64(mask)).tolist()
        out = np.empty((len(masked), 2), dtype=np.float32)
        stepped: Dict[int, Tuple[np.float32, np.float32]] = {}
        b1 = np.float32(self.beta1)
        b2 = np.float32(self.beta2)
        for i, g in enumerate(masked):
            if g in stepped:
                out[i] = stepped[g]
                continue
            p1, p2 = self._accum.get(g, (b1, b2))
            p1 = np.float32(p1 * b1)
            p2 = np.float32(p2 * b2)
            self._accum[g] = (p1, p2)
            stepped[g] = (p1, p2)
            out[i] = (p1, p2)
        return out

    def update(self, entries, grads, dim, batch_level_state=None):
        if batch_level_state is None:
            raise ValueError("SparseAdam.update requires batch_level_state")
        emb = entries[:, :dim]
        m = entries[:, dim : 2 * dim]
        v = entries[:, 2 * dim : 3 * dim]
        b1p = batch_level_state[:, 0][:, None]
        b2p = batch_level_state[:, 1][:, None]
        m *= self.beta1
        m += (1.0 - self.beta1) * grads
        v *= self.beta2
        v += (1.0 - self.beta2) * grads * grads
        m_hat = m / (1.0 - b1p)
        v_hat = v / (1.0 - b2p)
        emb -= self.lr * m_hat / (self.eps + np.sqrt(v_hat))

    def update_lr(self, lr: float) -> None:
        self.lr = lr

    def to_config(self) -> dict:
        return {
            "type": "adam",
            "lr": self.lr,
            "beta1": self.beta1,
            "beta2": self.beta2,
            "eps": self.eps,
        }


def apply_weight_bound(emb: np.ndarray, bound: float) -> None:
    """Clamp embeddings to [-bound, bound] in place
    (reference: persia-simd/src/lib.rs:231-251, applied at
    embedding_parameter_service/mod.rs:398)."""
    np.clip(emb, -bound, bound, out=emb)
