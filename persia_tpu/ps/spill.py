"""Disk spill tier for the embedding parameter store.

The bottom rung of the storage ladder (HBM device cache <-> host PS RAM
<-> disk): when the holder's row/byte-budget eviction would DROP a cold
row, a spill-armed holder hands it here instead, and a later access
faults it back in transparently — so capacity pressure demotes rows down
the ladder rather than destroying training state.

Layout: evicted rows stage in memory and flush as immutable append-only
**packet** files (``spill_<seq>.pkt``) through
:class:`~persia_tpu.storage.PersiaPath` (local disk or ``hdfs://``),
written atomically (tmp + rename) so a crash mid-write leaves either a
complete packet or a cleanable ``*.tmp`` — never a torn file that a
fault-in would decode as garbage. An in-memory index maps ``sign ->
(packet, offset, nbytes, dim)``; fault-in is one ranged read. Records
keep the holder's STORED byte form (fp32 f32 vector, or the
RowPrecision half layout), so a spill -> fault-in round trip is
bit-identical by construction — the parity the tier bench pins.

Dead space: a faulted-in row's bytes stay behind in its packet; the
packet is deleted once its last live row leaves. A ``max_bytes`` budget
drops whole OLDEST packets (their still-live rows die — the cold-cold
end of the ladder, counted in ``dropped_rows``).

Thread-safety: one lock guards index + staging + packet table. The
holder calls in under its per-shard locks (shard lock -> spill lock,
strictly; this module never calls back into the holder), so the spill
lock is a leaf like the hotness tracker's.

Failure semantics: a fault-in whose packet is missing or truncated
raises :class:`SpillReadError` (a typed ``IOError``) and leaves both
the index entry and the holder untouched — callers see a loud error,
not a silently corrupted or quietly re-initialized row.
"""

import os
import struct
import subprocess
import threading
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from persia_tpu.storage import PersiaPath

# per-record header: sign u64 | dim u32 | stored-vec nbytes u32
_REC = struct.Struct("<QII")


class SpillReadError(IOError):
    """A spilled row could not be read back (packet missing/truncated/
    corrupt). The spill index and the holder are left untouched."""


class SpillStore:
    """Append-only packet store of evicted rows with an in-memory index.

    ``stored`` vecs are whatever the holder keeps in its eviction maps
    (f32 arrays for fp32 holders, uint8 half layouts otherwise); this
    store never reinterprets them — bytes in, the same bytes out.
    """

    PACKET_BYTES = 4 << 20  # flush staging once this many bytes accrue

    def __init__(self, root: str, max_bytes: Optional[int] = None,
                 packet_bytes: Optional[int] = None):
        self.root = root
        self.max_bytes = max_bytes or None
        self.packet_bytes = int(packet_bytes or self.PACKET_BYTES)
        self._lock = threading.Lock()
        # sign -> (packet_seq, offset, nbytes, dim); packet_seq 0 means
        # "still staged in memory"
        self._index: Dict[int, Tuple[int, int, int, int]] = {}
        # staged (not yet on disk) sign -> (dim, stored vec)
        self._staged: "OrderedDict[int, Tuple[int, np.ndarray]]" = \
            OrderedDict()
        self._staged_bytes = 0
        # packet_seq -> [path, data_bytes, live_rows]
        self._packets: "OrderedDict[int, List]" = OrderedDict()
        self._seq = 0
        self.disk_bytes = 0
        # active dump capture (sign -> (dim, stored vec)) or None; see
        # start_dump_capture
        self._capture: Optional[Dict[int, Tuple[int, np.ndarray]]] = None
        # counters (read under the lock via stats(); plain ints)
        self.spilled_rows_total = 0
        self.fault_ins_total = 0
        self.dropped_rows = 0
        PersiaPath(root).makedirs()
        self._sweep_partials()

    # --- hygiene ---------------------------------------------------------

    def _sweep_partials(self):
        """Remove torn ``*.tmp`` packets left by a crash mid-write (the
        atomic rename means a ``.pkt`` is always complete) AND any
        previous run's ``*.pkt`` files: the sign->packet index lives
        only in memory, so after a restart those packets are
        unreadable dead bytes — the authoritative restore path is the
        checkpoint (+ inc replay). Left in place they would sit
        outside the ``max_bytes`` accounting forever and collide by
        name with this run's packets (``_seq`` restarts at 0)."""
        try:
            names = PersiaPath(self.root).listdir()
        except (OSError, RuntimeError):
            return
        for p in names:
            if p.endswith(".tmp") or p.endswith(".pkt"):
                try:
                    PersiaPath(p).remove()
                except (OSError, RuntimeError):
                    pass

    def _packet_path(self, seq: int) -> str:
        return os.path.join(self.root, f"spill_{seq:08d}.pkt")

    # --- spill (holder eviction path) ------------------------------------

    def put(self, sign: int, dim: int, stored: np.ndarray):
        """Stage one evicted row (overwrites any older spilled copy —
        the eviction carries the freshest value). The vec is kept (and
        later returned) as its raw uint8 byte image, whatever the
        holder's stored dtype — the store never reinterprets row bytes.
        Flushes a packet once the staging buffer reaches
        ``packet_bytes``."""
        sign = int(sign)
        with self._lock:
            self._evict_index_locked(sign)
            vec = np.ascontiguousarray(stored).view(np.uint8)
            self._staged[sign] = (int(dim), vec)
            self._staged_bytes += vec.nbytes
            self._index[sign] = (0, 0, vec.nbytes, int(dim))
            self.spilled_rows_total += 1
            if self._staged_bytes >= self.packet_bytes:
                self._flush_locked()

    def put_batch(self, signs: np.ndarray, dim: int, rows: np.ndarray):
        """Stage a SLAB SLICE of evicted rows in one call: ``rows`` is a
        ``(k, nbytes)`` uint8 matrix of stored (logical) records, one
        per sign. Each staged entry keeps a VIEW into the matrix — no
        per-row byte copies on the demotion path; serialization happens
        once, at packet flush. One lock acquisition for the batch."""
        if len(signs) == 0:
            return
        rows = np.ascontiguousarray(rows).view(np.uint8)
        nbytes = int(rows.shape[1])
        with self._lock:
            for i, sign in enumerate(signs.tolist()):
                sign = int(sign)
                self._evict_index_locked(sign)
                self._staged[sign] = (int(dim), rows[i])
                self._staged_bytes += nbytes
                self._index[sign] = (0, 0, nbytes, int(dim))
            self.spilled_rows_total += len(signs)
            if self._staged_bytes >= self.packet_bytes:
                self._flush_locked()

    def contains_batch(self, signs: np.ndarray) -> np.ndarray:
        """Vectorized membership (one lock acquisition): bool mask of
        which signs currently have a spilled copy — the native
        wrapper's pre-lookup fault-in planner."""
        with self._lock:
            if not self._index:
                return np.zeros(len(signs), dtype=bool)
            idx = self._index
            return np.fromiter((int(s) in idx for s in signs),
                               dtype=bool, count=len(signs))

    def flush(self):
        """Write every staged row to a packet (tests/checkpoint sync
        points; the spill path flushes on its own cadence)."""
        with self._lock:
            self._flush_locked()

    def _flush_locked(self):
        if not self._staged:
            return
        self._seq += 1
        seq = self._seq
        chunks = []
        offset = 0
        placed = []
        for sign, (dim, vec) in self._staged.items():
            raw = vec.tobytes()
            chunks.append(_REC.pack(sign, dim, len(raw)))
            chunks.append(raw)
            placed.append((sign, offset + _REC.size, len(raw), dim))
            offset += _REC.size + len(raw)
        data = b"".join(chunks)
        PersiaPath(self._packet_path(seq)).write_bytes_atomic(data)
        for sign, off, nbytes, dim in placed:
            self._index[sign] = (seq, off, nbytes, dim)
        self._packets[seq] = [self._packet_path(seq), len(data),
                              len(placed)]
        self.disk_bytes += len(data)
        self._staged = OrderedDict()
        self._staged_bytes = 0
        self._enforce_budget_locked()

    def _enforce_budget_locked(self):
        while (self.max_bytes is not None and len(self._packets) > 1
               and self.disk_bytes > self.max_bytes):
            seq, (path, nbytes, live) = next(iter(self._packets.items()))
            del self._packets[seq]
            self.disk_bytes -= nbytes
            if live:
                # cold-cold rows in the dropped packet die last-tier
                dead = [s for s, loc in self._index.items()
                        if loc[0] == seq]
                for s in dead:
                    del self._index[s]
                self.dropped_rows += live
            try:
                PersiaPath(path).remove()
            except (OSError, RuntimeError):
                pass

    # --- fault-in (holder access path) -----------------------------------

    def __contains__(self, sign: int) -> bool:
        with self._lock:
            return int(sign) in self._index

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def take(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        """Remove and return ``(dim, stored vec)`` for a spilled sign
        (None if absent) — the fault-in that promotes the row back to
        the RAM tier. Raises :class:`SpillReadError`, leaving the entry
        indexed, when the packet cannot be read."""
        sign = int(sign)
        with self._lock:
            loc = self._index.get(sign)
            if loc is None:
                return None
            dim, vec = self._read_locked(sign, loc)
            if self._capture is not None:
                self._capture[sign] = (dim, vec)
            self._evict_index_locked(sign)
            self.fault_ins_total += 1
            return dim, vec

    def discard(self, sign: int):
        """Drop any spilled copy of ``sign`` without reading it — the
        holder calls this before (re)inserting a sign resident, keeping
        the invariant that a resident row never shadows a stale disk
        copy."""
        sign = int(sign)
        with self._lock:
            if self._capture is not None and sign in self._index:
                try:
                    self._capture[sign] = self._read_locked(
                        sign, self._index[sign])
                except SpillReadError:
                    pass  # unreadable anyway; nothing to preserve
            self._evict_index_locked(sign)

    # --- dump-window capture ---------------------------------------------

    def start_dump_capture(self):
        """Arm the checkpoint-consistency net: while a dump is
        serializing shards, a row leaving the spill tier (fault-in /
        discard) AFTER its destination shard was already serialized
        would appear in neither section and silently fall out of the
        checkpoint. Between start and stop, every row removed from the
        index is also recorded here; the dump prepends those records
        (lowest load priority — any shard/spill record of the same
        sign is newer and wins on load)."""
        with self._lock:
            self._capture = {}

    def stop_dump_capture(self) -> Dict[int, Tuple[int, np.ndarray]]:
        """Disarm and return the rows captured since
        :meth:`start_dump_capture`."""
        with self._lock:
            cap, self._capture = self._capture, None
            return cap or {}

    def peek(self, sign: int) -> Optional[Tuple[int, np.ndarray]]:
        """Read WITHOUT removing — the read-only (eval/serving) path,
        which must not mutate tier residency."""
        sign = int(sign)
        with self._lock:
            loc = self._index.get(sign)
            if loc is None:
                return None
            return self._read_locked(sign, loc)

    def _read_locked(self, sign: int, loc) -> Tuple[int, np.ndarray]:
        seq, offset, nbytes, dim = loc
        if seq == 0:
            return self._staged[sign]
        pkt = self._packets.get(seq)
        if pkt is None:
            raise SpillReadError(
                f"spilled sign {sign}: packet seq {seq} is gone")
        try:
            raw = PersiaPath(pkt[0]).read_range(offset, nbytes)
        except (OSError, RuntimeError,
                subprocess.CalledProcessError) as e:
            raise SpillReadError(
                f"spilled sign {sign}: cannot read {pkt[0]} "
                f"[{offset}:{offset + nbytes}]: {e}") from e
        return dim, np.frombuffer(raw, dtype=np.uint8).copy()

    def _evict_index_locked(self, sign: int):
        loc = self._index.pop(sign, None)
        if loc is None:
            return
        seq = loc[0]
        if seq == 0:
            dim, vec = self._staged.pop(sign)
            self._staged_bytes -= vec.nbytes
            return
        pkt = self._packets.get(seq)
        if pkt is not None:
            pkt[2] -= 1
            if pkt[2] <= 0:  # last live row left: reclaim the packet
                del self._packets[seq]
                self.disk_bytes -= pkt[1]
                try:
                    PersiaPath(pkt[0]).remove()
                except (OSError, RuntimeError):
                    pass

    # --- whole-table views (checkpoint / len) ----------------------------

    def items(self) -> Iterator[Tuple[int, int, np.ndarray]]:
        """Yield every live spilled ``(sign, dim, stored vec)`` — the
        checkpoint path's view of the disk tier. Iterates a snapshot of
        the index so concurrent spills/fault-ins don't invalidate it;
        rows that leave mid-iteration are skipped."""
        with self._lock:
            snapshot = list(self._index.items())
        for sign, loc in snapshot:
            with self._lock:
                cur = self._index.get(sign)
                if cur is None:
                    continue
                try:
                    dim, vec = self._read_locked(sign, cur)
                except SpillReadError:
                    continue
            yield sign, dim, vec

    def clear(self):
        with self._lock:
            for seq, (path, _nbytes, _live) in self._packets.items():
                try:
                    PersiaPath(path).remove()
                except (OSError, RuntimeError):
                    pass
            self._packets = OrderedDict()
            self._index = {}
            self._staged = OrderedDict()
            self._staged_bytes = 0
            self.disk_bytes = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "spilled_rows": len(self._index),
                "spill_disk_bytes": self.disk_bytes,
                "spill_staged_bytes": self._staged_bytes,
                "spill_packets": len(self._packets),
                "spilled_rows_total": self.spilled_rows_total,
                "spill_fault_ins_total": self.fault_ins_total,
                "spill_dropped_rows": self.dropped_rows,
            }
