"""Client-side sparse-optimizer configuration (reference: persia/embedding/optim.py).

These wrappers only *describe* the optimizer; the numerics run server-side
(:mod:`persia_tpu.ps.optim`). ``apply()`` registers the config on every
parameter server through the active context, mirroring the reference's
NATS `register_optimizer` broadcast (persia-core/src/optim.rs:61-66).
"""

from abc import ABC
from typing import Tuple


class Optimizer(ABC):
    """Base class: holds a serializable server-side optimizer config."""

    def __init__(self):
        self.config: dict = {}

    def apply(self):
        """Register this optimizer on all parameter servers via the
        currently-entered context."""
        from persia_tpu.ctx import current_ctx

        ctx = current_ctx()
        if ctx is None:
            raise RuntimeError(
                "Optimizer.apply() requires an active EmbeddingCtx/TrainCtx"
            )
        ctx.register_optimizer(self)


class SGD(Optimizer):
    def __init__(self, lr: float, momentum: float = 0.0, weight_decay: float = 0.0):
        super().__init__()
        if momentum != 0.0:
            raise NotImplementedError(
                "momentum is not supported by the server-side SGD "
                "(the reference accepts and ignores it; we reject it)"
            )
        self.lr = lr
        self.weight_decay = weight_decay
        self.config = {"type": "sgd", "lr": lr, "wd": weight_decay}


class Adam(Optimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        weight_decay: float = 0.0,
        eps: float = 1e-8,
    ):
        super().__init__()
        self.lr = lr
        self.betas = betas
        self.weight_decay = weight_decay
        self.eps = eps
        self.config = {
            "type": "adam",
            "lr": lr,
            "beta1": betas[0],
            "beta2": betas[1],
            "eps": eps,
        }


class Adagrad(Optimizer):
    def __init__(
        self,
        lr: float = 1e-2,
        initial_accumulator_value: float = 1e-2,
        weight_decay: float = 0.0,
        g_square_momentum: float = 1.0,
        eps: float = 1e-10,
        vectorwise_shared: bool = False,
    ):
        super().__init__()
        self.lr = lr
        self.initial_accumulator_value = initial_accumulator_value
        self.weight_decay = weight_decay
        self.g_square_momentum = g_square_momentum
        self.eps = eps
        self.vectorwise_shared = vectorwise_shared
        self.config = {
            "type": "adagrad",
            "lr": lr,
            "wd": weight_decay,
            "g_square_momentum": g_square_momentum,
            "initialization": initial_accumulator_value,
            "eps": eps,
            "vectorwise_shared": vectorwise_shared,
        }
