"""Client-side embedding hyperparameters (reference: persia/embedding/__init__.py).

``EmbeddingConfig`` travels with :class:`~persia_tpu.ctx.EmbeddingCtx` to the
parameter servers, where it gates admission of new signs
(embedding_parameter_service/mod.rs:215-230) and bounds weights after every
update (mod.rs:398).
"""

from typing import Tuple


class EmbeddingConfig:
    """Embedding hyperparameters, argument of ``EmbeddingCtx``.

    Args:
        emb_initialization: lower and upper bound of the per-sign uniform
            initialization of new embedding entries.
        admit_probability: probability (in [0, 1]) of admitting a new sign
            on first lookup; non-admitted signs read as zeros.
        weight_bound: each embedding element is clamped to
            ``[-weight_bound, weight_bound]`` after updates.
    """

    def __init__(
        self,
        emb_initialization: Tuple[float, float] = (-0.01, 0.01),
        admit_probability: float = 1.0,
        weight_bound: float = 10.0,
    ):
        if not 0.0 <= admit_probability <= 1.0:
            raise ValueError("admit_probability must be within [0, 1]")
        self.emb_initialization = emb_initialization
        self.admit_probability = admit_probability
        self.weight_bound = weight_bound


def get_default_embedding_config() -> EmbeddingConfig:
    return EmbeddingConfig()
