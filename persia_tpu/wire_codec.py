"""Payload precision codec for the embedding-tier wire.

Encoders/decoders for the negotiated RPC payload codec (rpc.py's
``__codec__`` probe): lookup responses ship **fp16** rows, gradient
pushes ship **int8** rows with one f32 scale per row — the sparse-tier
analogue of the dense allreduce's int8 error-feedback scheme
(``parallel/train.py::_ef_int8_mean``, which quantizes per 1024-element
bucket; embedding rows are short, so per-ROW scales are the natural
bucket here). Tensor Casting (arxiv 2010.13100) is the empirical license:
embedding-gradient traffic tolerates aggressive precision reduction when
the quantization residual is fed back into the next step's gradient —
the residual store lives client-side in
:class:`persia_tpu.worker.middleware.GradErrorFeedback`.

Error bounds (documented for the parity tests and the bench gates):

- fp16 rows: ≤ 2^-11 relative per element (round-to-nearest half
  precision; embeddings are weight-bounded to [-10, 10], well inside
  fp16 range).
- int8 rows: per element ≤ ``max(|row|) / 254`` absolute per shipment;
  with error feedback the bias cancels across steps and SGD tracks the
  uncompressed trajectory (the convergence smoke pins this).

Everything here is pure numpy and symmetric: the client encodes what the
server decodes and vice versa; the ``codec`` key in the pack_arrays meta
dict names the payload's encoding, so frames stay self-describing and a
legacy fp32 payload is simply one without the key.
"""

from typing import Tuple

import numpy as np

# int8 symmetric range: +-127 (never -128, so dequant is symmetric)
_Q = 127.0


def encode_fp16_rows(rows: np.ndarray) -> np.ndarray:
    """f32 (n, d) -> fp16 (n, d); values are weight-bounded, no overflow."""
    return np.ascontiguousarray(rows, dtype=np.float16)


def decode_fp16_rows(rows: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(rows).astype(np.float32)


def quantize_int8_rows(
    rows: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """f32 (n, d) -> (q int8 (n, d), scales f32 (n,), residual f32 (n, d)).

    Per-row symmetric quantization: ``scale = max(|row|)/127``,
    ``q = round(row/scale)``. The residual ``row - q*scale`` is what the
    caller feeds back into the next shipment of the same sign (error
    feedback); shipping it is optional — dropping it degrades to plain
    deterministic rounding."""
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    scales = np.maximum(np.max(np.abs(rows), axis=1) / _Q, 1e-30).astype(
        np.float32)
    q = np.clip(np.rint(rows / scales[:, None]), -_Q, _Q).astype(np.int8)
    residual = rows - q.astype(np.float32) * scales[:, None]
    return q, scales, residual


def dequantize_int8_rows(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    q = np.ascontiguousarray(q)
    scales = np.ascontiguousarray(scales, dtype=np.float32)
    return q.astype(np.float32) * scales[:, None]
