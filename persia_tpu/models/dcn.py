"""DCN-v2: deep & cross network with full-matrix cross layers.

One of BASELINE.md's alternate dense towers. Cross layers compute
``x_{l+1} = x0 * (W_l x_l + b_l) + x_l`` (the v2 formulation) with the
matmul in bf16 on the MXU.
"""

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import MLP, flatten_embeddings


class CrossLayer(nn.Module):
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x0, x):
        w = nn.Dense(x0.shape[-1], dtype=self.compute_dtype)(x)
        return x0 * w + x


class DCNv2(nn.Module):
    num_cross_layers: int = 3
    deep_mlp: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors: Sequence[jnp.ndarray],
                 embedding_tensors: Sequence[Any], train: bool = False):
        dt = self.compute_dtype
        parts = [t.astype(dt) for t in non_id_tensors]
        parts.append(flatten_embeddings(embedding_tensors).astype(dt))
        x0 = jnp.concatenate(parts, axis=1)

        x = x0
        for _ in range(self.num_cross_layers):
            x = CrossLayer(compute_dtype=dt)(x0, x)

        deep = MLP(self.deep_mlp, compute_dtype=dt)(x0, train)
        combined = jnp.concatenate([x, deep], axis=1)
        out = nn.Dense(1, dtype=dt)(combined)
        return nn.sigmoid(out.astype(jnp.float32))
