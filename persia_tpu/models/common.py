"""Shared building blocks for the dense model zoo.

The dense tower is the part of a PERSIA-style model that runs on the
accelerator (reference: examples/src/adult-income/model.py and the torch
models users bring). Here it is flax.linen, designed TPU-first:

- **bf16 compute, f32 params**: matmuls run in bfloat16 on the MXU; the
  parameter copy and batch-norm statistics stay float32 (no loss-scaler
  needed — bf16 has f32's exponent range, unlike the reference's fp16
  GradScaler path in persia/ctx.py:753-852).
- **Static shapes**: raw (sequence) slots arrive as a fixed-capacity
  distinct tensor + index tensor (see worker/middleware.py) and are
  gathered on device — one XLA gather instead of host-side re-assembly.
"""

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def gather_raw_embedding(
    embeddings: jnp.ndarray, index: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expand a RawEmbedding (capacity, dim) + (bs, sfs) index into a
    (bs, sfs, dim) tensor and its (bs, sfs) validity mask.

    Row 0 of ``embeddings`` is zeros, so padded positions contribute zero
    without masking; the mask is still returned for attention-style use.
    """
    gathered = jnp.take(embeddings, index, axis=0)
    mask = index > 0
    return gathered, mask


def flatten_embeddings(embedding_tensors: Sequence[Any]) -> jnp.ndarray:
    """Concatenate model-ready embedding inputs along features.

    Each element is either a (bs, dim) summed tensor or a (emb, index)
    raw pair, which is gathered and mean-pooled over valid positions.
    """
    parts = []
    for e in embedding_tensors:
        if isinstance(e, (tuple, list)):
            emb, index = e
            gathered, mask = gather_raw_embedding(emb, index)
            denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
            parts.append(gathered.sum(axis=1) / denom)
        else:
            parts.append(e)
    return jnp.concatenate(parts, axis=1)


def stack_field_embeddings(embedding_tensors: Sequence[Any]) -> jnp.ndarray:
    """(bs, F, dim) field stack for interaction layers (DLRM/DeepFM).
    All fields must share one dim; raw slots are mean-pooled first."""
    parts = []
    for e in embedding_tensors:
        if isinstance(e, (tuple, list)):
            emb, index = e
            gathered, mask = gather_raw_embedding(emb, index)
            denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
            parts.append(gathered.sum(axis=1) / denom)
        else:
            parts.append(e)
    return jnp.stack(parts, axis=1)


class MLP(nn.Module):
    """Dense stack with optional batch-norm and configurable activation."""

    features: Sequence[int]
    activation: Callable = nn.relu
    use_batch_norm: bool = False
    final_activation: bool = True
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.compute_dtype)
        for i, width in enumerate(self.features):
            x = nn.Dense(width, dtype=self.compute_dtype)(x)
            is_last = i == len(self.features) - 1
            if not is_last or self.final_activation:
                if self.use_batch_norm:
                    x = nn.BatchNorm(
                        use_running_average=not train, dtype=jnp.float32
                    )(x.astype(jnp.float32)).astype(self.compute_dtype)
                x = self.activation(x)
        return x
