"""Dense model zoo (flax.linen, bf16-first).

Every model shares the calling convention of the reference's example
towers (examples/src/adult-income/model.py): ``model(non_id_tensors,
embedding_tensors, train=...)`` where embedding_tensors holds (bs, dim)
summed slots and (embeddings, index) raw pairs.
"""

from persia_tpu.models.common import (
    MLP,
    flatten_embeddings,
    gather_raw_embedding,
    stack_field_embeddings,
)
from persia_tpu.models.dcn import DCNv2
from persia_tpu.models.deepfm import DeepFM
from persia_tpu.models.dlrm import DLRM
from persia_tpu.models.dnn import DNN
from persia_tpu.models.seq import SequenceSelfAttention, SequenceTower
from persia_tpu.models.wide_deep import WideAndDeep

__all__ = [
    "MLP",
    "DNN",
    "DLRM",
    "DCNv2",
    "DeepFM",
    "SequenceTower",
    "WideAndDeep",
    "SequenceSelfAttention",
    "flatten_embeddings",
    "gather_raw_embedding",
    "stack_field_embeddings",
]
