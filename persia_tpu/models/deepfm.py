"""DeepFM: factorization-machine interaction + deep tower.

Second-order FM uses the sum-square trick over the (bs, F, d) field stack
— two elementwise ops and two reductions, fully fused by XLA.
"""

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import MLP, stack_field_embeddings


class DeepFM(nn.Module):
    deep_mlp: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors: Sequence[jnp.ndarray],
                 embedding_tensors: Sequence[Any], train: bool = False):
        dt = self.compute_dtype
        fields = stack_field_embeddings(embedding_tensors).astype(dt)
        bs, f, d = fields.shape

        # first order: per-field scalar projection + dense features
        first = nn.Dense(1, dtype=dt)(fields.reshape(bs, f * d))
        if non_id_tensors:
            dense_x = jnp.concatenate(
                [t.astype(dt) for t in non_id_tensors], axis=1)
            first += nn.Dense(1, dtype=dt)(dense_x)
        else:
            dense_x = None

        # second order: 0.5 * ((Σv)² - Σv²)
        sum_v = fields.sum(axis=1)
        second = 0.5 * (sum_v * sum_v - (fields * fields).sum(axis=1))
        second = second.sum(axis=1, keepdims=True)

        deep_in = (
            jnp.concatenate([fields.reshape(bs, f * d), dense_x], axis=1)
            if dense_x is not None else fields.reshape(bs, f * d)
        )
        deep = MLP(self.deep_mlp, compute_dtype=dt)(deep_in, train)
        deep_out = nn.Dense(1, dtype=dt)(deep)

        logit = first.astype(jnp.float32) + second.astype(jnp.float32) + \
            deep_out.astype(jnp.float32)
        return nn.sigmoid(logit)
