"""Wide & Deep: linear (wide) memorization + MLP (deep) generalization.

Rounds out the dense-tower family alongside DNN/DLRM/DCN-v2/DeepFM. The
wide part is a single linear layer over all features; the deep part an
MLP; outputs sum into one logit.
"""

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import MLP, flatten_embeddings


class WideAndDeep(nn.Module):
    deep_mlp: Sequence[int] = (256, 128, 64)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors, embedding_tensors, train: bool = False):
        dt = self.compute_dtype
        parts = [t.astype(dt) for t in non_id_tensors]
        parts.append(flatten_embeddings(embedding_tensors).astype(dt))
        x = jnp.concatenate(parts, axis=1)
        wide = nn.Dense(1, dtype=dt, name="wide")(x)
        deep = MLP(self.deep_mlp, compute_dtype=dt)(x, train)
        deep = nn.Dense(1, dtype=dt, name="deep_head")(deep)
        return nn.sigmoid((wide + deep).astype(jnp.float32))
