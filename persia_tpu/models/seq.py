"""Sequence tower: self-attention over user-history (raw) slots.

Recommendation models increasingly attend over long user-behavior
sequences (DIN/SASRec-style). The reference can only bag-sum its raw
slots; here raw slots become true sequences: gather → multi-head
self-attention → masked mean pool, with the attention core switchable to
ring attention over a mesh axis for histories too long for one chip
(persia_tpu/parallel/ring_attention.py).
"""

from typing import Any, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import MLP, gather_raw_embedding


class SequenceSelfAttention(nn.Module):
    """``context_parallel`` picks the strategy when a mesh is present:
    "ring" (ppermute K/V rotation; any head count) or "ulysses"
    (two all_to_all collectives; needs heads % axis_size == 0)."""

    num_heads: int = 2
    compute_dtype: Any = jnp.bfloat16
    mesh: Optional[Any] = None
    seq_axis: str = "model"
    causal: bool = False
    context_parallel: str = "ring"  # "ring" | "ulysses"
    # single-device / per-shard kernel: "xla" (dense reference or scan)
    # or "pallas" (VMEM-resident flash, persia_tpu.ops.flash_attention)
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x, mask):
        """x: (bs, t, d); mask: (bs, t) bool -> (bs, t, d)."""
        from persia_tpu.parallel.ring_attention import (
            reference_attention,
            ring_self_attention,
        )
        from persia_tpu.parallel.ulysses import ulysses_self_attention

        bs, t, d = x.shape
        dh = max(1, d // self.num_heads)
        dt = self.compute_dtype
        q = nn.Dense(self.num_heads * dh, dtype=dt)(x.astype(dt))
        k = nn.Dense(self.num_heads * dh, dtype=dt)(x.astype(dt))
        v = nn.Dense(self.num_heads * dh, dtype=dt)(x.astype(dt))

        def heads(y):  # (bs, t, h*dh) -> (bs, h, t, dh)
            return y.reshape(bs, t, self.num_heads, dh).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        # padded positions are masked at SCORE level inside the kernels
        # (kv_mask); manipulating key vectors instead would shift scores
        # by q·k_poison, which can be arbitrarily positive
        if self.context_parallel not in ("ring", "ulysses"):
            raise ValueError(
                f"context_parallel must be 'ring' or 'ulysses', got "
                f"{self.context_parallel!r}")
        if self.attn_impl not in ("xla", "pallas"):
            # a typo here must not silently fall through to the O(T^2)
            # dense reference path
            raise ValueError(
                f"attn_impl must be 'xla' or 'pallas', got "
                f"{self.attn_impl!r}")
        if self.mesh is not None and self.mesh.shape[self.seq_axis] > 1:
            if self.context_parallel == "ulysses":
                # pallas impl: keep the compute dtype — halves both the
                # all_to_all bytes on ICI and the kernel's HBM traffic
                # (f32 accumulation happens inside the kernel); the xla
                # impl keeps its historical f32 contract
                cast = (jnp.float32 if self.attn_impl == "xla"
                        else self.compute_dtype)
                out = ulysses_self_attention(
                    q.astype(cast), k.astype(cast), v.astype(cast),
                    self.mesh, seq_axis=self.seq_axis, causal=self.causal,
                    kv_mask=mask, impl=self.attn_impl)
            else:
                # ring streams k/v blocks ACROSS devices with the o/m/l
                # carry in the rotation itself; its inner update is not
                # swappable for the local pallas kernel
                out = ring_self_attention(
                    q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32),
                    self.mesh, seq_axis=self.seq_axis, causal=self.causal,
                    kv_mask=mask)
        elif self.attn_impl == "pallas":
            from persia_tpu.ops.flash_attention import (
                flash_attention_masked,
            )

            # keep the compute dtype: the kernel accumulates in f32
            # internally (preferred_element_type), so bf16 inputs keep
            # MXU rate + halve HBM bytes without losing the f32 math
            out = flash_attention_masked(
                q, k, v, kv_mask=mask, causal=self.causal)
        else:
            out = reference_attention(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), causal=self.causal, kv_mask=mask)
        out = out.transpose(0, 2, 1, 3).reshape(bs, t, self.num_heads * dh)
        return nn.Dense(d, dtype=dt)(out.astype(dt))


class SequenceTower(nn.Module):
    """Dense tower with attention-pooled sequence slots.

    Raw (sequence) slots go through self-attention + masked mean pooling;
    summed slots and dense features concatenate as usual; MLP head.
    """

    mlp: Sequence[int] = (256, 128)
    num_heads: int = 2
    compute_dtype: Any = jnp.bfloat16
    mesh: Optional[Any] = None
    context_parallel: str = "ring"  # "ring" | "ulysses"
    attn_impl: str = "xla"  # "xla" | "pallas" (see SequenceSelfAttention)

    @nn.compact
    def __call__(self, non_id_tensors, embedding_tensors, train: bool = False):
        dt = self.compute_dtype
        parts = [t.astype(dt) for t in non_id_tensors]
        for e in embedding_tensors:
            if isinstance(e, (tuple, list)):
                emb, index = e
                x, mask = gather_raw_embedding(emb, index)
                attended = SequenceSelfAttention(
                    num_heads=self.num_heads, compute_dtype=dt,
                    mesh=self.mesh,
                    context_parallel=self.context_parallel,
                    attn_impl=self.attn_impl,
                )(x, mask)
                denom = jnp.maximum(
                    mask.sum(axis=1, keepdims=True), 1).astype(dt)
                pooled = (attended * mask[..., None].astype(dt)).sum(axis=1)
                parts.append(pooled / denom)
            else:
                parts.append(e.astype(dt))
        x = jnp.concatenate(parts, axis=1)
        x = MLP(self.mlp, compute_dtype=dt)(x, train)
        out = nn.Dense(1, dtype=dt)(x)
        return nn.sigmoid(out.astype(jnp.float32))
