"""DLRM dense tower: bottom MLP + pairwise dot interactions + top MLP.

The canonical benchmark model for this framework's north-star metric
(BASELINE.md: Criteo DLRM samples/sec/chip). Interaction is the standard
lower-triangle pairwise dot of field embeddings + the bottom-MLP output,
computed as one batched matmul so it lands on the MXU.
"""

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import MLP, stack_field_embeddings


class DLRM(nn.Module):
    embedding_dim: int = 16
    bottom_mlp: Sequence[int] = (64, 32)
    top_mlp: Sequence[int] = (256, 128)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors: Sequence[jnp.ndarray],
                 embedding_tensors: Sequence[Any], train: bool = False):
        dt = self.compute_dtype
        dense_x = non_id_tensors[0].astype(dt)
        bottom = MLP((*self.bottom_mlp, self.embedding_dim),
                     compute_dtype=dt)(dense_x, train)

        fields = stack_field_embeddings(embedding_tensors).astype(dt)
        # (bs, F+1, d): dense projection joins the interaction
        t = jnp.concatenate([bottom[:, None, :], fields], axis=1)
        # pairwise dots on the MXU: (bs, F+1, F+1)
        dots = jnp.einsum("bfd,bgd->bfg", t, t)
        f = t.shape[1]
        iu, ju = jnp.triu_indices(f, k=1)
        interactions = dots[:, iu, ju]

        top_in = jnp.concatenate([bottom, interactions.astype(dt)], axis=1)
        out = MLP((*self.top_mlp, 1), final_activation=False,
                  compute_dtype=dt)(top_in, train)
        return nn.sigmoid(out.astype(jnp.float32))
