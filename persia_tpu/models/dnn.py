"""Adult-income style DNN tower (reference: examples/src/adult-income/model.py).

Same topology as the reference example — a dense-feature MLP+BN branch, a
sparse-embedding MLP+BN branch, three linear layers, sigmoid output — so
the e2e example and its AUC check carry over.
"""

from typing import Any, List, Sequence

import jax.numpy as jnp
from flax import linen as nn

from persia_tpu.models.common import flatten_embeddings


class DNN(nn.Module):
    dense_mlp_output_size: int = 16
    sparse_mlp_output_size: int = 128
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, non_id_tensors: Sequence[jnp.ndarray],
                 embedding_tensors: Sequence[Any], train: bool = False):
        dt = self.compute_dtype
        dense_x = non_id_tensors[0].astype(dt)
        sparse_concat = flatten_embeddings(embedding_tensors).astype(dt)

        sparse = nn.Dense(self.sparse_mlp_output_size, dtype=dt)(sparse_concat)
        sparse = nn.BatchNorm(use_running_average=not train,
                              dtype=jnp.float32)(sparse.astype(jnp.float32))

        dense_x = nn.Dense(self.dense_mlp_output_size, dtype=dt)(dense_x)
        dense_x = nn.BatchNorm(use_running_average=not train,
                               dtype=jnp.float32)(dense_x.astype(jnp.float32))

        x = jnp.concatenate([sparse, dense_x], axis=1).astype(dt)
        x = nn.Dense(256, dtype=dt)(x)
        x = nn.Dense(128, dtype=dt)(x)
        x = nn.Dense(1, dtype=dt)(x)
        return nn.sigmoid(x.astype(jnp.float32))
