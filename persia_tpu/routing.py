"""Versioned slot-based sign routing: the elastic replacement for the
launch-frozen ``farmhash64(sign) % replica_size``.

Every shard-routing decision in the stack — worker lookup/update
fan-out, serving miss fetches, checkpoint resharding, incremental-
update replay — goes through one :class:`RoutingTable`: an
epoch-stamped slot→replica map over a fixed slot space

    slot(sign)    = farmhash64(sign) % num_slots
    replica(sign) = replica_of_slot[slot(sign)]

A table born **uniform** picks ``num_slots = num_replicas *
slots_per_replica``, so ``slot % num_replicas`` reproduces the legacy
``hash % R`` routing bit-exactly — the wire, the per-replica request
counts, and the checkpoint shard layout of a fleet that never reshards
are untouched (pinned by tests/test_routing.py). Resharding keeps the
slot space FIXED and only reassigns slots: the slot is the migration
unit, so a live 2→4→3 scale dance moves whole slots between replicas
without ever re-keying a sign.

Concurrency contract: a ``RoutingTable`` is immutable after
construction. Holders of a table (the worker, the serving tier, the
reshard controller) swap the *reference* atomically under their own
lock and keep the predecessor for the **double-read window** — in-
flight work routed by epoch N stays valid against the donor replicas
until the migration's drain completes, because donors retain moved
rows (read-only) until :meth:`reshard.ReshardController.finalize`.

Epochs are strictly monotonic: ``derive()`` stamps ``epoch + 1``, and
every ``apply_routing`` implementation in the tree refuses a table
whose epoch does not advance — a delayed duplicate publish can never
roll routing back.
"""

import json
import threading
import time as _time
from typing import Dict, List, Optional, Sequence

import numpy as np

from persia_tpu.hashing import farmhash64_np
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

TABLE_VERSION = 1

# coordinator KV key the control plane publishes tables under; workers
# and serving replicas poll/watch it (reshard.py writes it at cutover)
COORDINATOR_KEY = "routing_table"


class RoutingStaleError(RuntimeError):
    """A replica refused a write because the signs' slots moved away
    under a newer routing epoch (the reshard freeze/cutover window).
    Retryable — after the caller observes a table with ``epoch >=
    min_epoch`` and re-splits the work. Carried over RPC as a plain
    RpcError whose message starts with :data:`STALE_PREFIX`;
    :func:`is_routing_stale` recognizes both forms."""

    def __init__(self, min_epoch: int, msg: str = ""):
        super().__init__(msg or f"{STALE_PREFIX}{min_epoch}")
        self.min_epoch = int(min_epoch)


STALE_PREFIX = "routing_stale:min_epoch="


def is_routing_stale(exc: BaseException) -> Optional[int]:
    """The minimum epoch a stale-routing failure demands, else None.
    Works on a local :class:`RoutingStaleError` and on its RPC-
    flattened form (any exception whose message carries the prefix)."""
    if isinstance(exc, RoutingStaleError):
        return exc.min_epoch
    msg = str(exc)
    at = msg.find(STALE_PREFIX)
    if at < 0:
        return None
    tail = msg[at + len(STALE_PREFIX):]
    digits = ""
    for ch in tail:
        if not ch.isdigit():
            break
        digits += ch
    return int(digits) if digits else None


class RoutingTable:
    """Immutable epoch-stamped slot→replica assignment (see module
    docstring for the routing function and the uniform-birth rule)."""

    __slots__ = ("epoch", "num_slots", "num_replicas", "replica_of_slot",
                 "weights", "_uniform")

    def __init__(self, epoch: int, replica_of_slot: np.ndarray,
                 num_replicas: int,
                 weights: Optional[np.ndarray] = None):
        self.epoch = int(epoch)
        a = np.ascontiguousarray(replica_of_slot, dtype=np.int32)
        a.setflags(write=False)
        self.replica_of_slot = a
        self.num_slots = len(a)
        self.num_replicas = int(num_replicas)
        if self.num_slots <= 0:
            raise ValueError("routing table needs at least one slot")
        if self.num_replicas <= 0:
            raise ValueError("routing table needs at least one replica")
        if len(a) and (a.min() < 0 or a.max() >= self.num_replicas):
            raise ValueError(
                f"slot assignment references replica outside "
                f"[0, {self.num_replicas})")
        if weights is not None:
            weights = np.ascontiguousarray(weights, dtype=np.float64)
            if len(weights) != self.num_slots:
                raise ValueError("per-slot weights length != num_slots")
            weights.setflags(write=False)
        self.weights = weights
        # cached: does this table route EXACTLY like hash % R? That is
        # the capability gate for the native shard_order fast path and
        # the byte-identical-wire guarantee.
        self._uniform = bool(
            self.num_slots % self.num_replicas == 0
            and np.array_equal(
                a, np.arange(self.num_slots, dtype=np.int32)
                % np.int32(self.num_replicas)))

    # --- construction ----------------------------------------------------

    @classmethod
    def uniform(cls, num_replicas: int,
                slots_per_replica: Optional[int] = None,
                epoch: int = 1) -> "RoutingTable":
        """The launch-default table: ``R * slots_per_replica`` slots,
        slot s → s % R — bit-exact ``farmhash % R`` routing."""
        from persia_tpu import knobs

        spr = int(slots_per_replica if slots_per_replica is not None
                  else knobs.get("PERSIA_ROUTING_SLOTS_PER_REPLICA"))
        if spr <= 0:
            raise ValueError("slots_per_replica must be positive")
        n = num_replicas * spr
        return cls(epoch,
                   np.arange(n, dtype=np.int32) % np.int32(num_replicas),
                   num_replicas)

    def derive(self, replica_of_slot: Sequence[int], num_replicas: int,
               weights: Optional[np.ndarray] = None) -> "RoutingTable":
        """Successor table over the SAME slot space at ``epoch + 1``
        (the reshard cutover constructor)."""
        a = np.ascontiguousarray(replica_of_slot, dtype=np.int32)
        if len(a) != self.num_slots:
            raise ValueError(
                f"derived table changes the slot space "
                f"({self.num_slots} -> {len(a)}); slots are the "
                f"migration unit and must be preserved")
        return RoutingTable(self.epoch + 1, a, num_replicas,
                            weights=weights)

    # --- routing ---------------------------------------------------------

    @property
    def is_uniform_modulo(self) -> bool:
        """True when this table routes exactly like ``hash % R`` — the
        native ``mw_native.shard_order`` kernel (which hard-codes the
        modulo) may serve it, and the wire is byte-identical to the
        pre-routing stack."""
        return self._uniform

    def slot_of(self, signs: np.ndarray) -> np.ndarray:
        """Slot index per sign: farmhash64(sign) % num_slots."""
        signs = np.ascontiguousarray(signs, dtype=np.uint64)
        return (farmhash64_np(signs)
                % np.uint64(self.num_slots)).astype(np.int64)

    def replica_of(self, signs: np.ndarray) -> np.ndarray:
        """Owning replica per sign (int64, shaped like ``signs``)."""
        return self.replica_of_slot[self.slot_of(signs)].astype(np.int64)

    def slots_of_replica(self, replica: int) -> np.ndarray:
        """The slots a replica currently owns (ascending)."""
        return np.nonzero(self.replica_of_slot
                          == np.int32(replica))[0].astype(np.int64)

    def moves_to(self, other: "RoutingTable") -> List[Dict]:
        """The migration plan from this table to ``other``: one
        ``{"donor", "target", "slots"}`` entry per (donor, target)
        pair with at least one reassigned slot."""
        if other.num_slots != self.num_slots:
            raise ValueError("tables span different slot spaces")
        moved = np.nonzero(self.replica_of_slot
                           != other.replica_of_slot)[0]
        pairs: Dict[tuple, List[int]] = {}
        for s in moved.tolist():
            key = (int(self.replica_of_slot[s]),
                   int(other.replica_of_slot[s]))
            pairs.setdefault(key, []).append(int(s))
        return [{"donor": d, "target": t, "slots": slots}
                for (d, t), slots in sorted(pairs.items())]

    # --- serialization ---------------------------------------------------

    def to_doc(self) -> Dict:
        doc = {
            "v": TABLE_VERSION,
            "epoch": self.epoch,
            "num_slots": self.num_slots,
            "num_replicas": self.num_replicas,
            "replica_of_slot": self.replica_of_slot.tolist(),
        }
        if self.weights is not None:
            doc["weights"] = [round(float(w), 9) for w in self.weights]
        return doc

    def to_bytes(self) -> bytes:
        """Canonical wire form (sorted keys, no whitespace drift) —
        what the coordinator KV stores and epochs are compared over."""
        return json.dumps(self.to_doc(), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_doc(cls, doc: Dict) -> "RoutingTable":
        if int(doc.get("v", 0)) != TABLE_VERSION:
            raise ValueError(
                f"unsupported routing table version {doc.get('v')!r}")
        weights = doc.get("weights")
        return cls(doc["epoch"],
                   np.asarray(doc["replica_of_slot"], dtype=np.int32),
                   doc["num_replicas"],
                   weights=(np.asarray(weights, dtype=np.float64)
                            if weights is not None else None))

    @classmethod
    def from_bytes(cls, raw: bytes) -> "RoutingTable":
        return cls.from_doc(json.loads(raw.decode("utf-8")))

    def __repr__(self):
        kind = "uniform" if self._uniform else "custom"
        return (f"RoutingTable(epoch={self.epoch}, slots={self.num_slots},"
                f" replicas={self.num_replicas}, {kind})")

    def __eq__(self, other):
        return (isinstance(other, RoutingTable)
                and self.epoch == other.epoch
                and self.num_replicas == other.num_replicas
                and np.array_equal(self.replica_of_slot,
                                   other.replica_of_slot))

    def __hash__(self):  # tables are value objects; keep dict-usable
        return hash((self.epoch, self.num_slots, self.num_replicas))


class RoutingHolder:
    """Atomic-swap cell for the current table plus the double-read
    predecessor. All mutation is epoch-checked; readers take a plain
    reference (tables are immutable, so a reader is always internally
    consistent even mid-swap)."""

    def __init__(self, table: RoutingTable):
        self._lock = threading.Lock()
        self._table = table
        self._prev: Optional[RoutingTable] = None
        self._prev_expiry = 0.0

    @property
    def table(self) -> RoutingTable:
        return self._table  # atomic reference read

    @property
    def prev(self) -> Optional[RoutingTable]:
        """The pre-swap table while the double-read window is open
        (None once drained). The window self-expires after twice the
        configured drain interval: pull-side consumers (coordinator-KV
        fetchers) are not in any controller's finalize list, and
        without the expiry they would double-read moved-and-absent
        rows against the donors forever."""
        prev = self._prev
        if prev is not None and _time.monotonic() >= self._prev_expiry:
            self.close_window()
            return None
        return prev

    @property
    def epoch(self) -> int:
        return self._table.epoch

    def apply(self, table: RoutingTable) -> bool:
        """Install a newer table; returns False (no-op) when the epoch
        does not advance — duplicate publishes and reordered deliveries
        are harmless."""
        from persia_tpu import knobs

        with self._lock:
            if table.epoch <= self._table.epoch:
                return False
            self._prev = self._table
            self._prev_expiry = _time.monotonic() + 2.0 * float(
                knobs.get("PERSIA_RESHARD_DRAIN_SEC"))
            self._table = table
            return True

    def window(self):
        """``(table, prev)`` read atomically under the holder lock — a
        concurrent :meth:`apply` swap can never hand out a torn pair
        (e.g. the OLD table paired with itself as predecessor, which
        would make an ownership filter reject the new owner's rows).
        Same self-expiry rule as :attr:`prev`."""
        with self._lock:
            prev = self._prev
            if prev is not None and _time.monotonic() >= self._prev_expiry:
                self._prev = None
                prev = None
            return self._table, prev

    def close_window(self):
        """Drop the double-read predecessor (migration drain done)."""
        with self._lock:
            self._prev = None


def publish_to_coordinator(coordinator_client, table: RoutingTable):
    """Publish a table through the coordinator KV (the control-plane
    distribution path for multi-process fleets). Epoch-guarded: a
    stale publisher (a resumed controller whose journal a newer
    migration already superseded) must never roll the fleet's
    bootstrap table back — pull-side consumers would route writes to
    non-owners."""
    raw = coordinator_client.kv_get(COORDINATOR_KEY)
    if raw:
        current = RoutingTable.from_bytes(raw)
        if current.epoch >= table.epoch:
            if current.epoch > table.epoch:
                _logger.warning(
                    "refusing to publish routing epoch %d over the "
                    "coordinator's newer epoch %d", table.epoch,
                    current.epoch)
            return
    coordinator_client.kv_put(COORDINATOR_KEY, table.to_bytes())


def fetch_from_coordinator(coordinator_client) -> Optional[RoutingTable]:
    raw = coordinator_client.kv_get(COORDINATOR_KEY)
    return RoutingTable.from_bytes(raw) if raw else None
