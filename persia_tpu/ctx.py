"""User-facing contexts: the core API (reference: persia/ctx.py).

- :class:`BaseCtx` — enter/exit + ``current_ctx()`` registry
  (ctx.py:202-271)
- :class:`DataCtx` — data-loader role, ``send_data`` into the dataflow
  (ctx.py:274-342)
- :class:`EmbeddingCtx` — embedding lookup, feature preparation, dump/load
  (ctx.py:345-652)
- :class:`TrainCtx` — adds the dense optimizer and the full hybrid train
  step (ctx.py:655-1064). In JAX the reference's forward/backward pair
  collapses into one compiled step whose outputs include the embedding
  gradients; ``train_step`` then routes them to the parameter servers —
  the sparse update stays asynchronous with respect to the next batch's
  lookup when driven through the DataLoader pipeline.
- :class:`InferCtx` — direct lookup + eval-mode forward (ctx.py:1077-1133)

The embedding tier is reached through an :class:`EmbeddingWorker`; in
local (in-process) mode its PS clients are EmbeddingHolders, in service
mode they are RPC clients — the ctx code is identical.
"""

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from persia_tpu.config import EmbeddingSchema, GlobalConfig
from persia_tpu.data.batch import PersiaBatch
from persia_tpu.embedding import EmbeddingConfig, get_default_embedding_config
from persia_tpu.logger import get_default_logger
from persia_tpu.worker.middleware import RawEmbedding, SumEmbedding
from persia_tpu.worker.worker import EmbeddingWorker

_logger = get_default_logger(__name__)

_ctx_lock = threading.Lock()
_ctx_stack: List["BaseCtx"] = []


def current_ctx() -> Optional["BaseCtx"]:
    return _ctx_stack[-1] if _ctx_stack else None


class BaseCtx:
    """Contexts nest (an eval_ctx may open inside a TrainCtx with-block,
    mirroring the reference's usage in examples/src/adult-income/train.py);
    ``current_ctx`` returns the innermost."""

    def __enter__(self):
        with _ctx_lock:
            _ctx_stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        with _ctx_lock:
            if self in _ctx_stack:
                _ctx_stack.remove(self)
        return False


class DataCtx(BaseCtx):
    """Data-loader role: push batches toward the embedding workers and
    trainers (reference ctx.py:274-342).

    In service mode ``dataflow`` is a persia_tpu.service dataflow client;
    in local mode batches go straight to a local EmbeddingWorker.
    """

    def __init__(self, dataflow=None):
        self.dataflow = dataflow
        self._next_batch_id = 0

    def send_data(self, batch: PersiaBatch):
        if self.dataflow is None:
            raise RuntimeError("DataCtx requires a dataflow client")
        if batch.batch_id is None:
            batch.batch_id = self._next_batch_id
        self._next_batch_id = batch.batch_id + 1
        self.dataflow.send(batch)


class EmbeddingCtx(BaseCtx):
    def __init__(
        self,
        model=None,
        schema: Optional[EmbeddingSchema] = None,
        worker: Optional[EmbeddingWorker] = None,
        embedding_config: Optional[EmbeddingConfig] = None,
        global_config: Optional[GlobalConfig] = None,
    ):
        self.model = model
        self.schema = schema if schema is not None else (
            worker.schema if worker is not None else None
        )
        self.worker = worker
        self.embedding_config = embedding_config or get_default_embedding_config()
        self.global_config = global_config or GlobalConfig()
        self._configured_servers = False

    def __enter__(self):
        super().__enter__()
        if self.worker is not None and not self._configured_servers:
            self.configure_embedding_parameter_servers()
        return self

    def configure_embedding_parameter_servers(self):
        """Broadcast hyperparameters to every PS
        (reference: lib.rs:307-318 -> mod.rs:429-451)."""
        ec = self.embedding_config
        init = self.schema.initialization if self.schema else None
        if init is not None and init.method.value != "bounded_uniform":
            method, params = init.method.value, init.to_params()
        else:
            lower, upper = ec.emb_initialization
            method, params = "bounded_uniform", {"lower": lower, "upper": upper}
        self.worker.configure_parameter_servers(
            method, params, ec.admit_probability, ec.weight_bound,
            enable_weight_bound=True,
        )
        self._configured_servers = True

    def register_optimizer(self, optimizer):
        """Called by embedding Optimizer.apply()."""
        self.worker.register_optimizer(optimizer.config)

    # --- feature preparation -------------------------------------------

    def prepare_features(
        self, batch: PersiaBatch, lookup: Dict[str, Any]
    ) -> Tuple[List[jnp.ndarray], List[Any], List[jnp.ndarray]]:
        """Worker lookup results -> device-ready model inputs
        (reference: _prepare_feature, ctx.py:75-199)."""
        non_id = [jnp.asarray(f.data) for f in batch.non_id_type_features]
        labels = [jnp.asarray(l.data) for l in batch.labels]
        emb_inputs: List[Any] = []
        for f in batch.id_type_features:
            r = lookup[f.name]
            if isinstance(r, SumEmbedding):
                emb_inputs.append(jnp.asarray(r.embeddings))
            elif isinstance(r, RawEmbedding):
                emb_inputs.append(
                    (jnp.asarray(r.embeddings), jnp.asarray(r.index))
                )
            else:
                raise TypeError(f"unexpected lookup result {type(r)}")
        return non_id, emb_inputs, labels

    def forward(self, batch: PersiaBatch):
        """Eval/infer forward: direct lookup + model apply
        (reference: forward_directly path, ctx.py:433-469)."""
        lookup = self.worker.lookup_direct(batch.id_type_features,
                                           training=False)
        return self.forward_prepared(batch, lookup)

    def forward_prepared(self, batch: PersiaBatch, lookup: Dict[str, Any]):
        """Forward from an ALREADY-performed lookup — the serving tier's
        entry point: its hot-row cache resolves the embeddings itself
        (serving.py `_lookup_cached`) and only needs the feature
        preparation + jitted eval apply from the ctx."""
        non_id, emb_inputs, labels = self.prepare_features(batch, lookup)
        pred = self._apply_model(non_id, emb_inputs)
        return pred, labels

    def _apply_model(self, non_id, emb_inputs):
        raise NotImplementedError

    # --- checkpointing ---------------------------------------------------

    def dump_checkpoint(self, dst_dir: str, with_dense: bool = True):
        from persia_tpu import checkpoint as ckpt

        ckpt.dump_checkpoint(self, dst_dir, with_dense=with_dense)

    def load_checkpoint(self, src_dir: str, with_dense: bool = True):
        from persia_tpu import checkpoint as ckpt

        ckpt.load_checkpoint(self, src_dir, with_dense=with_dense)


class TrainCtx(EmbeddingCtx):
    """Training context: hybrid sync-dense / async-sparse step.

    Args mirror the reference TrainCtx (ctx.py:655-852) where they still
    make sense on TPU; DDP options collapse into an optional mesh.
    """

    def __init__(
        self,
        model,
        dense_optimizer: optax.GradientTransformation,
        embedding_optimizer,
        schema: EmbeddingSchema,
        worker: EmbeddingWorker,
        embedding_config: Optional[EmbeddingConfig] = None,
        global_config: Optional[GlobalConfig] = None,
        mesh=None,
        loss_fn=None,
        grad_update_interval: int = 1,
        seed: int = 0,
        grad_reduce_dtype: Optional[str] = None,
        device_cache_capacity: int = 0,
        device_cache_admission: Optional[str] = None,
        profiler=None,
        resume_from: Optional[str] = None,
    ):
        super().__init__(model=model, schema=schema, worker=worker,
                         embedding_config=embedding_config,
                         global_config=global_config)
        from persia_tpu.parallel.train import bce_loss

        self.dense_optimizer = dense_optimizer
        self.embedding_optimizer = embedding_optimizer
        self.mesh = mesh
        self.loss_fn = loss_fn or bce_loss
        self.grad_update_interval = grad_update_interval
        self.seed = seed
        # "bf16" halves dense all-reduce bytes over ICI (the Bagua
        # low-precision-algorithm analogue); None = full f32 reduction
        self.grad_reduce_dtype = grad_reduce_dtype
        self.state = None
        self._train_step = None
        self._eval_step = None
        self._emb_shapes = None
        self._ddp = False
        # error-feedback residuals for grad_reduce_dtype="int8_ef"
        # (per-replica, data-axis-sharded; see parallel/train.py)
        self._ef_state = None
        # device-resident hot-row cache (TPU-first, beyond the reference:
        # hits never cross the host<->device wire; see
        # persia_tpu/parallel/cached_engine.py for the consistency model).
        # admission: None -> the PERSIA_TIER_ADMIT knob; "hotness"
        # selects the frequency-gated tier-ladder mapper
        self.device_cache_capacity = int(device_cache_capacity)
        self.device_cache_admission = device_cache_admission
        self._cache_engine = None
        self._cached_step = None
        self._cache_multi_id = False
        # opt-in device profiler window (tracing.StepProfiler): a
        # jax.profiler trace capture keyed to a step range, so the TPU
        # timeline aligns with the host spans of exactly those steps.
        # Defaults from PERSIA_PROFILE_DIR/_START_STEP/_NUM_STEPS.
        from persia_tpu import tracing as _tracing

        self.profiler = (profiler if profiler is not None
                         else _tracing.profiler_from_env())
        self._step_count = 0
        # --- whole-job resume (persia_tpu/snapshot.py) -----------------
        # `resume_from` names one snapshot directory or a snapshot_dir
        # parent (newest complete wins). Resolution + verification
        # happen HERE so a torn/absent snapshot fails at construction,
        # not mid-__enter__; the sparse rollback runs on __enter__ and
        # the dense bytes install lazily once the TrainState exists.
        self.resume_manifest: Optional[dict] = None
        self.resume_cursor: Optional[dict] = None
        self._resume_snap: Optional[str] = None
        self._pending_dense: Optional[bytes] = None
        if resume_from:
            from persia_tpu import snapshot as _snapshot

            self._resume_snap, self.resume_manifest = (
                _snapshot.resolve_snapshot(resume_from))
            self.resume_cursor = _snapshot.load_cursor(self._resume_snap)

    def __enter__(self):
        super().__enter__()
        if self.embedding_optimizer is not None:
            self.embedding_optimizer.apply()
        if self._cache_engine is not None:
            self._cache_engine.ensure_open()  # re-entry after __exit__
        if self._resume_snap is not None:
            self._restore_from_snapshot()
        return self

    def _restore_from_snapshot(self):
        """Roll the job back to the resolved snapshot: PS stores wiped
        to the snapshot's consistent cut (post-snapshot updates are
        re-derived by replaying the deterministic batch stream from
        ``resume_cursor``), dense bytes staged for lazy install, step
        counter restored. Runs once; re-entering the ctx later must
        not re-wipe live training progress."""
        from persia_tpu import snapshot as _snapshot

        snap, self._resume_snap = self._resume_snap, None
        if self._cache_engine is not None:
            self._cache_engine.invalidate()  # cached rows predate restore
        self.worker.load(snap)
        self._pending_dense = _snapshot.dense_bytes(snap)
        self._step_count = int(self.resume_manifest.get("step", 0))

    def snapshot(self, snapshot_dir: str, cursor: Optional[dict] = None,
                 inc_dir: Optional[str] = None,
                 keep: Optional[int] = None) -> str:
        """Take one coordinated job snapshot (persia_tpu/snapshot.py):
        device cache flushed, backward pipeline drained, then sparse +
        dense + cursor captured as one manifest-stamped unit."""
        from persia_tpu import snapshot as _snapshot

        self.flush_device_cache()
        return _snapshot.snapshot_job(
            snapshot_dir, self.worker, state=self.state, cursor=cursor,
            inc_dir=inc_dir, step=self._step_count, keep=keep)

    def _wire_dtype(self):
        return (
            jnp.bfloat16
            if self.global_config.common.embedding_wire_dtype == "bf16"
            else jnp.float32
        )

    def _use_ddp_step(self, emb_indices, batch_size: int) -> bool:
        """Mesh present + every slot summed + batch divisible by the data
        axis -> the explicit shard_map DDP step with batch-major packed
        wire. Raw slots' shared distinct tensors cannot batch-shard, and
        a partial final batch cannot split evenly — both keep the
        auto-sharded path (shard_batch_pytree's replicate fallback)."""
        if self.mesh is None or any(i is not None for i in emb_indices):
            return False
        from persia_tpu.parallel.mesh import DATA_AXIS

        return batch_size % self.mesh.shape[DATA_AXIS] == 0

    def _ensure_compiled(self, non_id, emb_inputs):
        from persia_tpu.parallel.train import (
            create_train_state,
            make_eval_step,
            make_packed_train_step,
            make_packed_train_step_ddp,
            split_embedding_inputs,
        )

        emb_values, emb_indices = split_embedding_inputs(emb_inputs)
        emb_shapes = tuple(tuple(v.shape) for v in emb_values)
        if self.state is None:
            self.state = create_train_state(
                self.model, self.dense_optimizer, jax.random.key(self.seed),
                non_id, emb_inputs,
            )
            self._eval_step = make_eval_step(self.model)
        if self._pending_dense is not None:
            # snapshot resume: install the dumped model + optimizer
            # leaves into the freshly built (template) TrainState
            from persia_tpu import checkpoint as _ckpt

            self.state = _ckpt.apply_dense_bytes(self.state,
                                                 self._pending_dense)
            self._pending_dense = None
        if self._train_step is None or emb_shapes != self._emb_shapes:
            # (re)build the packed step for this batch geometry; jit caches
            # by shape so alternating geometries stay cheap
            self._emb_shapes = emb_shapes
            reduce_dtype = {
                "bf16": jnp.bfloat16, "int8_ef": "int8_ef",
            }.get(self.grad_reduce_dtype)
            batch_size = emb_shapes[0][0] if emb_shapes else 0
            if self._use_ddp_step(emb_indices, batch_size):
                self._ddp = True
                self._slot_dims = [s[1] for s in emb_shapes]
                self._train_step = make_packed_train_step_ddp(
                    self.model, self.dense_optimizer, self._slot_dims,
                    self.mesh, loss_fn=self.loss_fn,
                    wire_dtype=self._wire_dtype(),
                    grad_reduce_dtype=reduce_dtype,
                )
                if reduce_dtype == "int8_ef" and self._ef_state is None:
                    from persia_tpu.parallel.train import init_ef_state

                    self._ef_state = init_ef_state(
                        self.state.params, self.mesh)
            else:
                self._ddp = False
                self._train_step = make_packed_train_step(
                    self.model, self.dense_optimizer, emb_shapes,
                    loss_fn=self.loss_fn, wire_dtype=self._wire_dtype(),
                )

    def _prep_train_inputs(self, batch: PersiaBatch,
                           lookup: Dict[str, Any]) -> tuple:
        """Lookup results -> train-step inputs, uploading the embedding
        values ONLY as the single packed wire blob.

        Unlike :meth:`prepare_features` (the eval path), the per-slot
        value matrices stay numpy: the jitted train step consumes the
        packed array, so per-slot device uploads would both double the
        pinned device memory and force a device->host round trip at
        pack time. Returns (non_id, emb_inputs_host, emb_shapes,
        flat_emb, emb_indices, labels). The packed layout is batch-major
        ``(batch, sum dims)`` for the DDP shard_map step (batch axis
        shards over the mesh), flat otherwise."""
        from persia_tpu.parallel.train import (
            pack_embedding_values,
            pack_embedding_values_batch_major,
        )

        non_id = [jnp.asarray(f.data) for f in batch.non_id_type_features]
        labels = [jnp.asarray(l.data) for l in batch.labels]
        emb_np: List[np.ndarray] = []
        emb_indices: List[Any] = []
        emb_inputs: List[Any] = []  # host-side, for model init/shapes only
        for f in batch.id_type_features:
            r = lookup[f.name]
            if isinstance(r, SumEmbedding):
                emb_np.append(r.embeddings)
                emb_indices.append(None)
                emb_inputs.append(r.embeddings)
            elif isinstance(r, RawEmbedding):
                idx = jnp.asarray(r.index)
                emb_np.append(r.embeddings)
                emb_indices.append(idx)
                emb_inputs.append((r.embeddings, idx))
            else:
                raise TypeError(f"unexpected lookup result {type(r)}")
        emb_shapes = tuple(tuple(v.shape) for v in emb_np)
        if self._use_ddp_step(emb_indices, len(labels[0])):
            from persia_tpu.parallel.mesh import batch_sharding

            flat_emb = jax.device_put(
                pack_embedding_values_batch_major(emb_np,
                                                  self._wire_dtype()),
                batch_sharding(self.mesh),
            )
        else:
            flat_emb = jnp.asarray(
                pack_embedding_values(emb_np, self._wire_dtype())
            )
        return non_id, emb_inputs, emb_shapes, flat_emb, emb_indices, labels

    def stage_batch(self, batch: PersiaBatch, lookup: Dict[str, Any]):
        """Host->device staging for one looked-up batch, run by the
        forward engine's prefetch workers so the uploads overlap the
        previous batch's compute (the reference's postprocess_worker
        moves batches to the GPU off the training thread via pinned
        pools, forward.rs:572-638 + cuda/). Returns the staged tuple the
        next ``train_step`` consumes; None when staging does not apply
        (mesh placement happens on the training thread)."""
        if self.mesh is not None:
            return None
        return self._prep_train_inputs(batch, lookup)

    def train_step(self, batch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """One full hybrid step: lookup -> dense step -> sparse update.

        Accepts a raw :class:`PersiaBatch` (synchronous lookup + update)
        or a pipeline :class:`~persia_tpu.pipeline.LookedUpBatch` from a
        DataLoader, in which case the lookup already happened in a
        prefetch worker (with host->device staging done there too) and
        the gradient update is submitted to the async backward engine
        (bounded by the staleness semaphore).

        Embedding values/gradients cross the host<->device boundary as a
        single packed bf16 array in each direction (the TPU analogue of
        the reference's f16 wire, persia-common/src/lib.rs:85-113).
        Returns (loss, pred).

        Observability: each step runs under a ``trainer/train_step``
        span — joined to the batch's existing trace when it came through
        the pipeline (the prefetch worker's lookup opened the root), a
        fresh root otherwise — and drives the opt-in
        :class:`~persia_tpu.tracing.StepProfiler` window."""
        from persia_tpu import tracing
        from persia_tpu.pipeline import LookedUpBatch

        self._step_count += 1
        if self.profiler is not None:
            self.profiler.on_step(self._step_count)
        tctx = batch.trace if isinstance(batch, LookedUpBatch) else None
        kw = {"ctx": tctx} if tctx is not None else {"root": True}
        with tracing.span("trainer/train_step", step=self._step_count,
                          **kw):
            return self._train_step_inner(batch)

    def _train_step_inner(self, batch) -> Tuple[jnp.ndarray, jnp.ndarray]:
        from persia_tpu.parallel.train import unpack_embedding_grads
        from persia_tpu.pipeline import LookedUpBatch

        if self.device_cache_capacity and not (
                self._cache_engine is None and jax.process_count() > 1
                and self._negotiate_multihost_cache()):
            if isinstance(batch, LookedUpBatch):
                # DataLoader yields raw batches when the active ctx is
                # cached (dataloader.py), so a pre-looked-up batch here
                # means an engine was driven against this ctx by hand
                raise RuntimeError(
                    "device-cache ctx received a pre-looked-up batch; "
                    "the cache path does its own (cheaper) miss lookups "
                    "— feed raw PersiaBatch objects (DataLoader does "
                    "this automatically for cached ctxs)")
            return self._cached_train_step(batch)

        engine = None
        staged = None
        if isinstance(batch, LookedUpBatch):
            ref_id, lookup, engine = batch.ref_id, batch.lookup, batch.engine
            staged = batch.staged
            batch = batch.batch
        else:
            ref_id, lookup = self.worker.lookup_direct_training(
                batch.id_type_features
            )
        if staged is None:
            staged = self._prep_train_inputs(batch, lookup)
        non_id, emb_inputs, _emb_shapes, flat_emb, emb_indices, labels = staged
        self._ensure_compiled(non_id, emb_inputs)
        if self.mesh is not None:
            from persia_tpu.parallel.mesh import shard_batch_pytree

            placed = shard_batch_pytree(
                {"n": non_id, "i": emb_indices, "l": labels[0]}, self.mesh
            )
            non_id, emb_indices, label = placed["n"], placed["i"], placed["l"]
        else:
            label = labels[0]
        if self._ddp:
            if self._ef_state is not None:
                (self.state, loss, flat_grads, pred,
                 self._ef_state) = self._train_step(
                    self.state, non_id, flat_emb, label, self._ef_state)
            else:
                self.state, loss, flat_grads, pred = self._train_step(
                    self.state, non_id, flat_emb, label
                )
        else:
            self.state, loss, flat_grads, pred = self._train_step(
                self.state, non_id, flat_emb, emb_indices, label
            )
        names = [f.name for f in batch.id_type_features]
        slot_dims = self._slot_dims if self._ddp else None
        if engine is not None:
            # the device->host gradient fetch happens in a backward worker
            # thread, not here — on a slow host link a synchronous fetch
            # would serialize every step on the d2h transfer
            engine.backward.submit_packed(
                ref_id, flat_grads, self._emb_shapes, names,
                slot_dims=slot_dims)
        else:
            if self._ddp:
                from persia_tpu.parallel.train import (
                    unpack_embedding_grads_batch_major,
                )

                per_slot = unpack_embedding_grads_batch_major(
                    flat_grads, slot_dims)
            else:
                per_slot = unpack_embedding_grads(flat_grads,
                                                  self._emb_shapes)
            self.worker.update_gradients(ref_id, dict(zip(names, per_slot)))
        return loss, pred

    def _apply_model(self, non_id, emb_inputs):
        from persia_tpu.parallel.train import split_embedding_inputs

        self._ensure_compiled(non_id, emb_inputs)
        emb_values, emb_indices = split_embedding_inputs(emb_inputs)
        return self._eval_step(self.state, non_id, emb_values, emb_indices)

    # --- device-resident cache path --------------------------------------

    def _negotiate_multihost_cache(self) -> bool:
        """Multi-process mesh + device cache requested: decide between
        the historic hard error and a loud negotiate-down.

        ``PERSIA_MULTIHOST_CACHE=off`` (default) disables the cache and
        lets the run continue on the PS-only hybrid path — a pod job
        must not die on a cache knob. ``refuse`` preserves the hard
        error (we return False and :meth:`_ensure_cache` raises).
        Returns True when the cache was negotiated off."""
        from persia_tpu import knobs

        mode = str(knobs.get("PERSIA_MULTIHOST_CACHE")).lower()
        if mode == "refuse":
            return False
        if mode != "off":
            raise ValueError(
                f"PERSIA_MULTIHOST_CACHE={mode!r}: expected 'off' or "
                "'refuse'")
        _logger.warning(
            "device cache requested (capacity=%d) on a multi-process "
            "mesh (jax.process_count()=%d) — the cache's sign->slot "
            "mapper and miss/evict host transfers are single-controller "
            "state; NEGOTIATING DOWN: device cache DISABLED, continuing "
            "on the PS-only hybrid path. Set PERSIA_MULTIHOST_CACHE="
            "refuse to make this a hard error instead.",
            self.device_cache_capacity, jax.process_count())
        self.device_cache_capacity = 0
        return True

    def _ensure_cache(self, batch: PersiaBatch):
        """First-batch validation + lazy build of the cache engine and
        the fused cached step. The v2 envelope: uniform dim, SUMMED
        slots, non-shared Adagrad. Single-id slots take the pure-gather
        fast path; multi-id bags take the segment-sum step (with
        sqrt_scaling parity). A mesh is supported — the cache becomes
        one GSPMD row-sharded array (cached_train._row_sharding).
        Anything outside the envelope raises with the reason rather
        than silently degrading."""
        if self._cache_engine is not None:
            return
        if jax.process_count() > 1:
            # Single-controller constraint: the engine's sign->slot map,
            # miss imports and eviction write-backs are host-side state
            # on THIS process, while a multi-process mesh shards the
            # cache arrays across hosts — remote rows would be
            # imported/flushed by a host that cannot address them, and
            # every process would run a divergent mapper. A multi-host
            # cache needs per-process row ownership (shard the mapper by
            # jax.process_index) before this can be lifted.
            raise NotImplementedError(
                "device cache is single-controller only: "
                f"jax.process_count()={jax.process_count()} — the "
                "sign->slot mapper and miss/evict host transfers live "
                "on one process; use the uncached hybrid path (or "
                "device mode) on multi-process meshes — or leave "
                "PERSIA_MULTIHOST_CACHE=off to negotiate the cache "
                "down instead of erroring")
        from persia_tpu.embedding.optim import Adagrad as ClientAdagrad

        opt = self.embedding_optimizer
        if not isinstance(opt, ClientAdagrad) or opt.vectorwise_shared:
            raise NotImplementedError(
                "device cache mirrors non-shared Adagrad on device; "
                f"got {type(opt).__name__}")
        from persia_tpu.data.batch import IDTypeFeatureWithSingleID

        # Mode dispatch is TYPE-based, not shape-based: the SingleID
        # class guarantees one id per sample on EVERY batch, so the
        # fast pure-gather path can never meet a later variable-length
        # batch. Base IDTypeFeature streams (even if the first batch
        # happens to look single-id) take the general bag path — a
        # first-batch shape probe would lock in the wrong step.
        multi_id = not all(
            isinstance(f, IDTypeFeatureWithSingleID)
            for f in batch.id_type_features)
        dims = set()
        for f in batch.id_type_features:
            slot = self.schema.get_slot(f.name)
            # both cached steps feed the model per-slot (B, D) pooled
            # values; a raw (non-summed) slot expects the padded
            # distinct + index representation and would be silently
            # sum-pooled — reject regardless of observed bag shape
            if not slot.embedding_summation:
                raise NotImplementedError(
                    "device cache needs summed (pooled) slots; "
                    f"{f.name} is a raw slot")
            if slot.pooling != "sum":
                # the fused cached step segment-SUMS bags on device;
                # running a mean/last-k slot through it would silently
                # change the pooling semantics
                raise NotImplementedError(
                    "device cache supports pooling='sum' slots only; "
                    f"{f.name} uses pooling={slot.pooling!r} (worker-"
                    "tier pooling) — use the uncached hybrid path")
            dims.add(slot.dim)
        if len(dims) != 1:
            raise NotImplementedError(
                f"device cache needs one uniform slot dim, got {dims}")
        dim = dims.pop()
        num_slots = len(batch.id_type_features)
        from persia_tpu.parallel.cached_engine import DeviceCacheEngine
        from persia_tpu.parallel.cached_train import (
            make_cached_bag_train_step,
            make_cached_train_step,
        )

        self._cache_engine = DeviceCacheEngine(
            self.worker, self.device_cache_capacity, num_slots, dim,
            acc_init=opt.initial_accumulator_value, mesh=self.mesh,
            sqrt_scaling=[
                self.schema.get_slot(f.name).sqrt_scaling
                for f in batch.id_type_features],
            admission=self.device_cache_admission)
        self._cache_multi_id = multi_id
        maker = make_cached_bag_train_step if multi_id \
            else make_cached_train_step
        self._cached_step = maker(
            self.model, self.dense_optimizer, num_slots, dim,
            lr=opt.lr, eps=opt.eps,
            g_square_momentum=opt.g_square_momentum,
            loss_fn=self.loss_fn,
            weight_bound=self.embedding_config.weight_bound,
            capacity=self.device_cache_capacity, mesh=self.mesh)
        if self.state is None:
            from persia_tpu.parallel.train import create_train_state

            batch_size = len(batch.labels[0].data)
            non_id = [jnp.asarray(f.data)
                      for f in batch.non_id_type_features]
            dummy_emb = [np.zeros((batch_size, dim), np.float32)
                         for _ in range(num_slots)]
            self.state = create_train_state(
                self.model, self.dense_optimizer,
                jax.random.key(self.seed), non_id, dummy_emb)
            from persia_tpu.parallel.train import make_eval_step

            self._eval_step = make_eval_step(self.model)
        if self._pending_dense is not None:
            from persia_tpu import checkpoint as _ckpt

            self.state = _ckpt.apply_dense_bytes(self.state,
                                                 self._pending_dense)
            self._pending_dense = None

    def _cached_train_step(self, batch: PersiaBatch):
        self._ensure_cache(batch)
        eng = self._cache_engine
        non_id = [jnp.asarray(f.data) for f in batch.non_id_type_features]
        label = jnp.asarray(batch.labels[0].data)
        if self._cache_multi_id:
            (flat_slot_idx, seg, scale, cold_idx, cold_vals, cold_acc,
             evicted, evicted_mask, inverse,
             unique_slots) = eng.prepare_bags(batch.id_type_features)
            (self.state, eng.cache_vals, eng.cache_acc, loss, pred,
             ev_vals, ev_acc) = self._cached_step(
                self.state, eng.cache_vals, eng.cache_acc, non_id,
                jnp.asarray(flat_slot_idx), jnp.asarray(seg),
                jnp.asarray(scale), jnp.asarray(cold_idx),
                jnp.asarray(cold_vals), jnp.asarray(cold_acc),
                jnp.asarray(inverse), jnp.asarray(unique_slots), label)
        else:
            (slot_idx, cold_idx, cold_vals, cold_acc, evicted,
             evicted_mask, inverse,
             unique_slots) = eng.prepare(batch.id_type_features)
            (self.state, eng.cache_vals, eng.cache_acc, loss, pred,
             ev_vals, ev_acc) = self._cached_step(
                self.state, eng.cache_vals, eng.cache_acc, non_id,
                jnp.asarray(slot_idx), jnp.asarray(cold_idx),
                jnp.asarray(cold_vals), jnp.asarray(cold_acc),
                jnp.asarray(inverse), jnp.asarray(unique_slots), label)
        eng.finish(evicted, evicted_mask, ev_vals, ev_acc)
        return loss, pred

    def flush_device_cache(self) -> int:
        """Write every cached row back to the PS (eval/checkpoint entry
        points call this; the cache stays valid for more training)."""
        if self._cache_engine is None:
            return 0
        return self._cache_engine.flush_all()

    def __exit__(self, exc_type, exc_val, exc_tb):
        # leaving the ctx must leave the PS authoritative (a later
        # InferCtx / dump / second TrainCtx reads it) and must not leak
        # the flush thread; super().__exit__ must run even when the
        # flush raises, or the dead ctx stays on the _ctx_stack and
        # current_ctx() keeps returning it
        if self.profiler is not None:
            self.profiler.close()  # stop an open device-trace capture
        try:
            if self._cache_engine is not None:
                try:
                    if exc_type is None:
                        self.flush_device_cache()
                finally:
                    self._cache_engine.close()
        finally:
            result = super().__exit__(exc_type, exc_val, exc_tb)
        return result

    def dump_checkpoint(self, dst_dir: str, with_dense: bool = True):
        self.flush_device_cache()
        super().dump_checkpoint(dst_dir, with_dense=with_dense)

    def load_checkpoint(self, src_dir: str, with_dense: bool = True):
        # invalidate (NOT flush) first: cached rows predate the restore;
        # flushing them — or serving further hits from them — would
        # clobber the loaded values
        if self._cache_engine is not None:
            self._cache_engine.invalidate()
        super().load_checkpoint(src_dir, with_dense=with_dense)


class InferCtx(EmbeddingCtx):
    """Inference: fixed worker addresses, eval-mode lookups
    (reference ctx.py:1077-1133).

    The eval step is built once and jit-caches per input geometry, so
    the number of XLA compiles equals the number of distinct batch-row
    shapes the server feeds it. ``eval_batch_rows_seen`` records those
    shapes — the serving tier's shape-bucketing exists exactly to keep
    this set equal to its bucket ladder instead of one entry per
    coalesced request count (see serving.py)."""

    def __init__(self, model, state, schema, worker, **kw):
        super().__init__(model=model, schema=schema, worker=worker, **kw)
        self.state = state
        self._eval_step = None
        self.eval_batch_rows_seen: set = set()

    def _apply_model(self, non_id, emb_inputs):
        from persia_tpu.parallel.train import (
            make_eval_step,
            split_embedding_inputs,
        )

        if self._eval_step is None:
            self._eval_step = make_eval_step(self.model)
        emb_values, emb_indices = split_embedding_inputs(emb_inputs)
        rows = None
        if non_id:
            rows = int(non_id[0].shape[0])
        else:
            # embedding-only model: summed slots are (batch, dim); raw
            # slots carry batch rows in their (batch, sfs) index tensor
            for v, idx in zip(emb_values, emb_indices):
                rows = int(v.shape[0] if idx is None else idx.shape[0])
                break
        if rows is not None:
            if rows not in self.eval_batch_rows_seen:
                # replace-on-write, not .add(): a concurrent stats
                # reader iterating the old set must never see it mutate
                # mid-iteration (serving's stats RPC runs on another
                # thread); a lost concurrent insert re-adds on the next
                # call with the same shape
                self.eval_batch_rows_seen = (
                    self.eval_batch_rows_seen | {rows})
        return self._eval_step(self.state, non_id, emb_values, emb_indices)


class _EvalCtx(EmbeddingCtx):
    def __init__(self, parent: TrainCtx):
        super().__init__(model=parent.model, schema=parent.schema,
                         worker=parent.worker,
                         embedding_config=parent.embedding_config)
        self._parent = parent
        self._configured_servers = True  # already configured by parent
        # cached rows train on device; make the PS authoritative before
        # eval lookups read it
        parent.flush_device_cache()

    def _apply_model(self, non_id, emb_inputs):
        return self._parent._apply_model(non_id, emb_inputs)


def eval_ctx(train_ctx: Optional[TrainCtx] = None) -> _EvalCtx:
    """Evaluation context over a trained TrainCtx (reference ctx.py:1072).

    Must be entered after exiting (or outside) the TrainCtx with-block.
    """
    ctx = train_ctx or current_ctx()
    if not isinstance(ctx, TrainCtx):
        raise RuntimeError("eval_ctx requires a TrainCtx")
    return _EvalCtx(ctx)
