"""Small shared helpers (reference: persia/utils.py)."""

import os
import random
import socket
import subprocess
from typing import Any, List, Optional

import numpy as np
import yaml


def setup_seed(seed: int):
    """Deterministic seeding across python/numpy (reference: utils.py:13-32).

    JAX PRNG keys are explicit (functional), so unlike the torch reference
    there is no global framework RNG to pin — training code derives all
    device randomness from ``jax.random.key(seed)``.
    """
    random.seed(seed)
    np.random.seed(seed)
    os.environ["PYTHONHASHSEED"] = str(seed)


def load_yaml(path: str) -> Any:
    if not os.path.exists(path):
        raise FileNotFoundError(f"yaml file not found: {path}")
    with open(path, "r") as f:
        return yaml.safe_load(f)


def dump_yaml(content: Any, path: str):
    with open(path, "w") as f:
        yaml.safe_dump(content, f)


def run_command(cmd: List[str], env: Optional[dict] = None) -> subprocess.Popen:
    full_env = dict(os.environ)
    if env:
        full_env.update({k: str(v) for k, v in env.items()})
    return subprocess.Popen(cmd, env=full_env)


def find_free_port(start: int = 10000, end: int = 65535) -> int:
    """Pick a currently-free TCP port (reference: utils.py:83-91)."""
    for _ in range(128):
        port = random.randint(start, end)
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            try:
                s.bind(("127.0.0.1", port))
                return port
            except OSError:
                continue
    raise RuntimeError("could not find a free port")


def resolve_binary_path(name: str) -> str:
    """Locate a native service binary shipped inside the package.

    Native binaries are built into ``persia_tpu/native_bin/`` by the
    Makefile (reference resolves rust binaries next to the package,
    persia/utils.py:64-66).
    """
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "native_bin", name),
        os.path.join(os.path.dirname(here), "native", "build", name),
    ]
    for c in candidates:
        if os.path.exists(c):
            return c
    raise FileNotFoundError(
        f"native binary {name!r} not found; run `make -C native` first "
        f"(searched {candidates})"
    )
