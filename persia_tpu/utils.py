"""Small shared helpers (reference: persia/utils.py)."""

import os
import random
import socket
import subprocess
import time
from typing import Any, List, Optional

import numpy as np
import yaml


def setup_seed(seed: int):
    """Deterministic seeding across python/numpy (reference: utils.py:13-32).

    JAX PRNG keys are explicit (functional), so unlike the torch reference
    there is no global framework RNG to pin — training code derives all
    device randomness from ``jax.random.key(seed)``.
    """
    random.seed(seed)
    np.random.seed(seed)
    os.environ["PYTHONHASHSEED"] = str(seed)


def force_cpu_platform(n_devices: int = 8, verify: bool = True) -> None:
    """Force JAX onto a virtual ``n_devices``-device CPU platform.

    Must run before the JAX backend initializes. Env vars alone are not
    enough when a platform plugin re-pins ``jax.config`` via sitecustomize,
    so this also updates the config; raises loudly if the backend was
    already initialized with fewer devices (at that point the flags are
    dead letters). Shared by tests/conftest.py, dryrun_multichip, and any
    multi-process CPU-cluster harness.

    ``verify=False`` skips the device-count check, which itself
    initializes the backend — required when ``jax.distributed.initialize``
    must still run after this (it rejects any prior backend init).
    """
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    flag = "--xla_force_host_platform_device_count"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(flag) + r"=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (flags + f" {flag}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{flag}={n_devices}")

    import jax

    jax.config.update("jax_platforms", "cpu")
    if not verify:
        return
    have = len(jax.devices("cpu"))
    if have < n_devices:
        raise RuntimeError(
            f"virtual CPU mesh has {have} device(s), need {n_devices}: the "
            "JAX backend initialized before force_cpu_platform() could set "
            f"XLA_FLAGS; export JAX_PLATFORMS=cpu XLA_FLAGS={flag}="
            f"{n_devices} (or call this earlier), before any jax device use"
        )


def load_yaml(path: str) -> Any:
    if not os.path.exists(path):
        raise FileNotFoundError(f"yaml file not found: {path}")
    with open(path, "r") as f:
        return yaml.safe_load(f)


def dump_yaml(content: Any, path: str):
    with open(path, "w") as f:
        yaml.safe_dump(content, f)


def run_command(cmd: List[str], env: Optional[dict] = None) -> subprocess.Popen:
    full_env = dict(os.environ)
    if env:
        full_env.update({k: str(v) for k, v in env.items()})
    return subprocess.Popen(cmd, env=full_env)


def arm_watchdog(max_seconds: int, label: str = "tool", on_fire=None):
    """Two-tier in-process watchdog for EVERY chip-touching tool.

    Round-4 lesson (BASELINE.md): a TPU client killed EXTERNALLY
    mid-compile wedges the accelerator claim for everyone after it; an
    in-process exit leaves the claim releasable. Tier 1
    (threading.Timer) dumps stacks and exits with a diagnostic — but
    needs the GIL, which a wedged native call may hold. Tier 2
    (faulthandler's pure-C watchdog) needs no GIL and hard-exits 60s
    later as the backstop. Used by bench.py, the probes, and the
    PERSIA_TEST_TPU pytest runs (conftest); never wrap these tools in
    external `timeout`/kill instead.

    ``on_fire``: optional callable run by tier 1 instead of the default
    exit (bench.py passes its JSON-diagnostic emitter); it must
    terminate the process itself. Returns a zero-arg ``cancel``.
    """
    import faulthandler
    import sys
    import threading

    def fire():
        print(f"{label}: watchdog fired after {max_seconds}s — "
              "exiting in-process to keep the accelerator claim "
              "releasable", file=sys.stderr, flush=True)
        faulthandler.dump_traceback(file=sys.stderr)
        if on_fire is not None:
            on_fire()
        # raising in a timer thread wouldn't stop the main thread;
        # os._exit skips atexit but IS an in-process exit — the PJRT
        # client object is torn down with the process, not killed
        # mid-syscall by an outside signal at an arbitrary point
        os._exit(17)

    t = threading.Timer(max_seconds, fire)
    t.daemon = True
    t.start()
    faulthandler.dump_traceback_later(max_seconds + 60, exit=True)

    def cancel():
        t.cancel()
        faulthandler.cancel_dump_traceback_later()

    return cancel


def write_addr_file(addr: str, path: str) -> None:
    """Atomically publish a bound server address for a waiting parent
    (the race-free alternative to probing a free port before spawn)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(addr)
    os.replace(tmp, path)


def wait_addr_file(path: str, timeout: float = 60.0,
                   proc: Optional[subprocess.Popen] = None) -> str:
    """Poll for an addr-file written by :func:`write_addr_file`; if
    ``proc`` is given, fail fast when the child exits first."""
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if proc is not None and proc.poll() is not None:
            raise TimeoutError(
                f"server exited (rc={proc.returncode}) before "
                f"publishing {path}")
        if time.monotonic() > deadline:
            raise TimeoutError(f"no addr-file at {path} after {timeout}s")
        time.sleep(0.05)
    with open(path) as f:
        return f.read().strip()


def find_free_port(start: int = 10000, end: int = 65535) -> int:
    """Pick a currently-free TCP port (reference: utils.py:83-91).

    NOTE: inherently racy (the port can be taken between probe and the
    caller's bind). Prefer binding port 0 + :func:`write_addr_file` for
    parent↔child port handoff; keep this only where a pre-known port is
    semantically required (e.g. restart-on-same-port tests)."""
    for _ in range(128):
        port = random.randint(start, end)
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            try:
                s.bind(("127.0.0.1", port))
                return port
            except OSError:
                continue
    raise RuntimeError("could not find a free port")


def resolve_binary_path(name: str) -> str:
    """Locate a native service binary shipped inside the package.

    Native binaries are built into ``persia_tpu/native_bin/`` by the
    Makefile (reference resolves rust binaries next to the package,
    persia/utils.py:64-66).
    """
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "native_bin", name),
        os.path.join(os.path.dirname(here), "native", "build", name),
    ]
    for c in candidates:
        if os.path.exists(c):
            return c
    raise FileNotFoundError(
        f"native binary {name!r} not found; run `make -C native` first "
        f"(searched {candidates})"
    )


def roc_auc(labels, preds) -> float:
    """Rank-based ROC AUC (Mann-Whitney U), replacing the reference's
    sklearn.metrics dependency in examples (train.py:66-68)."""
    labels = np.asarray(labels).ravel()
    preds = np.asarray(preds).ravel()
    n_pos = int((labels == 1).sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(preds, kind="mergesort")
    ranks = np.empty(len(preds), dtype=np.float64)
    ranks[order] = np.arange(1, len(preds) + 1)
    # average ranks for ties
    sorted_preds = preds[order]
    i = 0
    while i < len(sorted_preds):
        j = i
        while j + 1 < len(sorted_preds) and sorted_preds[j + 1] == sorted_preds[i]:
            j += 1
        if j > i:
            avg = (i + 1 + j + 1) / 2.0
            ranks[order[i : j + 1]] = avg
        i = j + 1
    rank_sum_pos = ranks[labels == 1].sum()
    return float((rank_sum_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))
