"""Pallas flash-attention forward for TPU.

Why a kernel here when the embedding-bag measurement said "let XLA do
it": the XLA formulation of blockwise attention
(`parallel/ring_attention.py local_flash_attention`) is a `lax.scan`
whose carry — o/m/l running statistics, (B,H,T,dh)+2×(B,H,T) f32 —
round-trips through HBM on EVERY k/v chunk. At B=4 H=8 T=8192 dh=128
that is ~134 MB of carry read+written per chunk step, ~16× per call:
the op is carry-bandwidth-bound, not MXU-bound. The fix is structural,
not fusion-level, so XLA cannot do it: keep the per-q-block statistics
in VMEM across the k-grid and only write the finished output block.
This is the classic flash-attention schedule mapped onto the Pallas
TPU grid (sequential iteration, innermost axis fastest; scratch
persists across grid steps — see /opt/skills/guides/pallas_guide.md).

Kernel shape rules: dh is the lane axis of every block (any dh ≤ 128
works, full-axis blocks are padded internally; dh=128 is the sweet
spot). T is padded to the k/q block size by the wrapper; padded KEY
positions are masked via the static true-length, padded QUERY rows
compute garbage that the wrapper slices off.

Backward: Pallas too (jax.custom_vjp). The forward saves (q, k, v,
out, lse); `flash_attention_bwd_pallas` recomputes each softmax block
in VMEM from those residuals with the same schedule run twice — dq
accumulates across the k-grid, dk/dv across the q-grid. delta
(rowsum(dO·O)) is a cheap XLA reduce. Memory stays O(T) end to end.

Measured on TPU v5e (B=4 H=8 T=8192 dh=128 bf16 causal): see
BASELINE.md round-4 table — the motivation numbers above are from
`bench.py --mode attn` on the scan implementation.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # large-finite: -inf NaNs the m-update on all-masked rows


def _fwd_kernel(q_ref, k_ref, v_ref, *rest,
                scale: float, causal: bool, block_q: int, block_k: int,
                t_k_real: int, n_k: int, with_lse: bool, with_mask: bool):
    if with_mask:
        mask_ref, rest = rest[0], rest[1:]
    o_ref, rest = rest[0], rest[1:]
    if with_lse:
        lse_ref, acc, m_scr, l_scr = rest
    else:
        acc, m_scr, l_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    def _body():
        q = q_ref[0]                       # (block_q, dh) bf16/f32
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < t_k_real            # padded keys never attend
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        if with_mask:
            mask = jnp.logical_and(mask, mask_ref[...] > 0)  # (1, bk) bcast
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[...]                # (block_q, 128) lane-replicated
        m_cur = jnp.max(s, axis=1, keepdims=True)       # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)              # (bq, 128)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])                   # (bq, bk) f32
        if with_mask:
            # a FULLY-masked row has s = m_new = NEG_INF everywhere, so
            # the subtraction above degenerates to exp(0)=1; zero it
            # (l stays 0 -> output 0, matching reference_attention)
            p = jnp.where(m_new[:, :1] > _NEG_INF / 2, p, 0.0)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[...] = m_new
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, dh)
        acc[...] = acc[...] * alpha[:, :1] + pv

    if causal:
        # blocks strictly above the diagonal contribute nothing — skip
        # their matmuls (their k/v DMAs still ride the pipeline; pruning
        # those too needs grid index-remapping, not worth it here)
        pl.when((qi + 1) * block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, :1], 1e-20)
        o_ref[0] = (acc[...] / l).astype(o_ref.dtype)
        if with_lse:
            # logsumexp residual for the backward kernels, stored
            # (BH, T) with T on lanes — a (T, 1) layout would be padded
            # to 128 lanes on TPU, 128x the footprint
            lse_ref[...] = jnp.transpose(m_scr[...][:, :1] + jnp.log(l))


def _clamp_block(block, t):
    """Clamp a requested block size to the (padded) sequence length,
    rounded up to a multiple of 8 so Pallas block shapes stay
    sublane-aligned even for ragged T (e.g. t=100 → block 104, with
    ``_pad_t`` padding T to 104). Mosaic rejects sublane-unaligned
    blocks on real hardware even though interpret mode accepts them."""
    return -(-min(block, max(t, 8)) // 8) * 8


def _pad_t(x, block, axis=1):
    """Zero-pad ``axis`` up to a multiple of ``block``."""
    pad = (-x.shape[axis]) % block
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    return x


def _spec_family(block_q, block_k, dh, h, q_minor: bool):
    """The four block-spec shapes every kernel here uses, for one grid
    order: q-tile, k-tile, per-q lane row (lse/delta), per-k lane row
    (kv_mask, batch axis = bh // h). ``q_minor=True`` = grid (bh, qi,
    ki); ``False`` = (bh, ki, qi). One definition so a layout change
    cannot drift between the forward and the two backward calls."""
    if q_minor:
        def pos(bh, qi, ki):
            return qi, ki
    else:
        def pos(bh, ki, qi):
            return qi, ki
    return (
        pl.BlockSpec((1, block_q, dh), lambda *g: (g[0], pos(*g)[0], 0)),
        pl.BlockSpec((1, block_k, dh), lambda *g: (g[0], pos(*g)[1], 0)),
        pl.BlockSpec((1, block_q), lambda *g: (g[0], pos(*g)[0])),
        pl.BlockSpec((1, block_k), lambda *g, h=h: (g[0] // h, pos(*g)[1])),
    )


def flash_attention_fwd_pallas(q, k, v, causal: bool = False,
                               block_q: int = 512, block_k: int = 512,
                               interpret: bool = False,
                               return_lse: bool = False,
                               kv_mask=None):
    """Forward Pallas flash attention. q/k/v: (B, H, T, Dh).

    With ``return_lse`` also returns the (B, H, T) logsumexp residual
    the backward kernels consume. ``kv_mask`` optional (B, T_k) of
    valid key positions; fully-masked query rows yield 0."""
    b, h, t_q, dh = q.shape
    t_k = k.shape[2]
    block_q = _clamp_block(block_q, t_q)
    block_k = _clamp_block(block_k, t_k)
    qp = _pad_t(q.reshape(b * h, t_q, dh), block_q)
    kp = _pad_t(k.reshape(b * h, t_k, dh), block_k)
    vp = _pad_t(v.reshape(b * h, t_k, dh), block_k)
    n_q = qp.shape[1] // block_q
    n_k = kp.shape[1] // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=1.0 / float(dh) ** 0.5, causal=causal,
        block_q=block_q, block_k=block_k, t_k_real=t_k, n_k=n_k,
        with_lse=return_lse, with_mask=kv_mask is not None)
    q_spec, k_spec, qrow_spec, krow_spec = _spec_family(
        block_q, block_k, dh, h, q_minor=True)
    in_specs = [q_spec, k_spec, k_spec]
    operands = [qp, kp, vp]
    if kv_mask is not None:
        # (B, T_k) f32 0/1; the grid's bh axis maps back to batch bh//h
        in_specs.append(krow_spec)
        operands.append(_pad_t(kv_mask.astype(jnp.float32), block_k))
    o_spec = q_spec
    o_shape = jax.ShapeDtypeStruct((b * h, n_q * block_q, dh), q.dtype)
    if return_lse:
        out_specs = (o_spec, qrow_spec)
        out_shape = (o_shape, jax.ShapeDtypeStruct(
            (b * h, n_q * block_q), jnp.float32))
    else:  # serving path: no lse output, no wasted HBM write
        out_specs, out_shape = o_spec, o_shape
    res = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, dh), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    if return_lse:
        out, lse = res
        return (out[:, :t_q].reshape(b, h, t_q, dh),
                lse[:, :t_q].reshape(b, h, t_q))
    return res[:, :t_q].reshape(b, h, t_q, dh)


def _masked_p(q, k, lse, *, scale, causal, block_q, block_k, qi, ki,
              t_q_real, t_k_real, mask_row=None):
    """Recompute the (block_q, block_k) softmax block from q/k/lse with
    padding + causal + optional key masking — shared by both backward
    kernels. Fully-masked rows (lse pinned at NEG_INF by the forward)
    are forced to p=0, not the exp(0)=1 the raw arithmetic gives."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = jnp.logical_and(q_pos < t_q_real, k_pos < t_k_real)
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
    if mask_row is not None:
        mask = jnp.logical_and(mask, mask_row > 0)      # (1, bk) bcast
    s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse)
    return jnp.where(lse > _NEG_INF / 2, p, 0.0)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, scale: float, causal: bool,
                   block_q: int, block_k: int, t_q_real: int,
                   t_k_real: int, n_k: int, with_mask: bool):
    if with_mask:
        mask_ref, dq_ref, dq_acc = rest
    else:
        mask_ref, (dq_ref, dq_acc) = None, rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body():
        # lse/delta ride in (1, block_q) lane-major rows (a (T, 1)
        # layout would be 128-lane padded in HBM); transpose to columns
        lse = jnp.transpose(lse_ref[...])               # (bq, 1)
        delta = jnp.transpose(delta_ref[...])
        p = _masked_p(q_ref[0], k_ref[0], lse, scale=scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      qi=qi, ki=ki, t_q_real=t_q_real, t_k_real=t_k_real,
                      mask_row=None if mask_ref is None else mask_ref[...])
        do = do_ref[0]
        dp = jax.lax.dot_general(                       # dO @ V^T
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                           # (bq, bk)
        dq_acc[...] += jax.lax.dot_general(             # ds @ K
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when((qi + 1) * block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, scale: float,
                    causal: bool, block_q: int, block_k: int,
                    t_q_real: int, t_k_real: int, n_q: int,
                    with_mask: bool):
    if with_mask:
        mask_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        mask_ref, (dk_ref, dv_ref, dk_acc, dv_acc) = None, rest
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0]
        lse = jnp.transpose(lse_ref[...])               # (bq, 1)
        delta = jnp.transpose(delta_ref[...])
        p = _masked_p(q, k_ref[0], lse, scale=scale,
                      causal=causal, block_q=block_q, block_k=block_k,
                      qi=qi, ki=ki, t_q_real=t_q_real, t_k_real=t_k_real,
                      mask_row=None if mask_ref is None else mask_ref[...])
        do = do_ref[0]
        dv_acc[...] += jax.lax.dot_general(             # P^T @ dO
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(                       # dO @ V^T
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[...] += jax.lax.dot_general(             # ds^T @ Q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when((qi + 1) * block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def flash_attention_bwd_pallas(q, k, v, out, lse, do, causal: bool = False,
                               block_q: int = 512, block_k: int = 512,
                               interpret: bool = False, kv_mask=None):
    """Pallas flash-attention backward: (dq, dk, dv).

    Same schedule as the forward, run twice: dq revisits its q-block
    accumulator across the k-grid; dk/dv revisit their k-block
    accumulators across the q-grid. The softmax block is recomputed
    from (q, k, lse) in VMEM — nothing quadratic ever touches HBM.
    """
    b, h, t_q, dh = q.shape
    t_k = k.shape[2]
    block_q = _clamp_block(block_q, t_q)
    block_k = _clamp_block(block_k, t_k)
    scale = 1.0 / float(dh) ** 0.5
    # delta_i = rowsum(dO_i * O_i) — cheap XLA elementwise+reduce
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                             # (b, h, t_q)
    qp = _pad_t(q.reshape(b * h, t_q, dh), block_q)
    kp = _pad_t(k.reshape(b * h, t_k, dh), block_k)
    vp = _pad_t(v.reshape(b * h, t_k, dh), block_k)
    dop = _pad_t(do.reshape(b * h, t_q, dh), block_q)
    lsep = _pad_t(lse.reshape(b * h, t_q), block_q)
    deltap = _pad_t(delta.reshape(b * h, t_q), block_q)
    n_q = qp.shape[1] // block_q
    n_k = kp.shape[1] // block_k

    maskp = (None if kv_mask is None
             else _pad_t(kv_mask.astype(jnp.float32), block_k))

    q_spec, k_spec, col_spec, mask_spec = _spec_family(
        block_q, block_k, dh, h, q_minor=True)
    in_specs = [q_spec, k_spec, k_spec, q_spec, col_spec, col_spec]
    operands = [qp, kp, vp, dop, lsep, deltap]
    if maskp is not None:
        in_specs.append(mask_spec)
        operands.append(maskp)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_q_real=t_q, t_k_real=t_k, n_k=n_k,
            with_mask=maskp is not None),
        grid=(b * h, n_q, n_k),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, n_q * block_q, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=interpret,
    )(*operands)

    # dk/dv: k-block outermost, q innermost (the accumulation axis)
    q_spec2, k_spec2, col_spec2, mask_spec2 = _spec_family(
        block_q, block_k, dh, h, q_minor=False)
    in_specs2 = [q_spec2, k_spec2, k_spec2, q_spec2, col_spec2, col_spec2]
    operands2 = [qp, kp, vp, dop, lsep, deltap]
    if maskp is not None:
        in_specs2.append(mask_spec2)
        operands2.append(maskp)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_q_real=t_q, t_k_real=t_k, n_q=n_q,
            with_mask=maskp is not None),
        grid=(b * h, n_k, n_q),
        in_specs=in_specs2,
        out_specs=(k_spec2, k_spec2),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, n_k * block_k, dh), k.dtype),
            jax.ShapeDtypeStruct((b * h, n_k * block_k, dh), v.dtype),
        ),
        scratch_shapes=[pltpu.VMEM((block_k, dh), jnp.float32),
                        pltpu.VMEM((block_k, dh), jnp.float32)],
        interpret=interpret,
    )(*operands2)
    return (dq[:, :t_q].reshape(b, h, t_q, dh),
            dk[:, :t_k].reshape(b, h, t_k, dh),
            dv[:, :t_k].reshape(b, h, t_k, dh))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 512,
                    block_k: int = 512, interpret: bool = False):
    """Flash attention, Pallas forward AND backward.

    The forward saves (q, k, v, out, lse); the backward recomputes each
    softmax block in VMEM from those residuals — memory stays O(T)
    end-to-end and nothing quadratic touches HBM in either direction.
    """
    return flash_attention_fwd_pallas(q, k, v, causal=causal,
                                      block_q=block_q, block_k=block_k,
                                      interpret=interpret)


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = flash_attention_fwd_pallas(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, return_lse=True)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return flash_attention_bwd_pallas(
        q, k, v, out, lse, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention_masked(q, k, v, maskf, causal, block_q, block_k,
                            interpret):
    return flash_attention_fwd_pallas(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, kv_mask=maskf)


def _fam_fwd(q, k, v, maskf, causal, block_q, block_k, interpret):
    out, lse = flash_attention_fwd_pallas(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, return_lse=True, kv_mask=maskf)
    return out, (q, k, v, out, lse, maskf)


def _fam_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse, maskf = res
    dq, dk, dv = flash_attention_bwd_pallas(
        q, k, v, out, lse, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret, kv_mask=maskf)
    return dq, dk, dv, jnp.zeros_like(maskf)


_flash_attention_masked.defvjp(_fam_fwd, _fam_bwd)


def flash_attention_masked(q, k, v, kv_mask=None, causal: bool = False,
                           block_q: int = 512, block_k: int = 512,
                           interpret="auto"):
    """`flash_attention` with an optional (B, T_k) key-validity mask —
    the entry the sequence tower / Ulysses paths use (the mask rides as
    f32 0/1 so the custom_vjp plumbing stays all-float; its cotangent
    is zero). ``interpret="auto"`` compiles on TPU and falls back to
    the Pallas interpreter elsewhere (CPU tests)."""
    if interpret == "auto":
        interpret = jax.default_backend() != "tpu"
    if kv_mask is None:
        return flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return _flash_attention_masked(
        q, k, v, kv_mask.astype(jnp.float32), causal, block_q, block_k,
        interpret)
