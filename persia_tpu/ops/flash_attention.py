"""Pallas flash-attention forward for TPU.

Why a kernel here when the embedding-bag measurement said "let XLA do
it": the XLA formulation of blockwise attention
(`parallel/ring_attention.py local_flash_attention`) is a `lax.scan`
whose carry — o/m/l running statistics, (B,H,T,dh)+2×(B,H,T) f32 —
round-trips through HBM on EVERY k/v chunk. At B=4 H=8 T=8192 dh=128
that is ~134 MB of carry read+written per chunk step, ~16× per call:
the op is carry-bandwidth-bound, not MXU-bound. The fix is structural,
not fusion-level, so XLA cannot do it: keep the per-q-block statistics
in VMEM across the k-grid and only write the finished output block.
This is the classic flash-attention schedule mapped onto the Pallas
TPU grid (sequential iteration, innermost axis fastest; scratch
persists across grid steps — see /opt/skills/guides/pallas_guide.md).

Kernel shape rules: dh is the lane axis of every block (any dh ≤ 128
works, full-axis blocks are padded internally; dh=128 is the sweet
spot). T is padded to the k/q block size by the wrapper; padded KEY
positions are masked via the static true-length, padded QUERY rows
compute garbage that the wrapper slices off.

Backward: jax.custom_vjp with recompute-through-the-XLA-scan — the
residuals are (q, k, v) only, the bwd pass differentiates
`local_flash_attention` (numerically identical online softmax). The
forward (serving, and the fwd half of training) takes the Pallas path.

Measured on TPU v5e (B=4 H=8 T=8192 dh=128 bf16 causal): see
BASELINE.md round-4 table — the motivation numbers above are from
`bench.py --mode attn` on the scan implementation.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30  # large-finite: -inf NaNs the m-update on all-masked rows


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                t_k_real: int, n_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    def _body():
        q = q_ref[0]                       # (block_q, dh) bf16/f32
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < t_k_real            # padded keys never attend
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[...]                # (block_q, 128) lane-replicated
        m_cur = jnp.max(s, axis=1, keepdims=True)       # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)              # (bq, 128)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])                   # (bq, bk) f32
        l_scr[...] = l_scr[...] * alpha + jnp.sum(
            p, axis=1, keepdims=True)
        m_scr[...] = m_new
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, dh)
        acc[...] = acc[...] * alpha[:, :1] + pv

    if causal:
        # blocks strictly above the diagonal contribute nothing — skip
        # their matmuls (their k/v DMAs still ride the pipeline; pruning
        # those too needs grid index-remapping, not worth it here)
        pl.when((qi + 1) * block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(ki == n_k - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, :1], 1e-20)
        o_ref[0] = (acc[...] / l).astype(o_ref.dtype)


def _pad_t(x, block, axis=1):
    """Zero-pad ``axis`` up to a multiple of ``block``."""
    pad = (-x.shape[axis]) % block
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    return x


def flash_attention_fwd_pallas(q, k, v, causal: bool = False,
                               block_q: int = 512, block_k: int = 512,
                               interpret: bool = False):
    """Forward-only Pallas flash attention. q/k/v: (B, H, T, Dh)."""
    b, h, t_q, dh = q.shape
    t_k = k.shape[2]
    block_q = min(block_q, max(t_q, 8))
    block_k = min(block_k, max(t_k, 8))
    qp = _pad_t(q.reshape(b * h, t_q, dh), block_q)
    kp = _pad_t(k.reshape(b * h, t_k, dh), block_k)
    vp = _pad_t(v.reshape(b * h, t_k, dh), block_k)
    n_q = qp.shape[1] // block_q
    n_k = kp.shape[1] // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=1.0 / float(dh) ** 0.5, causal=causal,
        block_q=block_q, block_k=block_k, t_k_real=t_k, n_k=n_k)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, dh), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, dh), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, n_q * block_q, dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, dh), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :t_q].reshape(b, h, t_q, dh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 512,
                    block_k: int = 512, interpret: bool = False):
    """Flash attention with a Pallas forward and recompute backward.

    Forward runs the VMEM-resident Pallas kernel; backward recomputes
    through the XLA blockwise implementation (same online softmax), so
    gradients match `local_flash_attention`'s to numerical tolerance.
    """
    return flash_attention_fwd_pallas(q, k, v, causal=causal,
                                      block_q=block_q, block_k=block_k,
                                      interpret=interpret)


def _fa_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _fa_bwd(causal, block_q, block_k, interpret, res, g):
    from persia_tpu.parallel.ring_attention import local_flash_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: local_flash_attention(
            q, k, v, causal=causal, chunk_size=block_k), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
