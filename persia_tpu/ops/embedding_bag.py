"""Fused embedding-bag: gather + weighted pool in one pass.

The hot op of device-mode sparse training. The XLA path materializes a
(batch, bag, dim) gather in HBM before pooling; the Pallas kernel streams
table rows HBM→VMEM with per-row async DMA (scalar-prefetched indices)
and pools in VMEM, so the intermediate never touches HBM — the op stays
at the HBM-bandwidth floor of one row read per id.

Backward is the standard scatter-add, expressed in XLA (a Pallas bwd
would need atomics or a sort pass; XLA's scatter is already near-optimal
on TPU), wired through jax.custom_vjp so the forward implementation
choice doesn't affect autodiff.

The Pallas kernel is validated in interpreter mode on CPU
(tests/test_ops.py) and compiled on TPU; `impl="auto"` picks XLA until
per-chip profiling justifies flipping the default.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def xla_embedding_bag(table, ids, weights):
    """Reference implementation: gather + weighted sum.

    table: (V, D) f32; ids: (B, S) int32; weights: (B, S) f32 (0 for
    padding). Returns (B, D).
    """
    gathered = jnp.take(table, ids, axis=0)  # (B, S, D)
    return (gathered * weights[..., None].astype(gathered.dtype)).sum(axis=1)


def _bag_kernel(ids_ref, table_hbm, w_ref, out_ref, scratch, sems):
    b = pl.program_id(0)
    bag = scratch.shape[0]

    def start_copy(j, _):
        idx = ids_ref[b * bag + j]
        pltpu.make_async_copy(
            table_hbm.at[idx], scratch.at[j], sems.at[j]
        ).start()
        return _

    jax.lax.fori_loop(0, bag, start_copy, 0)

    def wait_copy(j, _):
        idx = ids_ref[b * bag + j]
        pltpu.make_async_copy(
            table_hbm.at[idx], scratch.at[j], sems.at[j]
        ).wait()
        return _

    jax.lax.fori_loop(0, bag, wait_copy, 0)
    w = w_ref[0, :]  # (S,)
    out_ref[0, :] = jnp.sum(scratch[:, :] * w[:, None], axis=0)


def pallas_embedding_bag(table, ids, weights, interpret: bool = False):
    """Pallas forward. Shapes as :func:`xla_embedding_bag`."""
    batch, bag = ids.shape
    dim = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # table stays in HBM
            pl.BlockSpec((1, bag), lambda b, ids: (b, 0)),  # weights row
        ],
        out_specs=pl.BlockSpec((1, dim), lambda b, ids: (b, 0)),
        scratch_shapes=[
            pltpu.VMEM((bag, dim), jnp.float32),
            pltpu.SemaphoreType.DMA((bag,)),
        ],
    )
    fn = pl.pallas_call(
        _bag_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, dim), jnp.float32),
        interpret=interpret,
    )
    return fn(ids.reshape(-1).astype(jnp.int32),
              table.astype(jnp.float32),
              weights.astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def embedding_bag(table, ids, weights, impl: str = "auto",
                  interpret: bool = False):
    """Pooled embedding lookup with a scatter-add backward.

    impl: "xla" | "pallas" | "auto" (auto = xla until profiling flips it).
    """
    if impl == "pallas":
        return pallas_embedding_bag(table, ids, weights, interpret=interpret)
    return xla_embedding_bag(table, ids, weights)


def _fwd(table, ids, weights, impl, interpret):
    out = embedding_bag(table, ids, weights, impl, interpret)
    return out, (table, ids, weights)


def _bwd(impl, interpret, res, g):
    table, ids, weights = res
    # d table: scatter-add g into every id's row, weighted
    contrib = g[:, None, :] * weights[..., None].astype(g.dtype)  # (B,S,D)
    d_table = jnp.zeros_like(table).at[ids.reshape(-1)].add(
        contrib.reshape(-1, table.shape[1]).astype(table.dtype)
    )
    # d weights: dot of g with each gathered row
    gathered = jnp.take(table, ids, axis=0)
    d_weights = jnp.einsum("bsd,bd->bs", gathered.astype(g.dtype), g).astype(
        weights.dtype
    )
    return d_table, None, d_weights


embedding_bag.defvjp(_fwd, _bwd)
