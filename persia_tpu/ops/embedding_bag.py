"""Fused embedding-bag: gather + weighted pool in one pass.

The hot op of device-mode sparse training. The XLA path materializes a
(batch, bag, dim) gather in HBM before pooling; the Pallas kernel streams
table rows HBM→VMEM with per-row async DMA (scalar-prefetched indices)
and pools in VMEM, so the intermediate never touches HBM — the op stays
at the HBM-bandwidth floor of one row read per id.

Mosaic rejects DMAs of sub-(8,128) tiles, so a (V, dim<128) table cannot
be gathered row-by-row directly (found on real TPU in round 4 — the
interpret-mode tests had hidden it; tools/probe_dma_shapes.py records
which copy shapes lower: (128,)/(1,128)/(8,128) yes, (16,) no). The
kernel therefore works on a LANE-PACKED layout (:func:`pack_table`):
P = 128/dim rows share one 128-lane row, every DMA moves exactly one
(1,128) lane row, ids are split into (pack_row, segment) on the host,
the bag accumulates in packed lane space under a segment mask, and P
static lane-slices fold the result — per id: one DMA + one masked
multiply-add; per 8-sample group: P-1 adds. 8 samples per program keep
the output on full sublane tiles.

Measured verdict (real v5e, V=2^16 D=16 B=4096 S=8): the packed kernel
lowers and matches XLA bit-for-bit tolerance, but runs ~90x SLOWER than
XLA's gather (907 ms vs 10 ms/call) — one small DMA per id costs ~27 us
of descriptor overhead against a 512-byte payload, while XLA's native
dynamic-gather uses the hardware gather path. Scattered per-row DMA is
the wrong tool on this hardware; `impl="auto"` stays on XLA by
measurement, not by caution. The kernel remains as the validated
counter-example and as scaffolding for a future multi-row-per-DMA
variant (clustered/sorted ids).

Backward is the standard scatter-add, expressed in XLA (a Pallas bwd
would need atomics or a sort pass; XLA's scatter is already near-optimal
on TPU), wired through jax.custom_vjp so the forward implementation
choice doesn't affect autodiff.

The Pallas kernel is validated in interpreter mode on CPU
(tests/test_ops.py) and compiled on TPU; `impl="auto"` picks XLA until
per-chip profiling justifies flipping the default.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def xla_embedding_bag(table, ids, weights):
    """Reference implementation: gather + weighted sum.

    table: (V, D) f32; ids: (B, S) int32; weights: (B, S) f32 (0 for
    padding). Returns (B, D).
    """
    gathered = jnp.take(table, ids, axis=0)  # (B, S, D)
    return (gathered * weights[..., None].astype(gathered.dtype)).sum(axis=1)


_GROUP = 8  # samples per program: one f32 sublane tile of output


def pack_table(table):
    """Lane-pack a (V, dim) table into (ceil(V/P), 128), P = 128 // dim.

    Row ``i`` of the original table lives in packed row ``i // P`` at
    lanes ``[(i % P) * dim, (i % P + 1) * dim)``. Real Mosaic rejects
    per-row DMA of sub-(8,128) tiles, so a (dim,)-row table cannot be
    gathered row-by-row; after packing every DMA moves one full
    128-lane row. dim must divide 128 (8/16/32/64/128 — the recsys
    range; pad the table dim otherwise).
    """
    v, dim = table.shape
    if 128 % dim:
        hint = ("pad the table dim up to a divisor of 128"
                if dim < 128 else
                "split the columns into 128-wide chunks, or use the "
                "xla impl (impl='xla'/'auto')")
        raise ValueError(
            f"lane packing needs dim to divide 128, got {dim}; {hint}")
    p = 128 // dim
    vp = (v + p - 1) // p
    pad = vp * p - v
    if pad:
        table = jnp.concatenate(
            [table, jnp.zeros((pad, dim), table.dtype)], axis=0)
    return table.reshape(vp, 128)


def _packed_bag_kernel(pack_rows_ref, table_hbm, segs_ref, w_ref, out_ref,
                       scratch, sems, *, bag: int, dim: int):
    g = pl.program_id(0)
    grp = out_ref.shape[0]

    # bag and grp are static, so the copy loops unroll at trace time —
    # every scratch/semaphore index is static and every SMEM read uses
    # an affine (program_id-relative) address. Mosaic rejects the
    # fori_loop formulation: loop-carried j makes segs_ref[:, j] a
    # DYNAMIC lane index, which has no TPU lowering.
    copies = []
    for j in range(bag):
        for s in range(grp):
            r = pack_rows_ref[(g * grp + s) * bag + j]
            c = pltpu.make_async_copy(
                table_hbm.at[pl.ds(r, 1), :],
                scratch.at[j, pl.ds(s, 1), :],
                sems.at[j, s],
            )
            c.start()
            copies.append(c)
    for c in copies:
        c.wait()

    # accumulate in packed lane space: each id's row occupies its own
    # dim-lane segment; mask to that segment, weight, sum over the bag.
    # Static j -> segs_ref[:, j] is a static lane slice (legal).
    lane_seg = jax.lax.broadcasted_iota(jnp.int32, (grp, 128), 1) // dim
    acc = jnp.zeros((grp, 128), jnp.float32)
    for j in range(bag):
        seg = segs_ref[:, j][:, None]          # (grp, 1)
        w = w_ref[:, j][:, None]               # (grp, 1)
        rows = scratch[j]                      # (grp, 128)
        acc = acc + jnp.where(lane_seg == seg, rows, 0.0) * w

    # fold the P segments together: P static lane-slices at aligned
    # offsets (the only cross-lane step, once per group — not per id)
    out = acc[:, 0:dim]
    for p in range(1, 128 // dim):
        out = out + acc[:, p * dim:(p + 1) * dim]
    out_ref[...] = out


def pallas_embedding_bag_packed(packed_table, ids, weights, dim: int,
                                interpret: bool = False):
    """Forward over a :func:`pack_table`-packed table.

    packed_table: (Vp, 128) f32; ids: (B, S) int32 (original row ids);
    weights: (B, S) f32. Returns (B, dim) f32. B is padded up to a
    multiple of 8 internally (one sublane tile of output per program).
    """
    batch, bag = ids.shape
    if 128 % dim:
        # same guard as pack_table: a truncated P would silently address
        # the wrong lanes (garbage output, no error)
        raise ValueError(
            f"lane packing needs dim to divide 128, got {dim}")
    if packed_table.shape[1] != 128:
        raise ValueError(
            f"packed_table must be (Vp, 128) from pack_table(), got "
            f"{packed_table.shape}")
    p = 128 // dim
    padded = (batch + _GROUP - 1) // _GROUP * _GROUP
    if padded != batch:
        ids = jnp.concatenate(
            [ids, jnp.zeros((padded - batch, bag), ids.dtype)], axis=0)
        weights = jnp.concatenate(
            [weights, jnp.zeros((padded - batch, bag), weights.dtype)],
            axis=0)
    # Clamp to the packed-table range: XLA's gather clamps out-of-range
    # indices, but a Pallas DMA does not — an oversized id would read
    # past the table in HBM (garbage, or a fault on real hardware).
    ids = jnp.clip(ids, 0, packed_table.shape[0] * p - 1)
    pack_rows = (ids // p).reshape(-1).astype(jnp.int32)
    segs = (ids % p).astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(padded // _GROUP,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # packed table stays in HBM
            pl.BlockSpec((_GROUP, bag), lambda g, pr: (g, 0)),  # segs
            pl.BlockSpec((_GROUP, bag), lambda g, pr: (g, 0)),  # weights
        ],
        out_specs=pl.BlockSpec((_GROUP, dim), lambda g, pr: (g, 0)),
        scratch_shapes=[
            pltpu.VMEM((bag, _GROUP, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((bag, _GROUP)),
        ],
    )
    fn = pl.pallas_call(
        functools.partial(_packed_bag_kernel, bag=bag, dim=dim),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((padded, dim), jnp.float32),
        interpret=interpret,
    )
    out = fn(pack_rows, packed_table.astype(jnp.float32),
             segs, weights.astype(jnp.float32))
    return out[:batch]


def pallas_embedding_bag(table, ids, weights, interpret: bool = False):
    """Pallas forward. Shapes as :func:`xla_embedding_bag`.

    Convenience entry: lane-packs the table on every call (an O(V)
    reshape — fine for validation; steady-state users keep the table
    packed and call :func:`pallas_embedding_bag_packed` directly).
    """
    dim = table.shape[1]
    return pallas_embedding_bag_packed(
        pack_table(table), ids, weights, dim, interpret=interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def embedding_bag(table, ids, weights, impl: str = "auto",
                  interpret: bool = False):
    """Pooled embedding lookup with a scatter-add backward.

    impl: "xla" | "pallas" | "auto" (auto = xla until profiling flips it).
    """
    if impl == "pallas":
        return pallas_embedding_bag(table, ids, weights, interpret=interpret)
    return xla_embedding_bag(table, ids, weights)


def _fwd(table, ids, weights, impl, interpret):
    out = embedding_bag(table, ids, weights, impl, interpret)
    return out, (table, ids, weights)


def _bwd(impl, interpret, res, g):
    table, ids, weights = res
    # d table: scatter-add g into every id's row, weighted
    contrib = g[:, None, :] * weights[..., None].astype(g.dtype)  # (B,S,D)
    d_table = jnp.zeros_like(table).at[ids.reshape(-1)].add(
        contrib.reshape(-1, table.shape[1]).astype(table.dtype)
    )
    # d weights: dot of g with each gathered row
    gathered = jnp.take(table, ids, axis=0)
    d_weights = jnp.einsum("bsd,bd->bs", gathered.astype(g.dtype), g).astype(
        weights.dtype
    )
    return d_table, None, d_weights


embedding_bag.defvjp(_fwd, _bwd)
