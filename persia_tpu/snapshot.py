"""Coordinated whole-job snapshots + resume.

PERSIA persists the hybrid model through a dedicated model-manager
layer (persia-model-manager) so a *job* — not just a PS replica —
survives failure. PR 4 made PS replicas crash-safe and PR 12 made
resharding crash-safe; this module closes the last unprotected actor:
a SIGKILL of the trainer (or an embedding worker) no longer loses the
dense weights, dense optimizer state, data position, or in-flight
gradients of the run.

One snapshot is one directory ``<snapshot_dir>/snap_<seq>`` holding:

- ``replica_<i>.psd`` + ``embedding_dump_done`` — every PS replica's
  store, dumped through :func:`checkpoint.dump_sharded` AFTER the
  snapshot barrier (below), with the routing table recorded in the
  marker when non-uniform (the PR-12 ownership-filter contract);
- ``dense.msgpack`` — flax TrainState bytes (model + dense optimizer);
- ``cursor.json`` — the deterministic dataloader cursor
  (:class:`persia_tpu.data.dataloader.ResumableDataset`), so resume
  replays exactly the batches the wiped post-snapshot steps consumed;
- ``manifest.json`` — written LAST, via the fsync'd
  :meth:`storage.PersiaPath.write_bytes_atomic`, carrying a sha256 +
  size for every other file, the trainer step, per-replica PS
  update-version watermarks, the routing epoch, and the inc-packet
  watermark.

**Barrier.** :func:`snapshot_job` first drains the backward pipeline
(``flush_backward_engines`` — the PR-4 staleness-permit machinery), so
at the capture point there are ZERO in-flight gradient updates: the PS
dump, the dense state, and the cursor all describe the same consistent
cut "every update of batches ``0..cursor.consumed`` applied, nothing
else". That cut is what makes the resume path's bounded-loss argument
exact: rolling the whole job back to the snapshot and replaying the
deterministic batch stream from the cursor re-derives the wiped
suffix once — per-sign counting identities hold with zero ambiguity.

**Completeness.** A snapshot is complete iff ``manifest.json`` exists
AND every checksum verifies. The manifest is written last and
atomically, so a trainer killed mid-snapshot leaves a manifest-less
(or checksum-failing) directory that :func:`latest_snapshot` refuses,
falling back to the previous complete snapshot. Retention
(``PERSIA_SNAPSHOT_KEEP``) removes older completes and torn debris.

**Inc-packet watermark.** The manifest records the names of every
*complete* incremental-update packet at capture time. Packets are
absolute row values (last-writer-wins), so the watermark lets a PS
restore replay exactly the post-snapshot suffix; replaying a packet
that raced the dump is idempotent either way.
"""

import hashlib
import json
import os
import re
import time
from typing import List, Optional, Sequence, Tuple

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger
from persia_tpu.storage import PersiaPath

_logger = get_default_logger(__name__)

MANIFEST = "manifest.json"
SNAP_PREFIX = "snap_"
CURSOR_FILE = "cursor.json"
_SNAP_RE = re.compile(r"^snap_(\d{6,})$")


class SnapshotError(RuntimeError):
    """A snapshot directory failed verification (torn / tampered)."""


def _snap_name(seq: int) -> str:
    return f"{SNAP_PREFIX}{seq:06d}"


def _snap_seq(name: str) -> Optional[int]:
    m = _SNAP_RE.match(name)
    return int(m.group(1)) if m else None


def _sha256_file(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
            size += len(chunk)
    return h.hexdigest(), size


def list_snapshots(snapshot_dir: str) -> List[str]:
    """Every ``snap_*`` directory under ``snapshot_dir`` (complete or
    not), oldest first."""
    if not os.path.isdir(snapshot_dir):
        return []
    names = [(seq, n) for n in os.listdir(snapshot_dir)
             for seq in (_snap_seq(n),)
             if seq is not None
             and os.path.isdir(os.path.join(snapshot_dir, n))]
    return [os.path.join(snapshot_dir, n) for _, n in sorted(names)]


def load_manifest(snap_dir: str) -> dict:
    """Parse + VERIFY one snapshot's manifest. Raises
    :class:`SnapshotError` when the manifest is absent, unparsable, or
    any listed file is missing / size-mismatched / checksum-failed —
    the torn-snapshot refusal the resume path builds on."""
    mpath = os.path.join(snap_dir, MANIFEST)
    if not os.path.exists(mpath):
        raise SnapshotError(f"{snap_dir}: no {MANIFEST} (torn snapshot)")
    try:
        with open(mpath, "rb") as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise SnapshotError(f"{snap_dir}: unreadable manifest: {e}") from e
    files = manifest.get("files")
    if not isinstance(files, dict) or not files:
        raise SnapshotError(f"{snap_dir}: manifest lists no files")
    for name, meta in files.items():
        path = os.path.join(snap_dir, name)
        if not os.path.exists(path):
            raise SnapshotError(f"{snap_dir}: manifest names missing "
                                f"file {name!r}")
        digest, size = _sha256_file(path)
        if size != meta.get("bytes"):
            raise SnapshotError(
                f"{snap_dir}/{name}: size {size} != manifest "
                f"{meta.get('bytes')} (torn write)")
        if digest != meta.get("sha256"):
            raise SnapshotError(f"{snap_dir}/{name}: checksum mismatch")
    return manifest


def latest_snapshot(snapshot_dir: str) -> Optional[Tuple[str, dict]]:
    """Newest COMPLETE snapshot ``(path, manifest)`` — newest-first
    scan, refusing torn/partial directories with a warning and falling
    back to the previous complete one. ``None`` when nothing usable
    exists (cold start)."""
    for snap in reversed(list_snapshots(snapshot_dir)):
        try:
            return snap, load_manifest(snap)
        except SnapshotError as e:
            _logger.warning("refusing snapshot %s: %s", snap, e)
    return None


def _complete_inc_packets(inc_dir: Optional[str]) -> Optional[List[str]]:
    """Names of every COMPLETE inc packet right now — the replay
    watermark. None when the job runs without incremental updates."""
    if not inc_dir:
        return None
    from persia_tpu.inc_update import ready_packets

    return sorted(name for name, _, _ in ready_packets(inc_dir, set()))


def _ps_watermarks(worker, ps_clients: Optional[Sequence]) -> Optional[list]:
    """Per-replica ``{update_version, routing_epoch}`` read from each
    PS health doc — forensic watermarks stamped into the manifest (the
    restore path keys on the PSD files + routing doc, not on these)."""
    clients = ps_clients
    if clients is None:
        clients = getattr(worker, "ps_clients", None)
    if not clients:
        return None
    marks = []
    for c in clients:
        health = getattr(c, "health", None)
        if health is None:
            marks.append(None)
            continue
        try:
            doc = health()
            marks.append({"update_version": doc.get("update_version"),
                          "routing_epoch": doc.get("routing_epoch")})
        except Exception:  # noqa: BLE001 — watermark is advisory
            marks.append(None)
    return marks


def snapshot_job(
    snapshot_dir: str,
    worker,
    *,
    state=None,
    cursor: Optional[dict] = None,
    ps_clients: Optional[Sequence] = None,
    inc_dir: Optional[str] = None,
    step: int = 0,
    keep: Optional[int] = None,
    extra: Optional[dict] = None,
    pre_manifest=None,
) -> str:
    """Take one coordinated job snapshot; returns the snapshot path.

    ``worker`` is the (in-process or remote) embedding worker whose
    ``dump`` fans the PS store out — its dump path already runs the
    ``flush_backward_engines`` barrier, but we run it explicitly FIRST
    so the cursor/dense capture below sits behind the same quiesce
    point. ``state`` is the flax TrainState (None for sparse-only
    jobs), ``cursor`` the dataloader cursor doc, ``inc_dir`` the
    incremental-update packet directory (for the replay watermark).
    """
    from persia_tpu.pipeline import flush_backward_engines

    os.makedirs(snapshot_dir, exist_ok=True)
    seqs = [_snap_seq(os.path.basename(p))
            for p in list_snapshots(snapshot_dir)]
    seq = 1 + max([s for s in seqs if s is not None], default=-1)
    snap = os.path.join(snapshot_dir, _snap_name(seq))
    os.makedirs(snap, exist_ok=True)

    # --- barrier: zero in-flight gradient updates past this line -----
    flush_backward_engines(worker)

    # --- sparse: every PS replica + routing-stamped done marker -------
    worker.dump(snap)

    # --- dense + cursor ----------------------------------------------
    from persia_tpu import checkpoint as ckpt

    if state is not None:
        PersiaPath(os.path.join(snap, ckpt.DENSE_FILE)).write_bytes(
            ckpt.dense_state_bytes(state))
    if cursor is not None:
        PersiaPath(os.path.join(snap, CURSOR_FILE)).write_bytes(
            json.dumps(cursor, sort_keys=True).encode())

    # --- manifest (LAST, atomic + fsync'd): completeness stamp --------
    files = {}
    for name in sorted(os.listdir(snap)):
        path = os.path.join(snap, name)
        if name == MANIFEST or not os.path.isfile(path):
            continue
        digest, size = _sha256_file(path)
        files[name] = {"sha256": digest, "bytes": size}
    marker = ckpt.read_done_marker(snap)
    manifest = {
        "version": 1,
        "seq": seq,
        "step": int(step),
        "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "files": files,
        "cursor": cursor,
        "num_shards": marker.get("num_shards"),
        "routing": marker.get("routing"),
        "routing_epoch": getattr(worker, "routing_epoch", None),
        "ps_watermarks": _ps_watermarks(worker, ps_clients),
        "inc_watermark": _complete_inc_packets(inc_dir),
    }
    if extra:
        manifest.update(extra)
    if pre_manifest is not None:
        # chaos-injection seam: everything is on disk EXCEPT the
        # manifest — a kill fired here leaves exactly the torn state
        # the refusal/fallback path must handle
        pre_manifest(snap)
    PersiaPath(os.path.join(snap, MANIFEST)).write_bytes_atomic(
        json.dumps(manifest, sort_keys=True, indent=1).encode())

    gc_snapshots(snapshot_dir, keep=keep)
    return snap


def gc_snapshots(snapshot_dir: str, keep: Optional[int] = None) -> List[str]:
    """Retention: keep the newest ``keep`` (PERSIA_SNAPSHOT_KEEP)
    COMPLETE snapshots; remove older completes and any torn debris
    older than the newest complete (a torn directory NEWER than the
    newest complete may be a snapshot in progress — left alone).
    Returns the removed paths."""
    if keep is None:
        keep = knobs.get("PERSIA_SNAPSHOT_KEEP")
    keep = max(1, int(keep))
    snaps = list_snapshots(snapshot_dir)
    complete = []
    torn = []
    for snap in snaps:
        try:
            load_manifest(snap)
            complete.append(snap)
        except SnapshotError:
            torn.append(snap)
    removed = []
    for snap in complete[:-keep]:
        PersiaPath(snap).remove()
        removed.append(snap)
    if complete:
        newest = _snap_seq(os.path.basename(complete[-1]))
        for snap in torn:
            if _snap_seq(os.path.basename(snap)) < newest:
                PersiaPath(snap).remove()
                removed.append(snap)
    if removed:
        _logger.info("snapshot gc removed %d dir(s): %s", len(removed),
                     ", ".join(os.path.basename(r) for r in removed))
    return removed


# --- resume --------------------------------------------------------------


def dense_bytes(snap_dir: str) -> Optional[bytes]:
    from persia_tpu import checkpoint as ckpt

    path = os.path.join(snap_dir, ckpt.DENSE_FILE)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return f.read()


def load_cursor(snap_dir: str) -> Optional[dict]:
    path = os.path.join(snap_dir, CURSOR_FILE)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return json.load(f)


def resolve_snapshot(path: str) -> Tuple[str, dict]:
    """``path`` may be one snapshot directory or a snapshot_dir parent:
    returns the verified ``(snap_dir, manifest)``, preferring the
    newest complete snapshot for a parent. Raises
    :class:`SnapshotError` when nothing complete exists."""
    if os.path.exists(os.path.join(path, MANIFEST)) or _snap_seq(
            os.path.basename(os.path.normpath(path))) is not None:
        return path, load_manifest(path)
    found = latest_snapshot(path)
    if found is None:
        raise SnapshotError(f"{path}: no complete snapshot to resume from")
    return found


def restore_job(path: str, worker) -> dict:
    """Roll the SPARSE tier back to a snapshot: verify it, then stream
    every PSD file into the live PS fleet (``worker.load`` →
    :func:`checkpoint.load_sharded`, which reshards by the dump-time
    ownership filter when the live routing/replica layout differs).
    Post-snapshot PS updates are wiped by design — the caller resumes
    the deterministic batch stream from the returned manifest's cursor
    and re-derives them exactly once. Returns the verified manifest;
    dense bytes stay on disk for :func:`dense_bytes`."""
    snap, manifest = resolve_snapshot(path)
    worker.load(snap)
    return manifest
