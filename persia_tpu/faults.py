"""Deterministic fault injection: every recovery path must be testable.

The fault surface of a hybrid multi-tier trainer grows with every
independently-scheduled tier (PAPERS.md, MPMD pipeline parallelism):
connections reset, PS replicas die mid-request, frames corrupt, one
shard runs slow. None of those paths can be trusted until they can be
*produced on demand*, so this module gives the RPC and PS tiers named
**injection sites** that a test, the chaos bench, or an operator can arm
with rules:

- ``delay:<sec>`` — sleep before proceeding (slow one shard)
- ``reset``      — raise ``ConnectionResetError`` (connection dies)
- ``drop``       — the site swallows the frame (peer hangs until timeout)
- ``corrupt``    — the site mangles the frame payload
- ``die[:rc]``   — ``os._exit`` the process (kill a PS mid-request)
- ``error[:msg]``— raise a generic application error

Rules are **seedable** (probabilistic rules draw from one
``random.Random``) and **deterministic by count** (``after=N`` skips the
first N matches, ``times=M`` fires at most M times), so a test that arms
"reset the 3rd lookup" reproduces exactly.

Control planes, in the ``__tags__``/``__trace__`` opt-in spirit:

- **env**: ``PERSIA_FAULTS="site:action[:arg][@k=v,...];..."`` armed at
  import (subprocess PS replicas inherit it), seeded by
  ``PERSIA_FAULTS_SEED``.
- **RPC**: a server started with ``PERSIA_FAULTS_RPC=1`` registers a
  ``__faults__`` method (rpc.py), so the chaos bench can re-arm a live
  PS subprocess mid-run (:func:`control`).
- **programmatic**: :func:`add` / :func:`reset_faults` for same-process
  tests.

Zero-overhead disabled path: call sites guard on the module global
``_active`` (one dict-load + attribute test, the same discipline as
``tracing._enabled``), so a production process that never arms a rule
pays a single predictable branch per site — the wire and the timing are
identical to a build without the harness.

Sites, by tier:

- ``rpc.client.send`` / ``rpc.server.recv`` — the transport plane
  (kwargs: addr/method);
- ``ps.lookup`` / ``ps.update`` — the PS data plane (kwargs: n, dim);
- ``ps.reshard.{begin,extract,install,drain,freeze,finish}`` — the live
  migration protocol's server side (kwargs vary per site; ``drain``
  carries ``frozen=`` so a rule can distinguish the replay rounds from
  the definitive cutover drain) — a PERSIA_FAULTS spec can kill or slow
  a donor/target at an exact protocol step;
- ``reshard.controller`` — fired by the ReshardController at each
  protocol transition (kwargs: ``state=`` copy/replay/freeze/cutover/
  drain plus donor= where applicable); a ``die`` rule here is the chaos
  matrix's controller SIGKILL;
- ``obs.http`` — the observability sidecar (scrape-resilience tests).

Example::

    faults.add("rpc.server.recv", "reset", after=2, method="lookup")
    faults.add("ps.lookup", "delay", arg=0.05, prob=0.5)
    faults.add("ps.reshard.extract", "die")          # kill donor in copy
    faults.add("reshard.controller", "die", state="freeze")
"""

import os
import random
import threading
import time
from typing import Dict, List, Optional

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger

_logger = get_default_logger(__name__)

# fast-path gate: call sites test this module global before building the
# fire() kwargs, so the disabled path costs one branch
_active = False


class InjectedFault(RuntimeError):
    """Raised by the ``error`` action (application-level injected
    failure; transport-level injections raise ConnectionResetError)."""


class FaultRule:
    """One armed injection: fires at ``site`` when the count/probability
    and the optional kwarg filters all match."""

    __slots__ = ("site", "action", "arg", "prob", "after", "times",
                 "match", "seen", "fired")

    def __init__(self, site: str, action: str, arg: Optional[float] = None,
                 prob: float = 1.0, after: int = 0,
                 times: Optional[int] = None,
                 match: Optional[Dict[str, str]] = None):
        if action not in ("delay", "reset", "drop", "corrupt", "die",
                          "error"):
            raise ValueError(f"unknown fault action {action!r}")
        self.site = site
        self.action = action
        self.arg = arg
        self.prob = float(prob)
        self.after = int(after)
        self.times = times if times is None else int(times)
        self.match = dict(match or {})
        self.seen = 0    # matching calls observed (incl. skipped)
        self.fired = 0   # times the action actually ran

    def describe(self) -> dict:
        return {"site": self.site, "action": self.action, "arg": self.arg,
                "prob": self.prob, "after": self.after, "times": self.times,
                "match": dict(self.match), "seen": self.seen,
                "fired": self.fired}


class FaultInjector:
    """Rule set + deterministic RNG. One process-wide instance
    (:func:`default_injector`); tests may build private ones."""

    def __init__(self, seed: Optional[int] = None):
        self._rules: List[FaultRule] = []
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def seed(self, seed: Optional[int]):
        with self._lock:
            self._rng = random.Random(seed)

    def add(self, site: str, action: str, arg: Optional[float] = None,
            prob: float = 1.0, after: int = 0, times: Optional[int] = None,
            **match) -> FaultRule:
        rule = FaultRule(site, action, arg, prob, after, times,
                         {k: str(v) for k, v in match.items()})
        with self._lock:
            self._rules.append(rule)
        return rule

    def clear(self):
        with self._lock:
            self._rules = []

    def rules(self) -> List[dict]:
        with self._lock:
            return [r.describe() for r in self._rules]

    def load_spec(self, spec: str):
        """Parse the compact rule grammar (the env/RPC control form):
        ``site:action[:arg][@key=value,...]`` rules joined by ``;``.
        Modifier keys ``p``/``after``/``times`` control firing; any
        other key is a kwarg filter (e.g. ``method=lookup``)."""
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            head, _, mods = part.partition("@")
            fields = head.split(":")
            if len(fields) < 2:
                raise ValueError(f"bad fault rule {part!r}")
            site, action = fields[0].strip(), fields[1].strip()
            arg = float(fields[2]) if len(fields) > 2 and fields[2] else None
            prob, after, times = 1.0, 0, None
            match: Dict[str, str] = {}
            if mods:
                for kv in mods.split(","):
                    k, _, v = kv.partition("=")
                    k = k.strip()
                    if k == "p":
                        prob = float(v)
                    elif k == "after":
                        after = int(v)
                    elif k == "times":
                        times = int(v)
                    else:
                        match[k] = v.strip()
            self.add(site, action, arg, prob, after, times, **match)

    def fire(self, site: str, **kw) -> Optional[str]:
        """Evaluate ``site`` against the armed rules. Executes ``delay``
        (sleeps), ``reset``/``error`` (raises) and ``die`` (exits)
        inline; returns ``"drop"``/``"corrupt"`` for the actions the
        call site must apply itself, or None when nothing fires."""
        rule = None
        with self._lock:
            for r in self._rules:
                if r.site != site:
                    continue
                if r.match and any(str(kw.get(k)) != v
                                   for k, v in r.match.items()):
                    continue
                r.seen += 1
                if r.seen <= r.after:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.prob < 1.0 and self._rng.random() >= r.prob:
                    continue
                r.fired += 1
                rule = r
                break
        if rule is None:
            return None
        _logger.warning("fault injected at %s: %s(%s) %s",
                        site, rule.action, rule.arg, kw)
        if rule.action == "delay":
            time.sleep(rule.arg or 0.0)
            return None
        if rule.action == "reset":
            raise ConnectionResetError(f"injected reset at {site}")
        if rule.action == "error":
            raise InjectedFault(f"injected error at {site}")
        if rule.action == "die":
            os._exit(int(rule.arg) if rule.arg is not None else 137)
        return rule.action  # "drop" | "corrupt"


_injector = FaultInjector()


def default_injector() -> FaultInjector:
    return _injector


def active() -> bool:
    return _active


def add(site: str, action: str, arg: Optional[float] = None,
        prob: float = 1.0, after: int = 0, times: Optional[int] = None,
        **match) -> FaultRule:
    """Arm a rule on the process injector and activate the harness."""
    global _active
    rule = _injector.add(site, action, arg, prob, after, times, **match)
    _active = True
    return rule


def install(spec: str, seed: Optional[int] = None):
    """Arm rules from the compact grammar (env / RPC control form)."""
    global _active
    if seed is not None:
        _injector.seed(seed)
    _injector.load_spec(spec)
    _active = bool(_injector.rules())


def reset_faults():
    """Disarm every rule and restore the zero-overhead disabled path."""
    global _active
    _injector.clear()
    _active = False


def fire(site: str, **kw) -> Optional[str]:
    """Hot-path entry: no-op unless the harness is armed. Call sites
    should pre-check ``faults._active`` to skip kwargs construction."""
    if not _active:
        return None
    return _injector.fire(site, **kw)


def corrupt_bytes(payload) -> bytes:
    """The ``corrupt`` action's canonical payload mangler: flip every
    bit of the first byte (a parse-visible, deterministic mutation)."""
    b = bytes(payload)
    if not b:
        return b
    return bytes([b[0] ^ 0xFF]) + b[1:]


def control(addr: str, spec: Optional[str] = None,
            seed: Optional[int] = None, clear: bool = False):
    """Re-arm the injector of a REMOTE process through its RPC server
    (the server must run with ``PERSIA_FAULTS_RPC=1``; rpc.py registers
    the ``__faults__`` method). The chaos bench uses this to slow one
    shard of a live PS subprocess without restarting it."""
    import msgpack

    from persia_tpu.rpc import RpcClient

    client = RpcClient(addr)
    try:
        client.call("__faults__", msgpack.packb(
            {"spec": spec, "seed": seed, "clear": clear},
            use_bin_type=True))
    finally:
        client.close()


def _handle_control(payload: bytes) -> bytes:
    """Server side of :func:`control` (registered by rpc.RpcServer when
    PERSIA_FAULTS_RPC=1)."""
    import msgpack

    req = msgpack.unpackb(payload, raw=False) if payload else {}
    if req.get("clear"):
        reset_faults()
    if req.get("spec"):
        install(req["spec"], seed=req.get("seed"))
    import json

    return json.dumps(_injector.rules()).encode()


# env arming at import: subprocess service replicas inherit the spec
# import_time_safe knobs: arming must happen at import so
# subprocess service replicas inherit the spec from their parent
_env_spec = knobs.get("PERSIA_FAULTS")
if _env_spec:
    try:
        install(_env_spec, seed=knobs.get("PERSIA_FAULTS_SEED"))
        _logger.warning("fault injection armed from PERSIA_FAULTS: %s",
                        _env_spec)
    except ValueError as e:
        _logger.error("bad PERSIA_FAULTS spec %r: %s", _env_spec, e)
