"""HTTP observability sidecar: /metrics, /healthz, /trace.

Every service process (PS replica, embedding worker, inference server)
can start one of these next to its RPC socket. It replaces the
push-gateway-only exposition (``MetricsRegistry.push_loop``) with a
standard Prometheus pull endpoint, adds a health probe that reports the
live internals a pager actually needs (queue depths, in-flight RPCs,
last-activity age), and exposes the tracing ring buffer so a stuck or
slow batch can be followed across tiers without restarting anything:

- ``GET /metrics``  — Prometheus text exposition (``registry.render()``)
- ``GET /healthz``  — JSON health document; merges the sidecar's own
  fields (service name, pid, uptime) with whatever the service's
  ``health_fn`` reports. Always HTTP 200 while the process can answer —
  liveness is the TCP accept; the *content* carries the judgement.
- ``GET /healthz?ready=1`` — READINESS variant: same document, but the
  status code follows the health doc's ``ready`` field — 503 when the
  service reports ``ready: false`` (a PS that is Loading/restoring, a
  worker whose PS tier is down). Liveness and readiness are different
  questions: a restarting replica is alive (do not kill it again) but
  not ready (do not route traffic to it) — supervisors probe the
  former, k8s readiness probes and load balancers the latter.
- ``GET /trace?n=K[&format=chrome|raw]`` — the most recent K spans from
  the process-local trace collector. ``chrome`` (default) is a
  Chrome-trace/Perfetto ``traceEvents`` JSON ready to load as-is;
  ``raw`` is ``{"spans": [...], "dropped_total": N}`` — the span-dict
  window the fleet monitor and ``bench.py --mode trace`` merge into one
  multi-process timeline, with the ring's eviction count so a consumer
  knows whether the window is complete.
- ``GET /flight`` — the flight-recorder snapshot: ONE JSON document
  bundling the health doc, the current metrics exposition, the recent
  span window, the armed fault rules, and the PERSIA_* environment.
  Supervisors poll it cheaply and keep the last copy, so when this
  process dies (SIGKILL keeps no last words) the postmortem bundle
  still has the final observable state.

Dependency-free (http.server), daemon-threaded, bound to an ephemeral
port by default so test stacks never collide.

Fault-injection site ``obs.http`` (:mod:`persia_tpu.faults`, kwarg
``path=`` filters per endpoint): ``delay`` stalls a response (a hung
sidecar), ``drop`` swallows the request (reply never comes), ``corrupt``
returns garbage bytes, ``error`` answers 500 — the scrape-resilience
tests and the fleet bench arm these to prove a bad target cannot wedge
the scrape loop.
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from persia_tpu import knobs
from persia_tpu import faults
from persia_tpu.logger import get_default_logger
from persia_tpu.version import __version__

_logger = get_default_logger(__name__)


class ObservabilityServer:
    """Sidecar HTTP server for one service process.

    ``health_fn`` returns a JSON-serializable dict of live service
    internals; it is called per /healthz request, so keep it cheap and
    lock-light. ``registry`` defaults to the process-wide metrics
    registry, ``collector`` to the process-wide trace collector.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry=None, collector=None,
                 health_fn: Optional[Callable[[], Dict]] = None,
                 service: str = "persia",
                 refresh_fn: Optional[Callable[[], None]] = None,
                 hotness_fn: Optional[Callable[[], Dict]] = None,
                 variants_fn: Optional[Callable[[], list]] = None):
        if registry is None:
            from persia_tpu.metrics import default_registry

            registry = default_registry()
        if collector is None:
            from persia_tpu.tracing import default_collector

            collector = default_collector()
        self.registry = registry
        self.collector = collector
        self.health_fn = health_fn
        # called before each /metrics render: services sync pull-style
        # gauges (e.g. the PS resident-bytes-per-shard series) so a
        # scrape always sees current values without paying per-mutation
        # gauge updates on the data path
        self.refresh_fn = refresh_fn
        # returns the service's workload-hotness snapshot
        # (persia_tpu.hotness format); None = this service has no
        # hotness source and /hotness answers the disabled marker
        self.hotness_fn = hotness_fn
        # returns the serving tier's variant topology (the
        # InferenceServer's per-variant doc list); None = not a
        # variant-serving process and GET /variants answers the
        # disabled marker
        self.variants_fn = variants_fn
        self.service = service
        self._t0 = time.monotonic()
        # meta-observability: the sidecar measures ITSELF, so a slow
        # /flight render or a wedged refresh_fn is visible in the same
        # exposition it serves (and in /fleet/metrics). Pre-built per
        # known path — unknown paths share "other" so a scanner cannot
        # mint unbounded label cardinality.
        self._t_request = {
            p: registry.histogram(
                "obs_http_request_sec", {"path": p},
                help_text="sidecar HTTP request wall time per endpoint")
            for p in ("/metrics", "/healthz", "/trace", "/flight",
                      "/hotness", "/variants", "other")}
        sidecar = self

        class Handler(BaseHTTPRequestHandler):
            # per-request stderr lines would swamp service logs
            def log_message(self, *a):  # noqa: D102
                pass

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                t_req0 = time.perf_counter()
                try:
                    self._handle_get()
                finally:
                    path = urlparse(self.path).path
                    hist = sidecar._t_request.get(
                        path, sidecar._t_request["other"])
                    hist.observe(time.perf_counter() - t_req0)

            def _handle_get(self):
                status = 200
                try:
                    url = urlparse(self.path)
                    if faults._active:
                        # chaos sites for scrape-resilience testing:
                        # delay = hung sidecar, drop = request swallowed
                        # (peer read times out), corrupt = garbage body,
                        # error -> 500 below, die = process exit
                        action = faults.fire("obs.http", path=url.path)
                        if action == "drop":
                            return  # no response; scraper must time out
                        if action == "corrupt":
                            body = b"\x00garbage not exposition\xff"
                            self.send_response(200)
                            self.send_header("Content-Length",
                                             str(len(body)))
                            self.end_headers()
                            self.wfile.write(body)
                            return
                    if url.path == "/metrics":
                        if sidecar.refresh_fn is not None:
                            try:
                                sidecar.refresh_fn()
                            except Exception:  # never fail a scrape
                                pass
                        body = sidecar.registry.render().encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif url.path == "/healthz":
                        doc = sidecar._health()
                        body = json.dumps(doc).encode()
                        ctype = "application/json"
                        q = parse_qs(url.query)
                        if (q.get("ready", ["0"])[0] not in ("", "0")
                                and doc.get("ready") is False):
                            # readiness probe: alive but must not
                            # receive traffic (Loading/restoring/
                            # unarmed) — the 503 makes supervisors and
                            # k8s probes not route to it mid-recovery
                            status = 503
                    elif url.path == "/trace":
                        q = parse_qs(url.query)
                        n = int(q.get("n", ["256"])[0])
                        fmt = q.get("format", ["chrome"])[0]
                        body = sidecar._trace(n, fmt).encode()
                        ctype = "application/json"
                    elif url.path == "/flight":
                        body = json.dumps(sidecar._flight()).encode()
                        ctype = "application/json"
                    elif url.path == "/hotness":
                        q = parse_qs(url.query)
                        full = q.get("full", ["0"])[0] not in ("", "0")
                        body = json.dumps(
                            sidecar._hotness(full)).encode()
                        ctype = "application/json"
                    elif url.path == "/variants":
                        body = json.dumps(sidecar._variants()).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "unknown path")
                        return
                except Exception as e:  # noqa: BLE001 — surfaced as 500
                    self.send_error(500, str(e))
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread: Optional[threading.Thread] = None

    def _health(self) -> Dict:
        doc = {
            "status": "ok",
            "service": self.service,
            "pid": os.getpid(),
            # version lets the fleet topology view spot replica skew
            # (a half-finished rollout mixes versions silently otherwise)
            "version": __version__,
            "uptime_sec": round(time.monotonic() - self._t0, 3),
        }
        if self.health_fn is not None:
            try:
                doc.update(self.health_fn())
            except Exception as e:  # health must never 500 on a bad probe
                doc["status"] = "degraded"
                doc["health_fn_error"] = repr(e)
        return doc

    def _trace(self, n: int, fmt: str) -> str:
        spans = self.collector.recent(n)
        dropped = self.collector.dropped_total
        if fmt == "raw":
            return json.dumps({"spans": [s.to_dict() for s in spans],
                               "dropped_total": dropped})
        from persia_tpu.tracing import chrome_trace

        doc = chrome_trace(spans)
        doc["otherData"] = {"spans_dropped_total": dropped}
        return json.dumps(doc)

    def _hotness(self, full: bool) -> Dict:
        """``GET /hotness``: the workload-hotness view. Default is the
        human-sized summary (per-table totals, fitted zipf alpha,
        coverage curve, hottest rows); ``?full=1`` returns the raw
        mergeable snapshot (top-K + b64 count-min + HLL) the fleet
        monitor's /fleet/hotness cross-shard merge consumes. Sketches
        unarmed (or no hotness source) answers the disabled marker, so
        a scraper needs no negotiation."""
        from persia_tpu import hotness as _hotness

        snap = (self.hotness_fn() if self.hotness_fn is not None
                else _hotness.disabled_snapshot())
        return snap if full else _hotness.summary_view(snap)

    def _variants(self) -> Dict:
        """``GET /variants``: the serving replica's live variant
        topology (names, weights, default, status, per-variant request
        counts) — what the operator's promote/rollback runbook and the
        fleet monitor's /fleet/variants merge read. Non-serving
        processes answer the disabled marker, so a scraper needs no
        negotiation."""
        if self.variants_fn is None:
            return {"enabled": False, "variants": []}
        return {"enabled": True, "variants": self.variants_fn()}

    FLIGHT_SPANS = 2048
    _FLIGHT_ENV_PREFIXES = ("PERSIA_", "REPLICA_", "JAX_")

    def _flight(self) -> Dict:
        """Flight-recorder snapshot: everything a postmortem needs, in
        one GET (supervisors poll this; a crashed process cannot be
        asked afterwards). Refreshes pull-style gauges like /metrics
        does, so the captured exposition is current."""
        if self.refresh_fn is not None:
            try:
                self.refresh_fn()
            except Exception:
                pass
        return {
            "t_wall": time.time(),
            "service": self.service,
            "pid": os.getpid(),
            "version": __version__,
            "health": self._health(),
            "metrics": self.registry.render(),
            "spans": [s.to_dict()
                      for s in self.collector.recent(self.FLIGHT_SPANS)],
            "spans_dropped_total": self.collector.dropped_total,
            "faults": faults.default_injector().rules(),
            "env": {k: v for k, v in os.environ.items()
                    if k.startswith(self._FLIGHT_ENV_PREFIXES)},
        }

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"obs-http-{self.addr}")
        self._thread.start()
        return self

    def stop(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def maybe_start(host: str, http_port: Optional[int], health_fn,
                service: Optional[str] = None, refresh_fn=None,
                hotness_fn=None, variants_fn=None):
    """The one sidecar-construction convention every service shares:
    ``None`` keeps the sidecar off (in-process test instances), any port
    number starts one (0 = ephemeral). Returns the started server or
    None."""
    if http_port is None:
        return None
    if service is None:
        from persia_tpu.tracing import service_name

        service = service_name()
    return ObservabilityServer(host, http_port, health_fn=health_fn,
                               service=service,
                               refresh_fn=refresh_fn,
                               hotness_fn=hotness_fn,
                               variants_fn=variants_fn).start()


def add_http_args(parser):
    """Shared --http-port / --http-addr-file argparse wiring for the
    service binaries (one place owns the 0/-1 convention and the
    PERSIA_HTTP_PORT default)."""
    parser.add_argument(
        "--http-port", type=int,
        default=knobs.get("PERSIA_HTTP_PORT"),
        help="observability sidecar port (/metrics /healthz /trace); "
             "0 = ephemeral, -1 = disabled")
    parser.add_argument(
        "--http-addr-file", default=None,
        help="write the sidecar's bound address here (port handoff for "
             "scrapers/benches, like --addr-file)")


def port_from_args(args) -> Optional[int]:
    """argparse value -> maybe_start port (the -1 = disabled rule)."""
    return None if args.http_port < 0 else args.http_port


def write_addr_file_from_args(sidecar, args):
    if args.http_addr_file and sidecar is not None:
        from persia_tpu.utils import write_addr_file

        write_addr_file(sidecar.addr, args.http_addr_file)
