"""Process-identity plumbing via environment variables.

Reference: persia/env.py (RANK/LOCAL_RANK/WORLD_SIZE for nn-workers,
REPLICA_INDEX/REPLICA_SIZE for every other role). Same contract here so
launchers and k8s manifests stay interchangeable.
"""

import os
from typing import Optional

from persia_tpu import knobs


def _int_env(name: str) -> Optional[int]:
    val = os.environ.get(name)
    return int(val) if val is not None else None


def skip_check_data() -> bool:
    """Whether PersiaBatch input validation is disabled.

    Read at CALL time via the knob registry. This used to be a module
    constant frozen at import — so `PERSIA_SKIP_CHECK_DATA=1` set by a
    launcher or test after the first `persia_tpu` import was silently
    ignored (persialint's knob-registry pass now rejects that pattern
    outright)."""
    return knobs.get("PERSIA_SKIP_CHECK_DATA")


def get_rank() -> int:
    """Global rank of this nn-worker (dense trainer) process."""
    rank = _int_env("RANK")
    if rank is None:
        raise RuntimeError("RANK environment variable not set")
    return rank


def get_local_rank() -> int:
    """Rank of this nn-worker on its host (selects the local TPU chip)."""
    local_rank = _int_env("LOCAL_RANK")
    if local_rank is None:
        raise RuntimeError("LOCAL_RANK environment variable not set")
    return local_rank


def get_world_size() -> int:
    """Total number of nn-worker processes."""
    world_size = _int_env("WORLD_SIZE")
    if world_size is None:
        raise RuntimeError("WORLD_SIZE environment variable not set")
    return world_size


def get_replica_index() -> int:
    """Replica index for data-loader / embedding-worker / parameter-server roles."""
    idx = _int_env("REPLICA_INDEX")
    if idx is None:
        raise RuntimeError("REPLICA_INDEX environment variable not set")
    return idx


def get_replica_size() -> int:
    """Replica count for data-loader / embedding-worker / parameter-server roles."""
    size = _int_env("REPLICA_SIZE")
    if size is None:
        raise RuntimeError("REPLICA_SIZE environment variable not set")
    return size


def get_coordinator_addr() -> str:
    """Address of the persia-coordinator control-plane service.

    Plays the role NATS plays in the reference (PERSIA_NATS_URL,
    rust/others/persia-nats-client/src/lib.rs:98-108).
    """
    return knobs.get("PERSIA_COORDINATOR_ADDR")


def get_metrics_gateway_addr() -> Optional[str]:
    return knobs.get("PERSIA_METRICS_GATEWAY_ADDR")
