"""Length-prefixed TCP RPC — the data plane.

Replaces the reference's hyper-HTTP RPC with speedy + lz4
(rust/others/persia-rpc/src/lib.rs:68-145). Wire format per message:

    u32 frame_len | u8 flags | msgpack envelope | raw payload

Envelope: ``[method, payload_len]`` for requests, ``[status, payload_len]``
for responses; the payload is raw bytes (numpy buffers travel uncopied
into the socket). flags bit 0 = payload is zstd-compressed (mirrors the
reference's ``_compressed`` method variants).

Numpy arrays are framed with :func:`pack_arrays` / :func:`unpack_arrays`.
The server runs a thread per connection (clients hold few, long-lived
connections — trainers and workers, not end users).
"""

import socket
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

import msgpack
import numpy as np

try:
    import zstandard

    # ZstdCompressor/ZstdDecompressor objects are NOT safe for concurrent
    # use from multiple threads (observed as wire corruption under the
    # pipelined trainer); keep one pair per thread.
    _zstd_local = threading.local()

    def _zstd_c() -> "zstandard.ZstdCompressor":
        c = getattr(_zstd_local, "c", None)
        if c is None:
            c = _zstd_local.c = zstandard.ZstdCompressor(level=3)
        return c

    def _zstd_d() -> "zstandard.ZstdDecompressor":
        d = getattr(_zstd_local, "d", None)
        if d is None:
            d = _zstd_local.d = zstandard.ZstdDecompressor()
        return d
except ImportError:  # pragma: no cover
    zstandard = None

_FLAG_COMPRESSED = 1
COMPRESS_THRESHOLD = 1 << 16


def _is_loopback(sock: socket.socket) -> bool:
    """Compression exists for DCN links; on loopback it is pure CPU
    overhead (embedding/sign payloads are near-incompressible: zstd-3
    spends ~35 ms per 7 MB for a 7% size win, measured on this host)."""
    try:
        peer = sock.getpeername()[0]
    except OSError:
        return False
    return peer.startswith("127.") or peer == "::1"


class RpcError(RuntimeError):
    pass


def pack_arrays(meta: dict, arrays: List[np.ndarray]) -> bytes:
    """Frame a small msgpack meta dict + a list of numpy arrays."""
    heads = []
    bufs = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        heads.append((str(a.dtype), list(a.shape)))
        bufs.append(a.tobytes())
    head = msgpack.packb({"m": meta, "a": heads}, use_bin_type=True)
    out = [struct.pack("<I", len(head)), head]
    out.extend(bufs)
    return b"".join(out)


def unpack_arrays(payload: bytes) -> Tuple[dict, List[np.ndarray]]:
    (head_len,) = struct.unpack_from("<I", payload, 0)
    head = msgpack.unpackb(payload[4 : 4 + head_len], raw=False)
    arrays = []
    pos = 4 + head_len
    for dtype, shape in head["a"]:
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(payload, dtype=dt, count=n, offset=pos).reshape(shape)
        pos += n * dt.itemsize
        arrays.append(arr)
    return head["m"], arrays


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("socket closed")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _send_msg(sock: socket.socket, envelope: list, payload: bytes,
              compress: bool):
    flags = 0
    if compress and zstandard is not None and len(payload) > COMPRESS_THRESHOLD:
        payload = _zstd_c().compress(payload)
        flags |= _FLAG_COMPRESSED
    env = msgpack.packb(envelope + [len(payload)], use_bin_type=True)
    # frame_len counts everything after the u32: flags+env_len fields (3
    # bytes, already consumed by the fixed 7-byte header read) + env + payload
    frame_len = 3 + len(env) + len(payload)
    header = struct.pack("<IBH", frame_len, flags, len(env))
    sock.sendall(header + env + payload)


def _recv_msg(sock: socket.socket) -> Tuple[list, bytes]:
    head = _recv_exact(sock, 7)
    frame_len, flags, env_len = struct.unpack("<IBH", head)
    body = _recv_exact(sock, frame_len - 3)
    env = msgpack.unpackb(body[:env_len], raw=False)
    payload = body[env_len:]
    if flags & _FLAG_COMPRESSED:
        if zstandard is None:  # pragma: no cover
            raise RpcError("compressed payload but zstandard unavailable")
        payload = _zstd_d().decompress(payload)
    return env, payload


class RpcServer:
    """Thread-per-connection RPC server with named handlers.

    Handlers take ``(payload: bytes) -> bytes`` and run concurrently;
    state they touch must be internally synchronized (the stores are).

    Requests carrying a request id (``RpcClient.call(dedup=True)``) are
    executed at most once: a bounded LRU of recently-served ids returns
    the cached response for retried deliveries, so non-idempotent methods
    (gradient updates, forward-buffer ingestion) survive client retries
    without double-applying.

    Caveat: the id cache is in-memory per server process. A retry that
    lands after the server restarted re-executes the method — dedup is
    at-most-once per server incarnation, NOT exactly-once across
    restarts. Restart recovery instead relies on the worker tiers'
    restore-on-failure + re-arm paths (worker.py / worker_server.cc).

    ``concurrent_streams > 1`` enables per-connection read-ahead: up to
    that many requests from ONE connection execute concurrently in a
    shared pool while responses still go out in request order (the wire
    has no response tags, so order is the correlation). Existing
    blocking clients never pipeline, so the default of 1 keeps the
    exact serial per-connection behavior; the inference server opts in
    so a single ``call_many`` client can keep its micro-batcher full.
    The handler contract is unchanged — handlers already must tolerate
    cross-connection concurrency, and read-ahead only adds same-
    connection concurrency under the same rule.
    """

    DEDUP_CACHE_SIZE = 8192
    # Byte bound too: lookup responses are multi-MB, and 8192 of those
    # would not be a cache, it would be a leak (matches the C++
    # DedupCache in native/src/net.h).
    DEDUP_CACHE_BYTES = 256 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 concurrent_streams: int = 1):
        from collections import OrderedDict

        self._concurrent_streams = max(1, int(concurrent_streams))
        self._stream_pool = None  # built lazily on the first connection
        self._stream_pool_lock = threading.Lock()
        self._handlers: Dict[str, Callable[[bytes], bytes]] = {}
        self._dedup: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._dedup_bytes = 0
        self._dedup_lock = threading.Lock()
        # ids whose FIRST execution is still running: a client whose
        # socket timed out re-sends the same id on a fresh connection
        # while the original handler is still working; the duplicate
        # must wait for that execution, not run concurrently (it would
        # observe half-updated state, e.g. a popped buffer entry)
        self._inflight: Dict[bytes, threading.Event] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.addr = f"{host}:{self._sock.getsockname()[1]}"
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._shutdown_cb: Optional[Callable[[], None]] = None

    def register(self, name: str, fn: Callable[[bytes], bytes]):
        self._handlers[name] = fn

    def on_shutdown(self, cb: Callable[[], None]):
        self._shutdown_cb = cb

    def serve_background(self):
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name=f"rpc-server-{self.addr}")
        self._thread.start()

    def serve_forever(self):
        self._running = True
        self._accept_loop()

    def _accept_loop(self):
        try:
            self._sock.settimeout(0.5)
        except OSError:
            return  # stop() closed the socket before the loop started
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _handle_one(self, method: str, payload: bytes,
                    req_id) -> Tuple[list, bytes]:
        """Run one request to a (envelope, body) response pair."""
        try:
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"no such method {method!r}")
            if req_id is None:
                result = handler(payload)
            else:
                result = self._execute_once(handler, payload, req_id)
            return ["ok"], result
        except BaseException as e:
            return ["err", f"{type(e).__name__}: {e}"], b""

    def _serve_conn_concurrent(self, conn: socket.socket):
        """Read-ahead variant: this thread reads requests and submits
        them to the shared pool; a writer thread sends the results back
        strictly in request order. The bounded pending queue caps
        read-ahead at ``concurrent_streams`` so a fast sender cannot
        pile unbounded work into the pool."""
        import queue as _queue
        from concurrent.futures import ThreadPoolExecutor

        with self._stream_pool_lock:
            if not self._running:
                # stop() already ran: creating a pool here would leak an
                # executor nothing ever shuts down
                try:
                    conn.close()
                except OSError:
                    pass
                return
            if self._stream_pool is None:
                self._stream_pool = ThreadPoolExecutor(
                    max_workers=max(32, self._concurrent_streams),
                    thread_name_prefix="rpc-stream")
            pool = self._stream_pool
        compress = not _is_loopback(conn)
        pending: "_queue.Queue" = _queue.Queue(
            maxsize=self._concurrent_streams)
        conn_dead = threading.Event()

        def writer():
            while True:
                item = pending.get()
                if item is None:
                    return
                if item == "shutdown":
                    try:
                        _send_msg(conn, ["ok"], b"", False)
                    except OSError:
                        pass
                    self.stop()
                    if self._shutdown_cb is not None:
                        self._shutdown_cb()
                    return
                env, body = item.result()
                if conn_dead.is_set():
                    continue  # drain remaining futures without sending
                try:
                    _send_msg(conn, env, body,
                              compress if env[0] == "ok" else False)
                except OSError:
                    conn_dead.set()

        wt = threading.Thread(target=writer, daemon=True,
                              name="rpc-stream-writer")
        wt.start()
        try:
            with conn:
                while self._running and not conn_dead.is_set():
                    try:
                        env, payload = _recv_msg(conn)
                    except (ConnectionError, OSError):
                        break
                    method = env[0]
                    if method == "__shutdown__":
                        pending.put("shutdown")
                        wt.join()
                        return
                    req_id = env[1] if len(env) >= 3 else None
                    try:
                        fut = pool.submit(
                            self._handle_one, method, payload, req_id)
                    except RuntimeError:
                        # stop() shut the pool down between recv and
                        # submit; the server is closing anyway
                        break
                    pending.put(fut)
        finally:
            pending.put(None)

    def _serve_conn(self, conn: socket.socket):
        if self._concurrent_streams > 1:
            self._serve_conn_concurrent(conn)
            return
        compress = not _is_loopback(conn)
        with conn:
            while self._running:
                try:
                    env, payload = _recv_msg(conn)
                except (ConnectionError, OSError):
                    return
                method = env[0]
                req_id = env[1] if len(env) >= 3 else None
                try:
                    if method == "__shutdown__":
                        _send_msg(conn, ["ok"], b"", False)
                        self.stop()
                        if self._shutdown_cb is not None:
                            self._shutdown_cb()
                        return
                    handler = self._handlers.get(method)
                    if handler is None:
                        raise RpcError(f"no such method {method!r}")
                    if req_id is None:
                        result = handler(payload)
                    else:
                        result = self._execute_once(handler, payload, req_id)
                    _send_msg(conn, ["ok"], result, compress)
                except BaseException as e:
                    try:
                        _send_msg(conn, ["err", f"{type(e).__name__}: {e}"],
                                  b"", False)
                    except OSError:
                        return

    def _execute_once(self, handler, payload: bytes, req_id: bytes) -> bytes:
        """At-most-once execution for an id, including the concurrent
        window: a duplicate delivery waits for the in-flight original
        and returns its cached result. If the original ERRORED, nothing
        is cached and the duplicate executes itself — safe, because the
        failed execution restored any state it consumed."""
        while True:
            with self._dedup_lock:
                cached = self._dedup.get(req_id)
                if cached is not None:
                    return cached
                ev = self._inflight.get(req_id)
                if ev is None:
                    self._inflight[req_id] = mine = threading.Event()
                    break
            ev.wait(timeout=600.0)
        try:
            result = handler(payload)
        except BaseException:
            with self._dedup_lock:
                self._inflight.pop(req_id, None)
            mine.set()
            raise
        with self._dedup_lock:
            self._dedup[req_id] = result
            self._dedup_bytes += len(result)
            while len(self._dedup) > self.DEDUP_CACHE_SIZE or (
                self._dedup_bytes > self.DEDUP_CACHE_BYTES
                and len(self._dedup) > 1
            ):
                _, old = self._dedup.popitem(last=False)
                self._dedup_bytes -= len(old)
            self._inflight.pop(req_id, None)
        mine.set()
        return result

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
        with self._stream_pool_lock:
            pool, self._stream_pool = self._stream_pool, None
        if pool is not None:
            pool.shutdown(wait=False)


class RpcClient:
    """Blocking client with one pooled connection per thread.

    Transient connection failures retry with backoff (the reference's
    forward workers block on wait_for_serving until servers recover,
    forward.rs:708-715; here the recovery wait lives in the client so
    every caller gets it). Application-level errors (RpcError) never
    retry. At-least-once semantics: a request may be re-sent if the
    connection died after the server processed it.
    """

    def __init__(self, addr: str, timeout: float = 60.0,
                 max_retries: int = 5, retry_backoff: float = 0.2):
        self.addr = addr
        host, port = addr.rsplit(":", 1)
        self._target = (host, int(port))
        self.timeout = timeout
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self._local = threading.local()
        # one pooled conn per calling thread, keyed by the Thread object,
        # so close() (and GC via __del__) can release every socket
        # deterministically and conns of exited threads are swept instead
        # of leaking fds for the client's lifetime
        self._conn_by_thread: Dict[threading.Thread, socket.socket] = {}
        self._conns_lock = threading.Lock()

    def _dial(self) -> socket.socket:
        conn = socket.create_connection(self._target, timeout=self.timeout)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._local.compress = not _is_loopback(conn)
        me = threading.current_thread()
        dead = []
        with self._conns_lock:
            self._conn_by_thread[me] = conn
            for t in list(self._conn_by_thread):
                if not t.is_alive() and t is not me:
                    dead.append(self._conn_by_thread.pop(t))
        for c in dead:
            try:
                c.close()
            except OSError:
                pass
        return conn

    def call(self, method: str, payload: bytes = b"",
             dedup: bool = False) -> bytes:
        """``dedup=True`` attaches a per-request id that the server uses
        to execute the request at most once (RpcServer's LRU of served
        ids): required for non-idempotent methods (gradient updates,
        forward-buffer ingestion), where a blind re-send after an
        ambiguous connection death would double-apply the update or leak
        an orphaned forward-buffer entry. With the id attached, retries
        are safe, so every call keeps the full retry-with-backoff
        resilience (the reference's forward workers block on
        wait_for_serving until servers recover, forward.rs:708-715).

        The server's id cache does not survive its restart: a retry
        that lands on a restarted process re-executes the method (see
        RpcServer docstring)."""
        import os
        import time

        envelope: list = [method]
        if dedup:
            envelope.append(os.urandom(12))
        delay = self.retry_backoff
        attempts_left = self.max_retries
        while True:
            conn = getattr(self._local, "conn", None)
            fresh = conn is None
            if fresh:
                try:
                    conn = self._local.conn = self._dial()
                except (ConnectionError, OSError):
                    if attempts_left <= 0:
                        raise
                    attempts_left -= 1
                    time.sleep(delay)
                    delay = min(delay * 2, 5.0)
                    continue
            try:
                _send_msg(conn, envelope, payload,
                          getattr(self._local, "compress", True))
                env, result = _recv_msg(conn)
                break
            except (ConnectionError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                with self._conns_lock:
                    me = threading.current_thread()
                    if self._conn_by_thread.get(me) is conn:
                        del self._conn_by_thread[me]
                self._local.conn = None
                if not fresh:
                    continue  # stale pooled socket: redial once, no sleep
                if attempts_left <= 0:
                    raise
                attempts_left -= 1
                time.sleep(delay)
                delay = min(delay * 2, 5.0)
        if env[0] != "ok":
            raise RpcError(f"{self.addr} {method}: {env[1]}")
        return result

    def call_many(self, method: str, payloads: List[bytes],
                  window: int = 16) -> List[bytes]:
        """Pipelined calls on this thread's pooled connection: up to
        ``window`` requests are on the wire before the first response is
        read (responses arrive in request order — the framing has no
        tags). Against a ``concurrent_streams`` server the requests
        execute concurrently; against a default server they execute
        serially but still save the per-call round-trip gaps.

        The window bounds the responses the server may have to buffer
        while we are still sending (kernel-socket-buffer deadlock
        guard). No retry: a connection failure mid-pipeline is raised
        as-is because the completed prefix is ambiguous — use only for
        idempotent methods (predict, lookups). An APPLICATION error is
        raised only after every in-flight response has been read, so
        the pooled connection stays in sync for subsequent calls."""
        if not payloads:
            return []
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._local.conn = self._dial()
        compress = getattr(self._local, "compress", True)
        results: List[bytes] = []
        first_err: Optional[str] = None
        try:
            i_send = 0
            while len(results) < len(payloads):
                while (i_send < len(payloads)
                       and i_send - len(results) < window):
                    _send_msg(conn, [method], payloads[i_send], compress)
                    i_send += 1
                env, result = _recv_msg(conn)
                if env[0] != "ok":
                    # keep draining: an unread tail would desynchronize
                    # the NEXT call's request/response pairing
                    if first_err is None:
                        first_err = f"{self.addr} {method}: {env[1]}"
                    result = b""
                results.append(result)
        except (ConnectionError, OSError):
            try:
                conn.close()
            except OSError:
                pass
            with self._conns_lock:
                me = threading.current_thread()
                if self._conn_by_thread.get(me) is conn:
                    del self._conn_by_thread[me]
            self._local.conn = None
            raise
        if first_err is not None:
            raise RpcError(first_err)
        return results

    def call_msg(self, method: str, **kwargs) -> dict:
        """msgpack-dict convenience call."""
        result = self.call(method, msgpack.packb(kwargs, use_bin_type=True))
        return msgpack.unpackb(result, raw=False) if result else {}

    def shutdown_server(self):
        try:
            self.call("__shutdown__")
        except (RpcError, ConnectionError, OSError):
            pass

    def close(self):
        """Close every pooled connection (all threads). Safe to call from
        teardown while worker threads are gone; a racing caller simply
        redials."""
        with self._conns_lock:
            conns = list(self._conn_by_thread.values())
            self._conn_by_thread.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._local.conn = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
