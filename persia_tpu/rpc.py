"""Length-prefixed TCP RPC — the data plane.

Replaces the reference's hyper-HTTP RPC with speedy + lz4
(rust/others/persia-rpc/src/lib.rs:68-145). Wire format per message:

    u32 frame_len | u8 flags | u16 env_len | [u32 tag] | envelope | payload

Envelope: ``[method, payload_len]`` for requests, ``[status, payload_len]``
for responses; the payload is raw bytes (numpy buffers travel uncopied
into the socket). flags bit 0 = payload is zstd-compressed (mirrors the
reference's ``_compressed`` method variants); flags bit 1 = the frame
carries a u32 sequence **tag** between the fixed header and the envelope.

Tags make responses self-describing — a response carries the tag of the
request it answers — which lets the server complete requests
**out of order** (slow shard no longer head-of-line blocks fast ones)
and lets the client multiplex many requests on one connection
(:meth:`RpcClient.call_future`). Tagged framing is negotiated per
connection: a client that wants it sends a ``__tags__`` request first;
servers that support tags answer ``ok``, legacy peers (e.g. the C++
``ps_server``) answer "no such method" and the connection stays
untagged — fully backward compatible in both directions.

Trace context (:mod:`persia_tpu.tracing`) rides the envelope the same
negotiated way: a client whose process has tracing ENABLED probes
``__trace__`` at dial time; when the server acks, requests carry an
extra ``[trace_id, parent_span_id]`` envelope slot and the server runs
each handler under a child span — one ``trace_id`` then links a trainer
step to its worker stages to the per-shard PS handlers, across both the
serial and the out-of-order dispatch paths. Legacy peers answer the
probe "no such method" and never see the extra slot; with tracing
disabled (the default) the probe itself is never sent, so the wire is
byte-identical to the untraced protocol.

Two more optional envelope slots follow the same negotiate-down rule:
a client that wants **deadline propagation** probes ``__deadline__`` at
dial time; when the server acks, each call may carry its remaining time
budget (seconds) as a fourth envelope slot, and the server SHEDS work
whose budget expired before dispatch (typed back to the caller as
:class:`RpcDeadlineExceeded`). Neither probe nor slot exists when the
feature is off — byte-identical legacy wire.

Failures are typed: transport-level loss surfaces as
:class:`RpcTimeout` / :class:`RpcConnectionLost` (subclassing the
builtin ``TimeoutError`` / ``ConnectionError`` so existing catch
clauses keep working), application errors stay plain :class:`RpcError`,
and a :class:`CircuitBreaker` (per-replica, used by ``PsClient``) fails
fast with :class:`RpcCircuitOpen` instead of re-walking the retry
ladder against a dead peer. Deterministic fault injection
(:mod:`persia_tpu.faults`) hooks the client send and server receive
paths behind a zero-overhead ``_active`` guard.

Numpy arrays are framed with :func:`pack_arrays` / :func:`unpack_arrays`.
:func:`pack_arrays_sg` is the zero-copy twin: it returns a buffer LIST
that ``sendmsg``/writev hands to the kernel without the ``tobytes()``
concatenation copies, and the receive side reads each frame with
``recv_into`` into one preallocated buffer so ``unpack_arrays`` returns
views — bytes on the wire are bit-identical either way.

The server runs a thread per connection (clients hold few, long-lived
connections — trainers and workers, not end users).
"""

import os
import random
import select
import socket
import struct
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple, Union

import msgpack
import numpy as np

from persia_tpu import faults, knobs, tracing

try:
    import zstandard

    # ZstdCompressor/ZstdDecompressor objects are NOT safe for concurrent
    # use from multiple threads (observed as wire corruption under the
    # pipelined trainer); keep one pair per thread.
    _zstd_local = threading.local()

    def _zstd_c() -> "zstandard.ZstdCompressor":
        c = getattr(_zstd_local, "c", None)
        if c is None:
            c = _zstd_local.c = zstandard.ZstdCompressor(level=3)
        return c

    def _zstd_d() -> "zstandard.ZstdDecompressor":
        d = getattr(_zstd_local, "d", None)
        if d is None:
            d = _zstd_local.d = zstandard.ZstdDecompressor()
        return d
except ImportError:  # pragma: no cover
    zstandard = None

_FLAG_COMPRESSED = 1
_FLAG_TAGGED = 2
# request hint: more requests may already be in flight on this
# connection — the dispatch-pool server must NOT execute inline on the
# reader thread (a slow handler would head-of-line block the others)
_FLAG_PIPELINED = 4
# payload is block-compressed with the codec negotiated by the
# ``__codec__`` probe (lz4 when both sides have it, zlib fallback); the
# first payload byte names the algorithm, so the frame is self-decoding
# — but the flag is only ever SENT on a connection that negotiated it,
# so legacy peers never see a frame they cannot parse
_FLAG_BLOCK = 8
COMPRESS_THRESHOLD = 1 << 16
BLOCK_THRESHOLD = 1 << 16

# --- negotiated block compression (the __codec__ wire) -------------------
# zstd (above) predates the codec negotiation and stays as-is where the
# library exists; this path is the lz4-or-zlib block codec from the
# reference's lz4-compressed RPC, made safe by negotiation instead of by
# assuming both ends were built alike.
_BLOCK_LZ4 = 1
_BLOCK_ZLIB = 2

try:
    import lz4.frame as _lz4_frame
except ImportError:  # pragma: no cover — zlib fallback always exists
    _lz4_frame = None

import zlib as _zlib

# force block compression even on loopback (tests + bench exercise the
# codec path without a real DCN link; normal loopback traffic skips it,
# same rule as the zstd path — pure CPU tax there). Frozen at import on
# purpose (registered import_time_safe): this sits on the per-frame
# hot path.
_FORCE_BLOCK = knobs.get("PERSIA_RPC_FORCE_BLOCK")

# The server-side refusal table for dunder-named wire extensions: every
# ``__x__`` method a client may probe MUST be declared here, and
# :meth:`RpcServer.register` rejects any dunder handler that is not —
# an undeclared extension cannot ship by accident. ``envelope`` kind ==
# an opt-in negotiated envelope slot whose OFF wire must stay
# byte-identical to the legacy protocol (pinned by served-request-count
# tests); ``control`` kind == a plain opt-in control method with no
# envelope slot. tools/persialint's wire-protocol pass cross-checks
# every probe literal in the tree against this table and against the
# pinning tests in tests/.
ENVELOPE_EXTENSIONS: Dict[str, Dict[str, str]] = {
    "__tags__": {
        "kind": "envelope",
        "doc": "tagged frames: u32 request ids, out-of-order responses",
    },
    "__trace__": {
        "kind": "envelope",
        "doc": "distributed-tracing context rides an extra envelope slot",
    },
    "__deadline__": {
        "kind": "envelope",
        "doc": "per-call deadline propagation; servers shed expired work",
    },
    "__codec__": {
        "kind": "envelope",
        "doc": "negotiated payload codec: block compression + half-"
               "precision rows",
    },
    "__routing__": {
        "kind": "envelope",
        "doc": "routing-epoch rider: client stamps its slot-table epoch "
               "on request meta so a resharding server fast-rejects "
               "stale-epoch writes; opt-in via PERSIA_ROUTING_WIRE",
    },
    "__faults__": {
        "kind": "control",
        "doc": "remote fault-injection control, opt-in via "
               "PERSIA_FAULTS_RPC=1",
    },
    "__shutdown__": {
        "kind": "control",
        "doc": "cooperative server stop (handled inline by the serve "
               "loops, never dispatched to a handler)",
    },
}


def block_codecs() -> List[str]:
    """Locally supported block codecs, preference order first."""
    return (["lz4", "zlib"] if _lz4_frame is not None else ["zlib"])


def _block_compress(data: bytes, algo: str) -> bytes:
    if algo == "lz4" and _lz4_frame is not None:
        return bytes((_BLOCK_LZ4,)) + _lz4_frame.compress(data)
    return bytes((_BLOCK_ZLIB,)) + _zlib.compress(data, 1)


def _block_decompress(payload) -> bytes:
    buf = payload if isinstance(payload, (bytes, bytearray)) \
        else bytes(payload)
    algo, body = buf[0], buf[1:]
    if algo == _BLOCK_LZ4:
        if _lz4_frame is None:  # pragma: no cover — negotiation prevents
            raise RpcError("lz4 block payload but lz4 unavailable")
        return _lz4_frame.decompress(body)
    if algo == _BLOCK_ZLIB:
        return _zlib.decompress(body)
    raise RpcError(f"unknown block codec id {algo}")

# A payload is bytes, OR a buffer list from pack_arrays_sg (scatter-
# gather: written with one sendmsg instead of concatenated first).
Payload = Union[bytes, bytearray, memoryview, list, tuple]


def _is_loopback(sock: socket.socket) -> bool:
    """Compression exists for DCN links; on loopback it is pure CPU
    overhead (embedding/sign payloads are near-incompressible: zstd-3
    spends ~35 ms per 7 MB for a 7% size win, measured on this host)."""
    try:
        peer = sock.getpeername()[0]
    except OSError:
        return False
    if peer.startswith("::ffff:"):
        # IPv4-mapped IPv6 (dual-stack listeners hand these out for
        # plain 127.0.0.1 connects); strip the mapping prefix so local
        # traffic is not mis-billed the zstd CPU
        peer = peer[7:]
    return peer.startswith("127.") or peer == "::1"


class RpcError(RuntimeError):
    """Base of the typed RPC failure hierarchy. Application-level errors
    (a handler raised) are plain ``RpcError``; transport-level failures
    surface as the subclasses below, which ALSO subclass the builtin
    exception callers historically caught (``ConnectionError`` /
    ``TimeoutError``) — existing ``except (RpcError, ConnectionError,
    OSError)`` clauses keep working, while new callers can distinguish
    retryable transport loss from fatal application errors."""


class RpcTimeout(RpcError, TimeoutError):
    """The socket timed out waiting for the peer (``socket.timeout`` is
    a ``TimeoutError``/``OSError``, so legacy catch clauses still
    match). Retryable: the request MAY have executed."""


class RpcConnectionLost(RpcError, ConnectionError):
    """The connection died mid-call (reset, closed, refused). Retryable
    for idempotent/dedup'd methods; the completed state of an in-flight
    request is ambiguous."""


class RpcDeadlineExceeded(RpcError):
    """The server shed the request because its propagated deadline had
    already passed at dispatch time (or a deadline-aware layer failed
    it fast). NOT retryable with the same deadline — the time budget is
    spent; callers degrade instead (serving zero-vector fallback)."""


class RpcCircuitOpen(RpcConnectionLost):
    """Fail-fast refusal: the replica's :class:`CircuitBreaker` is open
    after consecutive transport failures. No wire traffic happened; a
    background probe re-closes the breaker when the replica returns."""


# server-side shed marker: the client maps this envelope prefix back to
# the typed exception (the err slot carries "ExcName: message" strings)
_DEADLINE_ERR = "RpcDeadlineExceeded"

# err-envelope exception names that re-type on the client. A handler in
# a MIDDLE tier (worker) that loses ITS downstream hop (PS) reports
# "ConnectionResetError: ..." through a perfectly healthy connection —
# without this mapping the caller sees a plain RpcError and every
# transport-aware layer above (serving degradation, pipeline
# lost-update accounting) misclassifies a nested outage as an
# application bug. Plain OSError is deliberately NOT mapped: it carries
# genuine application failures (filesystem errors in dump/load paths)
# that must surface, not be silently retried/dropped as transport loss.
_REMOTE_LOST = frozenset((
    "RpcConnectionLost", "RpcCircuitOpen", "ConnectionError",
    "ConnectionResetError", "ConnectionRefusedError",
    "ConnectionAbortedError", "BrokenPipeError",
))
_REMOTE_TIMEOUT = frozenset(("RpcTimeout", "TimeoutError", "timeout"))


def _typed_call_error(addr: str, method: str, msg: str) -> RpcError:
    msg = str(msg)
    name = msg.split(":", 1)[0]
    full = f"{addr} {method}: {msg}"
    if name == _DEADLINE_ERR:
        return RpcDeadlineExceeded(full)
    if name in _REMOTE_LOST:
        return RpcConnectionLost(full)
    if name in _REMOTE_TIMEOUT:
        return RpcTimeout(full)
    return RpcError(full)


def _typed_transport_error(e: BaseException, addr: str,
                           method: str) -> RpcError:
    """Wrap a raw OSError/socket.timeout in the typed hierarchy (pass
    already-typed errors through untouched)."""
    if isinstance(e, RpcError):
        return e
    if isinstance(e, socket.timeout):
        return RpcTimeout(f"{addr} {method}: {e!r}")
    return RpcConnectionLost(f"{addr} {method}: {e!r}")


class CircuitBreaker:
    """Per-replica fail-fast gate: CLOSED -> (``threshold`` consecutive
    transport failures) -> OPEN, where :meth:`allow` refuses instantly
    (callers raise :class:`RpcCircuitOpen` without touching the wire,
    so a dead PS replica costs microseconds instead of a full
    retry-with-backoff ladder per call). From OPEN, a background probe
    (``probe`` callable, e.g. a bare TCP connect) — or the ``cooldown``
    clock when no probe is given — moves the breaker to HALF_OPEN:
    exactly ONE trial call is let through; its success closes the
    breaker, its failure re-opens it. Application errors (plain
    RpcError) never trip the breaker — only transport-level loss does.
    """

    def __init__(self, threshold: int = 5, cooldown: float = 1.0,
                 probe: Optional[Callable[[], bool]] = None,
                 probe_interval: float = 0.25):
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self.probe_interval = float(probe_interval)
        self._probe = probe
        self._lock = threading.Lock()
        self._state = "closed"
        self._fails = 0
        self._opened_at = 0.0
        self._trial_inflight = False
        self._probe_thread: Optional[threading.Thread] = None

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """True when a call may proceed (closed, or the half-open
        trial slot). False == fail fast, no wire traffic."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if _time.monotonic() - self._opened_at < self.cooldown:
                    return False
                self._state = "half_open"
                self._trial_inflight = False
            # half_open: one trial call at a time
            if self._trial_inflight:
                return False
            self._trial_inflight = True
            return True

    def record_success(self):
        with self._lock:
            self._state = "closed"
            self._fails = 0
            self._trial_inflight = False

    def record_failure(self):
        with self._lock:
            self._fails += 1
            if self._state == "half_open" or self._fails >= self.threshold:
                self._open_locked()

    def _open_locked(self):
        self._state = "open"
        self._opened_at = _time.monotonic()
        self._trial_inflight = False
        if self._probe is not None and (
            self._probe_thread is None or not self._probe_thread.is_alive()
        ):
            self._probe_thread = threading.Thread(
                target=self._probe_loop, daemon=True,
                name="circuit-breaker-probe")
            self._probe_thread.start()

    def _probe_loop(self):
        """Background recovery watch: while the breaker is open, poll
        the probe; the first success arms the half-open trial slot
        immediately (no need to wait out the cooldown). The inter-probe
        sleep is decorrelated-jittered: after a supervised PS restart,
        every client in the fleet opens its breaker at the same instant,
        and a fixed cadence would land all N recovery probes (and the
        trial calls they arm) on the reborn replica in synchronized
        waves."""
        delay = self.probe_interval
        while True:
            with self._lock:
                if self._state != "open":
                    return
            try:
                ok = bool(self._probe())
            except Exception:
                ok = False
            if ok:
                with self._lock:
                    if self._state == "open":
                        self._state = "half_open"
                        self._trial_inflight = False
                return
            delay = decorrelated_jitter(self.probe_interval,
                                        8 * self.probe_interval, delay)
            self._sleep(delay)

    # injectable for fake-clock tests
    _sleep = staticmethod(_time.sleep)


def decorrelated_jitter(base: float, cap: float, prev: float,
                        rand: Optional[Callable[[], float]] = None
                        ) -> float:
    """Next backoff delay, AWS-style "decorrelated jitter":
    ``min(cap, uniform(base, max(base, prev * 3)))``. Unlike plain
    exponential backoff (deterministic, so N clients that failed
    together retry together, forever), each client's delay is drawn
    from a widening window — reconnect storms de-synchronize within a
    round or two. ``rand`` is injectable for deterministic tests."""
    r = (rand or random.random)()
    hi = max(float(base), float(prev) * 3.0)
    return min(float(cap), float(base) + r * (hi - float(base)))


class RetryBudget:
    """Per-client token bucket bounding transport retries: ``capacity``
    tokens burst, refilled at ``refill_per_sec``. Each retry SLEEP
    spends one token; an empty bucket stops the ladder immediately
    (the call surfaces its transport error instead of sleeping). The
    point is storm control — during a long PS outage, N workers x M
    threads x unbounded ladders otherwise wake in lockstep and hammer
    the reborn replica; with a budget, each client's retry pressure is
    capped at ``refill_per_sec`` regardless of caller count. The
    defaults are generous (single-call ladders never notice them).
    ``clock`` is injectable for fake-clock tests. Thread-safe."""

    def __init__(self, capacity: float = 64.0,
                 refill_per_sec: float = 8.0,
                 clock: Optional[Callable[[], float]] = None):
        self.capacity = float(capacity)
        self.refill_per_sec = float(refill_per_sec)
        self._clock = clock or _time.monotonic
        self._tokens = self.capacity
        self._stamp = self._clock()
        self._lock = threading.Lock()

    def _refill_locked(self):
        now = self._clock()
        self._tokens = min(self.capacity, self._tokens
                           + (now - self._stamp) * self.refill_per_sec)
        self._stamp = now

    def acquire(self, n: float = 1.0) -> bool:
        """Spend ``n`` tokens; False == budget exhausted, stop retrying."""
        with self._lock:
            self._refill_locked()
            if self._tokens < n:
                return False
            self._tokens -= n
            return True

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens


def tcp_probe(addr: str, timeout: float = 1.0) -> Callable[[], bool]:
    """Cheapest liveness probe for a breaker: does the address accept a
    TCP connection. (Readiness — checkpoint restored, optimizer armed —
    is the trial call's job; the probe only gates when to bother.)"""
    host, port = addr.rsplit(":", 1)

    def probe() -> bool:
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=timeout):
                return True
        except OSError:
            return False

    return probe


def pack_arrays(meta: dict, arrays: List[np.ndarray]) -> bytes:
    """Frame a small msgpack meta dict + a list of numpy arrays."""
    heads = []
    bufs = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        heads.append((str(a.dtype), list(a.shape)))
        bufs.append(a.tobytes())
    head = msgpack.packb({"m": meta, "a": heads}, use_bin_type=True)
    out = [struct.pack("<I", len(head)), head]
    out.extend(bufs)
    return b"".join(out)


def pack_arrays_sg(meta: dict, arrays: List[np.ndarray]) -> list:
    """Zero-copy twin of :func:`pack_arrays`: returns a buffer list
    ``[prefix, *array buffers]`` that :func:`_send_msg` writes with one
    ``sendmsg`` — the array bytes go socketward without the
    ``tobytes()``/join concatenation copies. The byte stream is
    bit-identical to ``pack_arrays`` output (``unpack_arrays`` cannot
    tell them apart). The caller must not mutate the arrays until the
    send completes (all in-repo callers send synchronously)."""
    heads = []
    bufs = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        heads.append((str(a.dtype), list(a.shape)))
        bufs.append(memoryview(a).cast("B"))
    head = msgpack.packb({"m": meta, "a": heads}, use_bin_type=True)
    return [struct.pack("<I", len(head)) + head] + bufs


def unpack_arrays(payload) -> Tuple[dict, List[np.ndarray]]:
    """Parse a pack_arrays/pack_arrays_sg byte stream. Accepts any
    bytes-like object; the returned arrays are VIEWS into it (the
    receive path hands in the per-frame buffer, so no copy happens
    between socket and numpy)."""
    (head_len,) = struct.unpack_from("<I", payload, 0)
    head = msgpack.unpackb(payload[4 : 4 + head_len], raw=False)
    arrays = []
    pos = 4 + head_len
    for dtype, shape in head["a"]:
        dt = np.dtype(dtype)
        n = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(payload, dtype=dt, count=n, offset=pos).reshape(shape)
        pos += n * dt.itemsize
        arrays.append(arr)
    return head["m"], arrays


def _payload_nbytes(payload: Payload) -> int:
    if isinstance(payload, (bytes, bytearray)):
        return len(payload)
    if isinstance(payload, memoryview):
        return payload.nbytes
    return sum(_payload_nbytes(b) for b in payload)


def _payload_bytes(payload: Payload) -> bytes:
    """Flatten a payload (possibly a buffer list) to one bytes object —
    only needed on the compression path, which copies anyway."""
    if isinstance(payload, bytes):
        return payload
    if isinstance(payload, (bytearray, memoryview)):
        return bytes(payload)
    return b"".join(b if isinstance(b, bytes) else bytes(b) for b in payload)


def _as_byte_view(b) -> memoryview:
    mv = b if isinstance(b, memoryview) else memoryview(b)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return mv


def _sendmsg_all(sock: socket.socket, bufs: List[memoryview]):
    """Vectored send of the whole buffer list (handles short writes and
    IOV_MAX); the scatter-gather half of the zero-copy framing."""
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:  # pragma: no cover — non-POSIX fallback
        sock.sendall(b"".join(bytes(b) for b in bufs))
        return
    while bufs:
        n = sendmsg(bufs[:1024])
        while n and bufs:
            if n >= bufs[0].nbytes:
                n -= bufs[0].nbytes
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][n:]
                n = 0


def _send_msg(sock: socket.socket, envelope: list, payload: Payload,
              compress: bool, tag: Optional[int] = None,
              pipelined: bool = False, block: Optional[str] = None):
    flags = _FLAG_PIPELINED if pipelined else 0
    nbytes = _payload_nbytes(payload)
    if compress and zstandard is not None and nbytes > COMPRESS_THRESHOLD:
        payload = _zstd_c().compress(_payload_bytes(payload))
        nbytes = len(payload)
        flags |= _FLAG_COMPRESSED
    elif block is not None and nbytes > BLOCK_THRESHOLD and (
            compress or _FORCE_BLOCK):
        comp = _block_compress(_payload_bytes(payload), block)
        if len(comp) < nbytes:  # incompressible payloads ship raw
            payload = comp
            nbytes = len(comp)
            flags |= _FLAG_BLOCK
    env = msgpack.packb(envelope + [nbytes], use_bin_type=True)
    # frame_len counts everything after the u32: flags+env_len fields (3
    # bytes, already consumed by the fixed 7-byte header read) + the
    # optional 4-byte tag + env + payload
    if tag is None:
        header = struct.pack("<IBH", 3 + len(env) + nbytes, flags, len(env))
    else:
        flags |= _FLAG_TAGGED
        header = struct.pack("<IBHI", 7 + len(env) + nbytes, flags,
                             len(env), tag & 0xFFFFFFFF)
    if isinstance(payload, bytes) and nbytes <= (1 << 14):
        # small single-buffer frames: one concatenated sendall beats the
        # sendmsg bookkeeping
        sock.sendall(header + env + payload)
        return
    bufs = [_as_byte_view(header + env)]
    if isinstance(payload, (list, tuple)):
        bufs.extend(_as_byte_view(b) for b in payload)
    else:
        bufs.append(_as_byte_view(payload))
    _sendmsg_all(sock, [b for b in bufs if b.nbytes])


def _recv_exact_into(sock: socket.socket, view: memoryview):
    n = view.nbytes
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("socket closed")
        got += r


def _recv_msg_full(sock: socket.socket) -> Tuple[list, Payload,
                                                 Optional[int], int]:
    """Read one frame: (envelope, payload view, tag-or-None, flags).
    The whole body lands in ONE fresh buffer via recv_into (no
    chunk-join copy); the payload is a view into it, which
    unpack_arrays turns into numpy views — socket to array without an
    intermediate copy."""
    head = bytearray(7)
    _recv_exact_into(sock, memoryview(head))
    frame_len, flags, env_len = struct.unpack("<IBH", head)
    extra = 4 if flags & _FLAG_TAGGED else 0
    if frame_len < 3 + extra + env_len:
        raise ConnectionError("bad frame header")
    body = bytearray(frame_len - 3)
    view = memoryview(body)
    _recv_exact_into(sock, view)
    tag = None
    if extra:
        (tag,) = struct.unpack_from("<I", body, 0)
        view = view[4:]
    env = msgpack.unpackb(view[:env_len], raw=False)
    payload: Payload = view[env_len:]
    if flags & _FLAG_COMPRESSED:
        if zstandard is None:  # pragma: no cover
            raise RpcError("compressed payload but zstandard unavailable")
        payload = _zstd_d().decompress(payload)
    elif flags & _FLAG_BLOCK:
        payload = _block_decompress(payload)
    return env, payload, tag, flags


def _recv_msg_tagged(sock: socket.socket) -> Tuple[list, Payload,
                                                   Optional[int]]:
    env, payload, tag, _ = _recv_msg_full(sock)
    return env, payload, tag


def _recv_msg(sock: socket.socket) -> Tuple[list, Payload]:
    env, payload, _, _ = _recv_msg_full(sock)
    return env, payload


class RpcServer:
    """Thread-per-connection RPC server with named handlers.

    Handlers take ``(payload: bytes) -> bytes`` and run concurrently;
    state they touch must be internally synchronized (the stores are).
    Handlers may also return a buffer LIST (:func:`pack_arrays_sg`) for
    zero-copy responses.

    Requests carrying a request id (``RpcClient.call(dedup=True)``) are
    executed at most once: a bounded LRU of recently-served ids returns
    the cached response for retried deliveries, so non-idempotent methods
    (gradient updates, forward-buffer ingestion) survive client retries
    without double-applying.

    Caveat: the id cache is in-memory per server process. A retry that
    lands after the server restarted re-executes the method — dedup is
    at-most-once per server incarnation, NOT exactly-once across
    restarts. Restart recovery instead relies on the worker tiers'
    restore-on-failure + re-arm paths (worker.py / worker_server.cc).

    ``concurrent_streams > 1`` enables the per-connection dispatch pool:
    up to that many requests from ONE connection execute concurrently in
    a shared pool. On an untagged connection responses still go out in
    request order (the legacy wire has no response tags, so order is the
    correlation). On a TAGGED connection (client negotiated ``__tags__``)
    responses carry the request's tag and are sent in COMPLETION order —
    a slow handler no longer head-of-line blocks fast ones. Existing
    blocking clients never pipeline, so the default of 1 keeps the exact
    serial per-connection behavior. The handler contract is unchanged —
    handlers already must tolerate cross-connection concurrency, and the
    dispatch pool only adds same-connection concurrency under the same
    rule.
    """

    DEDUP_CACHE_SIZE = 8192
    # Byte bound too: lookup responses are multi-MB, and 8192 of those
    # would not be a cache, it would be a leak (matches the C++
    # DedupCache in native/src/net.h).
    DEDUP_CACHE_BYTES = 256 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 concurrent_streams: int = 1, enable_tags: bool = True,
                 enable_trace: bool = True, enable_deadline: bool = True,
                 enable_codec: bool = True):
        from collections import OrderedDict

        self._concurrent_streams = max(1, int(concurrent_streams))
        # enable_tags=False emulates a legacy (pre-tag) peer: the
        # ``__tags__`` negotiation answers "no such method" and clients
        # negotiate down to untagged framing (compat tests use this);
        # enable_trace=False likewise refuses the ``__trace__`` probe so
        # clients never attach the trace envelope slot,
        # enable_deadline=False refuses ``__deadline__`` so clients
        # never attach the deadline slot, and enable_codec=False refuses
        # the ``__codec__`` probe so clients never send block-compressed
        # frames or half-precision payloads (legacy-peer emulation)
        self._enable_tags = enable_tags
        self._enable_codec = enable_codec
        self._handlers: Dict[str, Callable[[bytes], bytes]] = {}
        if enable_trace:
            self._handlers["__trace__"] = lambda payload: b""
        if enable_deadline:
            self._handlers["__deadline__"] = lambda payload: b""
        # remote fault-injection control (chaos bench re-arms a live PS
        # subprocess): opt-in by env — never exposed by default
        if knobs.get("PERSIA_FAULTS_RPC"):
            self._handlers["__faults__"] = faults._handle_control
        # /healthz surface: in-flight + served handler counts and the
        # age of the last request seen (scrapers distinguish "idle" from
        # "wedged" by pairing this with their own traffic knowledge).
        # Lock-guarded on purpose: inflight must not drift (a lost +=
        # under a bytecode race would mis-report forever), and two
        # uncontended acquisitions cost ~0.2us against the >=100us of
        # real per-request work — noise next to the GIL this path
        # already serializes on.
        self._stats_lock = threading.Lock()
        self._inflight_reqs = 0
        self._served_reqs = 0
        self._shed_reqs = 0  # deadline-expired requests refused unrun
        self._last_activity = _time.monotonic()
        self._stream_pool = None  # built lazily on the first connection
        self._stream_pool_lock = threading.Lock()
        self._dedup: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._dedup_bytes = 0
        self._dedup_lock = threading.Lock()
        # ids whose FIRST execution is still running: a client whose
        # socket timed out re-sends the same id on a fresh connection
        # while the original handler is still working; the duplicate
        # must wait for that execution, not run concurrently (it would
        # observe half-updated state, e.g. a popped buffer entry)
        self._inflight: Dict[bytes, threading.Event] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.addr = f"{host}:{self._sock.getsockname()[1]}"
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._shutdown_cb: Optional[Callable[[], None]] = None

    def register(self, name: str, fn: Callable[[bytes], bytes]):
        if (name.startswith("__") and name.endswith("__")
                and name not in ENVELOPE_EXTENSIONS):
            # dunder method names are reserved for declared wire
            # extensions: an undeclared one would dodge the negotiate-
            # down/byte-identical discipline persialint enforces
            raise ValueError(
                f"dunder RPC method {name!r} is not a declared wire "
                "extension; add it to rpc.ENVELOPE_EXTENSIONS first")
        self._handlers[name] = fn

    def on_shutdown(self, cb: Callable[[], None]):
        self._shutdown_cb = cb

    def serve_background(self):
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name=f"rpc-server-{self.addr}")
        self._thread.start()

    def serve_forever(self):
        self._running = True
        self._accept_loop()

    def _accept_loop(self):
        try:
            self._sock.settimeout(0.5)
        except OSError:
            return  # stop() closed the socket before the loop started
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _codec_negotiate(payload) -> Tuple[bytes, Optional[str]]:
        """Answer a ``__codec__`` probe: pick the first of the client's
        block codecs this process also has (lz4 both sides, else zlib —
        zlib is stdlib, so the intersection is never empty against a
        probe from this codebase). Returns (reply payload, chosen)."""
        chosen = None
        try:
            req = msgpack.unpackb(_payload_bytes(payload), raw=False) or {}
            mine = block_codecs()
            chosen = next((c for c in req.get("compress", []) if c in mine),
                          None)
        except Exception:
            chosen = None
        return msgpack.packb({"compress": chosen}, use_bin_type=True), chosen

    def health(self) -> dict:
        """Live-internals snapshot for the HTTP sidecar's /healthz."""
        with self._stats_lock:
            return {
                "rpc_addr": self.addr,
                "inflight_rpcs": self._inflight_reqs,
                "served_rpcs": self._served_reqs,
                "shed_rpcs": self._shed_reqs,
                "last_activity_age_sec": round(
                    _time.monotonic() - self._last_activity, 3),
            }

    def _handle_one(self, method: str, payload, req_id,
                    trace=None, deadline=None) -> Tuple[list, bytes]:
        """Run one request to a (envelope, body) response pair — the
        single execution point for BOTH the serial and dispatch-pool
        paths. ``trace`` is the propagated ``(trace_id, parent_span)``
        context from the envelope (None when the request is untraced):
        the handler runs under a child span, so per-shard PS handler
        work shows up parented to the caller's stage span even when a
        pool thread answers out of order. ``deadline`` is the request's
        LOCAL-monotonic expiry (computed at recv from the envelope's
        remaining-time slot): a request whose deadline already passed —
        e.g. it sat queued behind a slow handler in the dispatch pool —
        is SHED, not run; the caller's time budget is spent either way,
        and running it anyway would burn server work nobody reads."""
        with self._stats_lock:
            self._inflight_reqs += 1
            self._last_activity = _time.monotonic()
        try:
            if deadline is not None and _time.monotonic() >= deadline:
                with self._stats_lock:
                    self._shed_reqs += 1
                return ["err", f"{_DEADLINE_ERR}: deadline expired "
                               f"before {method!r} dispatched"], b""
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"no such method {method!r}")
            with tracing.span(f"rpc/{method}",
                              ctx=tuple(trace) if trace else None):
                if req_id is None:
                    result = handler(payload)
                else:
                    result = self._execute_once(handler, payload, req_id)
            return ["ok"], result
        except BaseException as e:
            return ["err", f"{type(e).__name__}: {e}"], b""
        finally:
            with self._stats_lock:
                self._inflight_reqs -= 1
                self._served_reqs += 1

    def _serve_conn_concurrent(self, conn: socket.socket):
        """Dispatch-pool variant: this thread reads requests and submits
        them to the shared pool; a writer thread sends the results back.
        Untagged requests answer strictly in request order (enqueued at
        submit time, the writer blocks on each future); tagged requests
        answer in COMPLETION order (enqueued by a done-callback). The
        ``inflight`` semaphore caps read-ahead at ``concurrent_streams``
        so a fast sender cannot pile unbounded work into the pool."""
        import queue as _queue
        from concurrent.futures import Future, ThreadPoolExecutor

        with self._stream_pool_lock:
            if not self._running:
                # stop() already ran: creating a pool here would leak an
                # executor nothing ever shuts down
                try:
                    conn.close()
                except OSError:
                    pass
                return
            if self._stream_pool is None:
                self._stream_pool = ThreadPoolExecutor(
                    max_workers=max(32, self._concurrent_streams),
                    thread_name_prefix="rpc-stream")
            pool = self._stream_pool
        compress = not _is_loopback(conn)
        pending: "_queue.Queue" = _queue.Queue()
        inflight = threading.BoundedSemaphore(self._concurrent_streams)
        # block codec negotiated by this connection's __codec__ probe
        # (mutable cell: send_response closes over it before the probe
        # can arrive)
        block_algo: List[Optional[str]] = [None]
        # responses may leave from the reader (inline fast path), the
        # writer (untagged in-order) or a pool thread (tagged,
        # completion order) — the lock keeps frames from interleaving
        send_lock = threading.Lock()
        # count of requests admitted whose response has not been sent
        # yet; when 0 the reader may execute+respond INLINE (a blocking
        # one-at-a-time client then never pays the pool tax — byte- and
        # order-identical to the serial server)
        queued = [0]
        queued_lock = threading.Lock()
        conn_dead = threading.Event()

        def send_response(env, body, tag):
            if conn_dead.is_set():
                return
            try:
                with send_lock:
                    _send_msg(conn, env, body,
                              compress if env[0] == "ok" else False,
                              tag=tag, block=block_algo[0])
            except OSError:
                conn_dead.set()

        def handle_direct(method, payload, req_id, tag, trace, deadline):
            """Tagged request in a pool thread: handle and send straight
            from here, in COMPLETION order — no queue hop, no writer
            wakeup (out-of-order is the tag wire's whole point)."""
            env, body = self._handle_one(method, payload, req_id, trace,
                                         deadline)
            send_response(env, body, tag)
            with queued_lock:
                queued[0] -= 1
            inflight.release()

        def writer():
            """Untagged responses must go out in REQUEST order (the
            legacy wire has no tags, so order is the correlation)."""
            while True:
                item = pending.get()
                if item is None:
                    return
                if item[0] == "__SHUTDOWN__":
                    try:
                        with send_lock:
                            _send_msg(conn, ["ok"], b"", False, tag=item[1])
                    except OSError:
                        pass
                    self.stop()
                    if self._shutdown_cb is not None:
                        self._shutdown_cb()
                    return
                tag, fut = item
                env, body = fut.result()
                send_response(env, body, tag)
                with queued_lock:
                    queued[0] -= 1
                inflight.release()

        wt = threading.Thread(target=writer, daemon=True,
                              name="rpc-stream-writer")
        wt.start()
        try:
            with conn:
                while self._running and not conn_dead.is_set():
                    try:
                        env, payload, tag, flags = _recv_msg_full(conn)
                    except (ConnectionError, OSError):
                        break
                    method = env[0]
                    if faults._active:
                        # injection sites for the chaos tests: reset
                        # kills the connection cold, drop swallows the
                        # frame (client times out), error answers an
                        # err envelope, corrupt mangles the payload
                        # (handler errors, connection survives)
                        try:
                            act = faults.fire("rpc.server.recv",
                                              method=method)
                        except ConnectionError:
                            break
                        except faults.InjectedFault as e:
                            send_response(
                                ["err", f"InjectedFault: {e}"], b"", tag)
                            continue
                        if act == "drop":
                            continue
                        if act == "corrupt":
                            payload = faults.corrupt_bytes(payload)
                    if method == "__shutdown__":
                        pending.put(("__SHUTDOWN__", tag))
                        wt.join()
                        return
                    if method == "__tags__" and self._enable_tags:
                        inflight.acquire()
                        with queued_lock:
                            queued[0] += 1
                        ack: Future = Future()
                        ack.set_result((["ok"], b""))
                        pending.put((tag, ack))
                        continue
                    if method == "__codec__" and self._enable_codec:
                        reply, block_algo[0] = self._codec_negotiate(payload)
                        inflight.acquire()
                        with queued_lock:
                            queued[0] += 1
                        ack = Future()
                        ack.set_result((["ok"], reply))
                        pending.put((tag, ack))
                        continue
                    req_id = env[1] if len(env) >= 3 else None
                    trace = env[2] if len(env) >= 4 else None
                    # deadline slot carries REMAINING seconds (clock-sync
                    # free); pin it to this host's monotonic clock once,
                    # at recv — queue wait then counts against it
                    deadline = env[3] if len(env) >= 5 else None
                    if deadline is not None:
                        deadline = _time.monotonic() + float(deadline)
                    if flags & _FLAG_PIPELINED:
                        # the client declared more requests may be in
                        # flight: executing inline would head-of-line
                        # block them behind this handler
                        idle = False
                    else:
                        with queued_lock:
                            idle = queued[0] == 0
                        if idle:
                            # ...and the client has not already pipelined
                            # the NEXT request (buffered data means
                            # read-ahead has value; handling inline would
                            # serialize an actively-pipelining client)
                            try:
                                idle = not select.select([conn], [], [],
                                                         0)[0]
                            except ValueError:
                                # fd >= FD_SETSIZE: select() can't watch
                                # it — take the pooled path, never kill
                                # the connection thread
                                idle = False
                    if idle:
                        # nothing in flight on this connection and no
                        # request queued behind this one: respond from
                        # the reader thread
                        renv, rbody = self._handle_one(method, payload,
                                                       req_id, trace,
                                                       deadline)
                        send_response(renv, rbody, tag)
                        if conn_dead.is_set():
                            break
                        continue
                    inflight.acquire()
                    with queued_lock:
                        queued[0] += 1
                    try:
                        if tag is None:
                            fut = pool.submit(
                                self._handle_one, method, payload, req_id,
                                trace, deadline)
                            pending.put((None, fut))
                        else:
                            pool.submit(handle_direct, method, payload,
                                        req_id, tag, trace, deadline)
                    except RuntimeError:
                        # stop() shut the pool down between recv and
                        # submit; the server is closing anyway
                        with queued_lock:
                            queued[0] -= 1
                        inflight.release()
                        break
        finally:
            pending.put(None)

    def _serve_conn(self, conn: socket.socket):
        if self._concurrent_streams > 1:
            self._serve_conn_concurrent(conn)
            return
        compress = not _is_loopback(conn)
        block = None  # set by this connection's __codec__ probe
        with conn:
            while self._running:
                try:
                    env, payload, tag = _recv_msg_tagged(conn)
                except (ConnectionError, OSError):
                    return
                method = env[0]
                req_id = env[1] if len(env) >= 3 else None
                trace = env[2] if len(env) >= 4 else None
                deadline = env[3] if len(env) >= 5 else None
                if deadline is not None:
                    deadline = _time.monotonic() + float(deadline)
                if faults._active:
                    try:
                        act = faults.fire("rpc.server.recv", method=method)
                    except ConnectionError:
                        return
                    except faults.InjectedFault as e:
                        try:
                            _send_msg(conn, ["err", f"InjectedFault: {e}"],
                                      b"", False, tag=tag)
                        except OSError:
                            return
                        continue
                    if act == "drop":
                        continue
                    if act == "corrupt":
                        payload = faults.corrupt_bytes(payload)
                try:
                    if method == "__shutdown__":
                        _send_msg(conn, ["ok"], b"", False, tag=tag)
                        self.stop()
                        if self._shutdown_cb is not None:
                            self._shutdown_cb()
                        return
                    if method == "__tags__" and self._enable_tags:
                        # serial server: tags are echoed but responses
                        # stay in order (valid — tags enable reordering,
                        # they do not promise it)
                        _send_msg(conn, ["ok"], b"", False, tag=tag)
                        continue
                    if method == "__codec__" and self._enable_codec:
                        reply, block = self._codec_negotiate(payload)
                        _send_msg(conn, ["ok"], reply, False, tag=tag)
                        continue
                except OSError:
                    return
                renv, rbody = self._handle_one(method, payload, req_id,
                                               trace, deadline)
                try:
                    _send_msg(conn, renv, rbody,
                              compress if renv[0] == "ok" else False,
                              tag=tag, block=block)
                except OSError:
                    return

    def _execute_once(self, handler, payload, req_id: bytes) -> bytes:
        """At-most-once execution for an id, including the concurrent
        window: a duplicate delivery waits for the in-flight original
        and returns its cached result. If the original ERRORED, nothing
        is cached and the duplicate executes itself — safe, because the
        failed execution restored any state it consumed."""
        while True:
            with self._dedup_lock:
                cached = self._dedup.get(req_id)
                if cached is not None:
                    return cached
                ev = self._inflight.get(req_id)
                if ev is None:
                    self._inflight[req_id] = mine = threading.Event()
                    break
            ev.wait(timeout=600.0)
        try:
            result = handler(payload)
        except BaseException:
            with self._dedup_lock:
                self._inflight.pop(req_id, None)
            mine.set()
            raise
        with self._dedup_lock:
            self._dedup[req_id] = result
            self._dedup_bytes += _payload_nbytes(result)
            while len(self._dedup) > self.DEDUP_CACHE_SIZE or (
                self._dedup_bytes > self.DEDUP_CACHE_BYTES
                and len(self._dedup) > 1
            ):
                _, old = self._dedup.popitem(last=False)
                self._dedup_bytes -= _payload_nbytes(old)
            self._inflight.pop(req_id, None)
        mine.set()
        return result

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
        with self._stream_pool_lock:
            pool, self._stream_pool = self._stream_pool, None
        if pool is not None:
            pool.shutdown(wait=False)


class _ConnState:
    """One pooled connection + its negotiated framing + tag bookkeeping.
    Owned by exactly one thread (the client pools one per thread), so
    none of this state needs a lock."""

    __slots__ = ("sock", "compress", "tagged", "trace", "deadline",
                 "codec", "block", "routing", "routing_epoch", "next_tag",
                 "outstanding", "done", "evicted", "dead")

    def __init__(self, sock: socket.socket, compress: bool):
        self.sock = sock
        self.compress = compress
        self.tagged = False
        self.trace = False  # peer acked the __trace__ envelope slot
        self.deadline = False  # peer acked the __deadline__ envelope slot
        self.codec = False  # peer acked the __codec__ payload codec
        self.block = None  # negotiated block-compression algo (or None)
        self.routing = False  # peer acked the __routing__ epoch rider
        self.routing_epoch = None  # peer's routing epoch at dial time
        self.next_tag = 1
        self.outstanding = set()  # tags sent, reply not yet claimed
        self.done: Dict[int, tuple] = {}  # tag -> (env, payload) parked
        self.evicted = set()  # parked replies dropped at DONE_PARK_LIMIT
        self.dead = False


class RpcFuture:
    """Tag-matched pending reply on a multiplexed connection.

    ``result()`` must be called from the thread that issued the call
    (connections are pooled per thread; the waiting thread drives the
    socket and parks replies for other tags — no reader thread)."""

    __slots__ = ("_client", "_cs", "_tag", "_method", "_resolved", "_value",
                 "_error")

    def __init__(self, client, cs, tag, method):
        self._client = client
        self._cs = cs
        self._tag = tag
        self._method = method
        self._resolved = False
        self._value = None
        self._error = None

    @classmethod
    def completed(cls, value=None, error=None) -> "RpcFuture":
        f = cls(None, None, None, None)
        f._resolved = True
        f._value = value
        f._error = error
        return f

    def result(self):
        if not self._resolved:
            self._resolved = True
            try:
                env, payload = self._client._wait_tag(self._cs, self._tag)
                self._client._count_wire(recv=_payload_nbytes(payload))
            except (ConnectionError, OSError) as e:
                self._error = _typed_transport_error(
                    e, self._client.addr, self._method)
                self._client._drop_conn(self._cs)
                raise self._error from e
            if env[0] != "ok":
                self._error = _typed_call_error(
                    self._client.addr, self._method, env[1])
            else:
                self._value = payload
        if self._error is not None:
            raise self._error
        return self._value


class RpcClient:
    """Blocking client with one pooled connection per thread.

    Transient connection failures retry with backoff (the reference's
    forward workers block on wait_for_serving until servers recover,
    forward.rs:708-715; here the recovery wait lives in the client so
    every caller gets it). Application-level errors (RpcError) never
    retry. At-least-once semantics: a request may be re-sent if the
    connection died after the server processed it.

    With ``enable_tags`` (default) each fresh connection negotiates
    tagged framing (``__tags__`` probe); against a tag-capable server,
    :meth:`call_future` multiplexes many in-flight requests on the one
    connection with tag-matched completion, and :meth:`call_many`
    windows requests that the server may execute out of order. Legacy
    peers negotiate down to the untagged wire transparently.
    """

    def __init__(self, addr: str, timeout: float = 60.0,
                 max_retries: int = 5, retry_backoff: float = 0.2,
                 enable_tags: bool = True,
                 deadline: Optional[float] = None,
                 enable_deadline: Optional[bool] = None,
                 enable_codec: bool = False,
                 enable_routing: bool = False,
                 retry_budget: Optional[RetryBudget] = None):
        self.addr = addr
        host, port = addr.rsplit(":", 1)
        self._target = (host, int(port))
        self.timeout = timeout
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        # storm control on the transport retry ladder: delays are
        # decorrelated-jittered (see decorrelated_jitter) and retry
        # sleeps spend from a per-client token bucket, so N clients
        # that lost the same replica neither wake in lockstep nor
        # retry unboundedly against the reborn process
        self.retry_budget = (retry_budget if retry_budget is not None
                             else RetryBudget())
        self._retry_rand: Callable[[], float] = random.random
        self._retry_sleep: Callable[[float], None] = _time.sleep
        self.enable_tags = enable_tags
        # opt-in payload codec (PsClient turns it on for its
        # mixed-precision wire): probes __codec__ at dial; legacy
        # servers negotiate down; when off, no probe — byte-identical
        self.enable_codec = enable_codec
        # opt-in routing-epoch rider (PERSIA_ROUTING_WIRE / reshard
        # tooling): probes __routing__ at dial; legacy servers refuse
        # and the connection carries no rider; when off, no probe —
        # byte-identical legacy wire
        self.enable_routing = enable_routing
        # payload bytes in/out, pre-framing (what the wire codec
        # shrinks): the bench's bytes-on-wire accounting
        self._wire_lock = threading.Lock()
        self._bytes_sent = 0
        self._bytes_recv = 0
        # deadline propagation is negotiated like __trace__: the
        # ``__deadline__`` probe is ONLY sent when this client wants
        # deadlines at all (a default deadline, or enable_deadline=True
        # for per-call use), so the no-deadline wire stays byte-identical
        # to the legacy protocol. ``deadline`` is seconds-from-send; the
        # envelope carries the remaining budget and the server sheds
        # work whose budget expired before dispatch.
        self.default_deadline = deadline
        self.enable_deadline = (bool(enable_deadline)
                                if enable_deadline is not None
                                else deadline is not None)
        self._local = threading.local()
        # one pooled conn per calling thread, keyed by the Thread object,
        # so close() (and GC via __del__) can release every socket
        # deterministically and conns of exited threads are swept instead
        # of leaking fds for the client's lifetime
        self._conn_by_thread: Dict[threading.Thread, _ConnState] = {}
        self._conns_lock = threading.Lock()

    def _dial(self) -> _ConnState:
        sock = socket.create_connection(self._target, timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        cs = _ConnState(sock, compress=not _is_loopback(sock))
        try:
            if self.enable_tags:
                # negotiate tagged framing; a legacy peer answers
                # "no such method __tags__" and the connection stays
                # untagged (negotiate-down, both directions compatible)
                _send_msg(sock, ["__tags__"], b"", False)
                env, _, _ = _recv_msg_tagged(sock)
                cs.tagged = env[0] == "ok"
            if tracing.tracing_enabled():
                # negotiate the trace envelope slot the same way; only
                # probed when this process traces at all, so the
                # disabled wire stays byte-identical to the legacy one
                _send_msg(sock, ["__trace__"], b"", False)
                env, _, _ = _recv_msg_tagged(sock)
                cs.trace = env[0] == "ok"
            if self.enable_deadline:
                # deadline slot negotiation: legacy peers answer "no
                # such method" and never see the slot (negotiate-down)
                _send_msg(sock, ["__deadline__"], b"", False)
                env, _, _ = _recv_msg_tagged(sock)
                cs.deadline = env[0] == "ok"
            if self.enable_codec:
                # payload-codec negotiation: the probe carries this
                # side's block codecs; an acking server replies with the
                # chosen one and both sides may then ship half-precision
                # payloads and block-compressed large frames. Legacy
                # peers answer "no such method" and the connection stays
                # on the fp32/raw wire; with the codec off the probe is
                # never sent — byte-identical legacy wire.
                _send_msg(sock, ["__codec__"],
                          msgpack.packb({"compress": block_codecs()},
                                        use_bin_type=True), False)
                env, pl, _ = _recv_msg_tagged(sock)
                if env[0] == "ok":
                    cs.codec = True
                    try:
                        rep = msgpack.unpackb(_payload_bytes(pl), raw=False)
                        cs.block = (rep or {}).get("compress")
                    except Exception:
                        cs.block = None
            if self.enable_routing:
                # routing-epoch rider negotiation: a reshard-aware
                # server acks with its current slot-table epoch; legacy
                # peers answer "no such method" and the connection
                # carries no rider (negotiate-down) — with the rider
                # off the probe is never sent, byte-identical wire
                _send_msg(sock, ["__routing__"], b"", False)
                env, pl, _ = _recv_msg_tagged(sock)
                if env[0] == "ok":
                    cs.routing = True
                    try:
                        rep = msgpack.unpackb(_payload_bytes(pl), raw=False)
                        cs.routing_epoch = int((rep or {}).get("epoch", 0))
                    except Exception:
                        cs.routing_epoch = None
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        me = threading.current_thread()
        dead = []
        with self._conns_lock:
            self._conn_by_thread[me] = cs
            for t in list(self._conn_by_thread):
                if not t.is_alive() and t is not me:
                    dead.append(self._conn_by_thread.pop(t))
        for c in dead:
            try:
                c.sock.close()
            except OSError:
                pass
        self._local.cs = cs
        return cs

    def _conn(self) -> _ConnState:
        cs = getattr(self._local, "cs", None)
        if cs is None or cs.dead:
            cs = self._dial()
        return cs

    def renegotiate(self):
        """Drop the calling thread's pooled connection so its next call
        re-dials and re-runs envelope-extension negotiation. Used when
        an extension flag flips after the first dial — e.g. the reshard
        controller arming ``enable_deadline`` on an already-connected
        client; other threads' connections are untouched (their wire
        stays exactly as negotiated)."""
        cs = getattr(self._local, "cs", None)
        if cs is not None:
            self._drop_conn(cs)

    def codec_active(self) -> bool:
        """True when this thread's connection negotiated the payload
        codec (dialing if needed); False against legacy peers, on
        dial failure (the caller's normal call path retries), or when
        the codec was never enabled."""
        if not self.enable_codec:
            return False
        try:
            return self._conn().codec
        except (ConnectionError, OSError):
            return False

    def routing_active(self) -> bool:
        """True when this thread's connection negotiated the
        __routing__ epoch rider (dialing if needed); False against
        legacy peers or when the rider was never enabled."""
        if not self.enable_routing:
            return False
        try:
            return self._conn().routing
        except (ConnectionError, OSError):
            return False

    def _count_wire(self, sent: int = 0, recv: int = 0):
        with self._wire_lock:
            self._bytes_sent += sent
            self._bytes_recv += recv

    def wire_stats(self) -> Dict[str, int]:
        """Cumulative request/response PAYLOAD bytes through this client
        (pre-compression — the codec-sensitive number: fp16 rows and
        int8 grads halve/quarter it; block compression on a DCN link
        shrinks the physical bytes further)."""
        with self._wire_lock:
            return {"sent": self._bytes_sent, "recv": self._bytes_recv}

    def _drop_conn(self, cs: Optional[_ConnState]):
        if cs is None:
            return
        cs.dead = True
        try:
            cs.sock.close()
        except OSError:
            pass
        with self._conns_lock:
            me = threading.current_thread()
            if self._conn_by_thread.get(me) is cs:
                del self._conn_by_thread[me]
        if getattr(self._local, "cs", None) is cs:
            self._local.cs = None

    @staticmethod
    def _traced_envelope(envelope: list, cs: _ConnState) -> list:
        """Attach the caller's active span context as the third envelope
        slot when this connection negotiated ``__trace__`` (the req-id
        slot is explicitly None when absent so servers index the slots
        positionally). Untraced calls and un-negotiated connections send
        the envelope untouched — byte-identical to the legacy wire."""
        if not cs.trace:
            return envelope
        tctx = tracing.current_context()
        if tctx is None:
            return envelope
        return [envelope[0], envelope[1] if len(envelope) > 1 else None,
                list(tctx)]

    def _build_envelope(self, envelope: list, cs: _ConnState,
                        deadline: Optional[float]) -> list:
        """Full envelope assembly: trace slot (slot 2) then the deadline
        slot (slot 3, remaining seconds). Earlier slots are padded with
        None so servers keep indexing positionally; with no deadline in
        play the envelope is exactly the traced/legacy form —
        byte-identical wire when the feature is off."""
        env = self._traced_envelope(envelope, cs)
        if deadline is None:
            deadline = self.default_deadline
        if deadline is not None and cs.deadline:
            env = list(env)
            while len(env) < 3:
                env.append(None)
            env.append(float(deadline))
        return env

    def _take_tag(self, cs: _ConnState) -> int:
        tag = cs.next_tag
        cs.next_tag = ((tag + 1) & 0xFFFFFFFF) or 1
        return tag

    # parked replies nobody has claimed yet; beyond this, the oldest are
    # evicted — replies for ABANDONED futures (e.g. a windowed burst cut
    # short by an earlier error) would otherwise accumulate on the
    # pooled connection for the client's lifetime. An evicted tag that
    # IS later claimed fails loudly (RpcError from _wait_tag), never
    # hangs — the dict cannot distinguish abandoned from merely
    # slow-to-resolve, so keep in-flight call_future bursts under this.
    DONE_PARK_LIMIT = 1024

    def _park_one(self, cs: _ConnState):
        """Read ONE reply and park it for whichever tag it answers."""
        env, payload, rtag = _recv_msg_tagged(cs.sock)
        if rtag is None:
            raise ConnectionError("untagged reply on tagged connection")
        if rtag in cs.outstanding:
            cs.outstanding.discard(rtag)
            cs.done[rtag] = (env, payload)
            while len(cs.done) > self.DONE_PARK_LIMIT:
                old = next(iter(cs.done))
                cs.done.pop(old)
                cs.evicted.add(old)
                while len(cs.evicted) > 8 * self.DONE_PARK_LIMIT:
                    cs.evicted.pop()
        # unknown tags (abandoned futures) are dropped

    def _drain_ready(self, cs: _ConnState):
        """Park any responses already sitting in the kernel buffer.
        Called before each pipelined SEND: if the client only ever reads
        after its whole send burst, its unread responses can fill both
        sockets' kernel buffers and stall the server's writer (and with
        it the server's read-ahead semaphore) — the classic duplex-pipe
        deadlock. Draining keeps the response direction flowing, so
        sends never face a stalled peer."""
        try:
            while cs.outstanding and select.select([cs.sock], [], [], 0)[0]:
                self._park_one(cs)
        except ValueError:
            # fd >= FD_SETSIZE: select() can't watch it; skip the
            # opportunistic drain (the eventual blocking reads still
            # make progress)
            pass

    def _wait_tag(self, cs: _ConnState, tag: int) -> tuple:
        """Read replies until ``tag``'s arrives; replies for other
        outstanding tags are parked for their futures. Single-owner-
        thread demultiplexing: whoever waits drives the socket."""
        while True:
            if tag in cs.done:
                return cs.done.pop(tag)
            if tag in cs.evicted:
                cs.evicted.discard(tag)
                raise RpcError(
                    f"{self.addr}: reply for tag {tag} was evicted "
                    f"(more than {self.DONE_PARK_LIMIT} unresolved "
                    f"futures parked on one connection)")
            self._park_one(cs)

    def call(self, method: str, payload: Payload = b"",
             dedup: bool = False, deadline: Optional[float] = None):
        """``dedup=True`` attaches a per-request id that the server uses
        to execute the request at most once (RpcServer's LRU of served
        ids): required for non-idempotent methods (gradient updates,
        forward-buffer ingestion), where a blind re-send after an
        ambiguous connection death would double-apply the update or leak
        an orphaned forward-buffer entry. With the id attached, retries
        are safe, so every call keeps the full retry-with-backoff
        resilience (the reference's forward workers block on
        wait_for_serving until servers recover, forward.rs:708-715).

        The server's id cache does not survive its restart: a retry
        that lands on a restarted process re-executes the method (see
        RpcServer docstring)."""
        import os
        import time

        envelope: list = [method]
        if dedup:
            envelope.append(os.urandom(12))
        delay = self.retry_backoff
        attempts_left = self.max_retries
        while True:
            cs = getattr(self._local, "cs", None)
            if cs is not None and cs.dead:
                cs = None
            fresh = cs is None
            if fresh:
                try:
                    cs = self._dial()
                except (ConnectionError, OSError) as e:
                    if attempts_left <= 0 or not self.retry_budget.acquire():
                        raise _typed_transport_error(e, self.addr,
                                                     method) from e
                    attempts_left -= 1
                    delay = decorrelated_jitter(self.retry_backoff, 5.0,
                                                delay, self._retry_rand)
                    self._retry_sleep(delay)
                    continue
            others_inflight = bool(cs.outstanding)
            try:
                if faults._active:
                    faults.fire("rpc.client.send", addr=self.addr,
                                method=method)
                env_send = self._build_envelope(envelope, cs, deadline)
                if cs.tagged:
                    tag = self._take_tag(cs)
                    _send_msg(cs.sock, env_send, payload, cs.compress,
                              tag=tag, block=cs.block)
                    cs.outstanding.add(tag)
                    env, result = self._wait_tag(cs, tag)
                else:
                    _send_msg(cs.sock, env_send, payload, cs.compress,
                              block=cs.block)
                    env, result = _recv_msg(cs.sock)
                self._count_wire(sent=_payload_nbytes(payload),
                                 recv=_payload_nbytes(result))
                break
            except (ConnectionError, OSError) as e:
                self._drop_conn(cs)
                if others_inflight:
                    # tag-matched calls were in flight on this
                    # connection; a transparent re-send cannot know
                    # their completion state — surface the failure
                    raise _typed_transport_error(e, self.addr,
                                                 method) from e
                if not fresh:
                    continue  # stale pooled socket: redial once, no sleep
                if attempts_left <= 0 or not self.retry_budget.acquire():
                    raise _typed_transport_error(e, self.addr,
                                                 method) from e
                attempts_left -= 1
                delay = decorrelated_jitter(self.retry_backoff, 5.0,
                                            delay, self._retry_rand)
                self._retry_sleep(delay)
        if env[0] != "ok":
            raise _typed_call_error(self.addr, method, env[1])
        return result

    def call_future(self, method: str, payload: Payload = b"",
                    dedup: bool = False,
                    deadline: Optional[float] = None) -> RpcFuture:
        """Issue a request and return a tag-matched :class:`RpcFuture`
        without waiting for the reply — many can be in flight on this
        thread's one connection, and a tag-capable server completes them
        out of order (no head-of-line blocking on a slow method).
        ``result()`` must be called from this same thread. No transport
        retry (the completed prefix of a multiplexed burst is ambiguous);
        against a legacy untagged peer this degrades to a synchronous
        call returning an already-completed future."""
        import os

        cs = self._conn()
        if not cs.tagged:
            try:
                return RpcFuture.completed(
                    value=self.call(method, payload, dedup=dedup,
                                    deadline=deadline))
            except (RpcError, ConnectionError, OSError) as e:
                return RpcFuture.completed(error=e)
        envelope: list = [method]
        if dedup:
            envelope.append(os.urandom(12))
        envelope = self._build_envelope(envelope, cs, deadline)
        tag = self._take_tag(cs)
        try:
            if faults._active:
                faults.fire("rpc.client.send", addr=self.addr,
                            method=method)
            self._drain_ready(cs)  # keep the reply direction flowing
            _send_msg(cs.sock, envelope, payload, cs.compress, tag=tag,
                      pipelined=True, block=cs.block)
        except (ConnectionError, OSError) as e:
            self._drop_conn(cs)
            raise _typed_transport_error(e, self.addr, method) from e
        self._count_wire(sent=_payload_nbytes(payload))
        cs.outstanding.add(tag)
        return RpcFuture(self, cs, tag, method)

    def call_many(self, method: str, payloads: List[Payload],
                  window: int = 16,
                  deadline: Optional[float] = None) -> list:
        """Pipelined calls on this thread's pooled connection: up to
        ``window`` requests are on the wire before the first response is
        read. On a tagged connection the server may execute and answer
        them OUT OF ORDER (tags restore the pairing); results still
        return in request order. On a legacy untagged connection the
        responses arrive in request order — the framing has no tags —
        and a ``concurrent_streams`` server still executes them
        concurrently.

        The window bounds the responses the server may have to buffer
        while we are still sending (kernel-socket-buffer deadlock
        guard). No retry: a connection failure mid-pipeline is raised
        as-is because the completed prefix is ambiguous — use only for
        idempotent methods (predict, lookups). An APPLICATION error is
        raised only after every in-flight response has been read, so
        the pooled connection stays in sync for subsequent calls."""
        if not payloads:
            return []
        cs = self._conn()
        if cs.tagged:
            return self._call_many_tagged(cs, method, payloads, window,
                                          deadline)
        results: list = []
        first_err: Optional[str] = None
        envelope = self._build_envelope([method], cs, deadline)
        try:
            i_send = 0
            while len(results) < len(payloads):
                while (i_send < len(payloads)
                       and i_send - len(results) < window):
                    if faults._active:
                        faults.fire("rpc.client.send", addr=self.addr,
                                    method=method)
                    _send_msg(cs.sock, envelope, payloads[i_send],
                              cs.compress, pipelined=True, block=cs.block)
                    self._count_wire(sent=_payload_nbytes(payloads[i_send]))
                    i_send += 1
                env, result = _recv_msg(cs.sock)
                self._count_wire(recv=_payload_nbytes(result))
                if env[0] != "ok":
                    # keep draining: an unread tail would desynchronize
                    # the NEXT call's request/response pairing
                    if first_err is None:
                        first_err = (self.addr, method, env[1])
                    result = b""
                results.append(result)
        except (ConnectionError, OSError) as e:
            self._drop_conn(cs)
            raise _typed_transport_error(e, self.addr, method) from e
        if first_err is not None:
            raise _typed_call_error(*first_err)
        return results

    def _call_many_tagged(self, cs: _ConnState, method: str,
                          payloads: List[Payload], window: int,
                          deadline: Optional[float] = None) -> list:
        results: list = []
        tags: List[int] = []
        first_err: Optional[tuple] = None
        envelope = self._build_envelope([method], cs, deadline)
        try:
            i_send = 0
            while len(results) < len(payloads):
                while (i_send < len(payloads)
                       and i_send - len(results) < window):
                    if faults._active:
                        faults.fire("rpc.client.send", addr=self.addr,
                                    method=method)
                    self._drain_ready(cs)  # keep the reply direction flowing
                    tag = self._take_tag(cs)
                    _send_msg(cs.sock, envelope, payloads[i_send],
                              cs.compress, tag=tag, pipelined=True,
                              block=cs.block)
                    self._count_wire(sent=_payload_nbytes(payloads[i_send]))
                    cs.outstanding.add(tag)
                    tags.append(tag)
                    i_send += 1
                # claim in request order; out-of-order arrivals park in
                # cs.done, so a slow request never blocks the server
                env, result = self._wait_tag(cs, tags[len(results)])
                self._count_wire(recv=_payload_nbytes(result))
                if env[0] != "ok":
                    if first_err is None:
                        first_err = (self.addr, method, env[1])
                    result = b""
                results.append(result)
        except (ConnectionError, OSError) as e:
            self._drop_conn(cs)
            raise _typed_transport_error(e, self.addr, method) from e
        if first_err is not None:
            raise _typed_call_error(*first_err)
        return results

    def call_msg(self, method: str, **kwargs) -> dict:
        """msgpack-dict convenience call."""
        result = self.call(method, msgpack.packb(kwargs, use_bin_type=True))
        return msgpack.unpackb(result, raw=False) if result else {}

    def shutdown_server(self):
        try:
            self.call("__shutdown__")
        except (RpcError, ConnectionError, OSError):
            pass

    def close(self):
        """Close every pooled connection (all threads). Safe to call from
        teardown while worker threads are gone; a racing caller simply
        redials."""
        with self._conns_lock:
            conns = list(self._conn_by_thread.values())
            self._conn_by_thread.clear()
        for cs in conns:
            cs.dead = True
            try:
                cs.sock.close()
            except OSError:
                pass
        self._local.cs = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
