"""persia_tpu — a TPU-native hybrid-parallel recommendation training framework.

A ground-up re-design of the capabilities of PersiaML/PERSIA
(/root/reference) for TPU hardware:

- Dense model training in JAX: ``jit`` + ``shard_map`` over a
  ``jax.sharding.Mesh``, bf16 mixed precision, optax dense optimizers,
  XLA collectives over ICI for data parallelism
  (reference: persia/ctx.py + persia/distributed.py, torch DDP/NCCL).
- Giant sparse embedding tables in sharded CPU-memory parameter servers
  written in C++ (reference: rust/persia-embedding-server), updated
  asynchronously with bounded staleness.
- An embedding-worker middleware tier that shards sign lookups,
  aggregates results into static-shape TPU-friendly tensors, and
  accumulates gradients (reference: embedding_worker_service/mod.rs).
- A native host-side pipeline feeding the TPU via pinned host buffers +
  ``jax.device_put`` (reference: rust/persia-core CUDA pools + forward.rs).
- Alternatively, fully device-resident sharded embedding tables in TPU
  HBM via ``shard_map`` collectives (no CPU PS) — a TPU-first mode the
  CUDA reference does not have.
"""

from persia_tpu.version import __version__

# Core user API at the package root (reference exposes the equivalents
# under persia.*). Heavy deps (jax) load lazily via these imports'
# modules only when first used.
from persia_tpu.config import EmbeddingSchema, GlobalConfig, uniform_slots
from persia_tpu.data.batch import (
    IDTypeFeature,
    IDTypeFeatureWithSingleID,
    Label,
    NonIDTypeFeature,
    PersiaBatch,
)
from persia_tpu.embedding import EmbeddingConfig

__all__ = [
    "__version__",
    "EmbeddingSchema",
    "GlobalConfig",
    "uniform_slots",
    "PersiaBatch",
    "IDTypeFeature",
    "IDTypeFeatureWithSingleID",
    "NonIDTypeFeature",
    "Label",
    "EmbeddingConfig",
]
