"""persia_tpu — a TPU-native hybrid-parallel recommendation training framework.

A ground-up re-design of the capabilities of PersiaML/PERSIA
(/root/reference) for TPU hardware:

- Dense model training in JAX: ``jit`` + ``shard_map`` over a
  ``jax.sharding.Mesh``, bf16 mixed precision, optax dense optimizers,
  XLA collectives over ICI for data parallelism
  (reference: persia/ctx.py + persia/distributed.py, torch DDP/NCCL).
- Giant sparse embedding tables in sharded CPU-memory parameter servers
  written in C++ (reference: rust/persia-embedding-server), updated
  asynchronously with bounded staleness.
- An embedding-worker middleware tier that shards sign lookups,
  aggregates results into static-shape TPU-friendly tensors, and
  accumulates gradients (reference: embedding_worker_service/mod.rs).
- A native host-side pipeline feeding the TPU via pinned host buffers +
  ``jax.device_put`` (reference: rust/persia-core CUDA pools + forward.rs).
- Alternatively, fully device-resident sharded embedding tables in TPU
  HBM via ``shard_map`` collectives (no CPU PS) — a TPU-first mode the
  CUDA reference does not have.
"""

from persia_tpu.version import __version__

__all__ = ["__version__"]
