"""Autopilot: the decision engine that closes the telemetry ->
planner -> operator loop.

Every ingredient of a self-scaling fleet exists as a manual step —
hotness fits zipf alpha and emits placement plans, the SLO engine
detects breaches, the reshard controller survives crashes, the operator
has scale/reshard/variant drivers — but a human still watches
``/fleet/*`` and decides. This module is the watcher that ACTS, the
role the reference deployment delegates to the k8s operator's CRD
reconciliation loop (PAPER.md L7, ``k8s/src/crd.rs``):

- **Policies** own one decision each. A policy contributes declarative
  :class:`~persia_tpu.slos.SloRule` objectives (installed into the
  fleet monitor's engine, so the trigger shares the alert surface
  operators already watch) and a ``decide()`` that turns firing rules
  plus :class:`~persia_tpu.fleet.FleetHistory` context into at most
  one proposed action per tick:

  - :class:`PsScalePolicy` — scale the PS tier out on SUSTAINED row
    load (fleet-scope ``sustained(ps_lookup_row_rate)``, so one spike
    never scales), back in when load stays below the low-water band.
    The two thresholds form the hysteresis band: anything between
    them holds the current size.
  - :class:`RebalancePolicy` — when one replica's share of the fleet
    row rate breaches, hold for a confirmation window, then re-place
    slots by workload hotness (the planner's ``placement_plan``) at
    the same replica count — but only when the plan PREDICTS a real
    improvement (no churn for a plan that cannot help).
  - :class:`VariantShedPolicy` — when a per-variant by_label rule
    burns (one A/B arm degraded/slow), shed that variant's split
    weight so the healthy arms absorb its traffic.

- The **Autopilot** ticks: evaluate rules, let each policy propose,
  pass proposals through per-(policy, kind) cooldowns and a GLOBAL
  trailing-hour action-rate limiter (both armed identically in
  recommend and enforce mode, so a recommend soak paces exactly like
  enforcement would), journal every decision with its triggering
  evidence (firing alerts + a bounded history excerpt), and — in
  ``enforce`` mode only — execute through the operator. Default mode
  is **recommend** (``PERSIA_AUTOPILOT_MODE``): the pilot journals
  what it WOULD do and touches nothing.

- The **ActionJournal** uses the reshard journal's atomic-file
  discipline (one ``rec_<seq>_<kind>.json`` per record via
  ``write_bytes_atomic``) so a SIGKILL mid-decision leaves a readable
  prefix. Kinds: ``decision`` (proposal + evidence, both modes),
  ``executed`` / ``action_failed`` (enforce), ``outcome`` /
  ``regressed`` (the deferred verification verdict), ``deferred``
  (blocked by cooldown/rate limit).

- **Verification**: every executed action schedules a check — after
  ``verify_sec`` the pilot asks whether the triggering rule is still
  firing. Still burning means the action did not help: the journal
  records ``regressed`` and the FlightRecorder captures a postmortem
  bundle of the worst service, same as an SLO breach would.

Pull-only and wire-neutral by construction: the pilot reads only what
the fleet monitor already scraped; in recommend mode it never touches
the RPC plane at all (pinned by test), and in enforce mode every
action flows through the operator's audited drivers.
"""

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from persia_tpu import knobs
from persia_tpu.logger import get_default_logger
from persia_tpu.slos import SloRule

_logger = get_default_logger(__name__)


class ActionJournal:
    """Append-only decision/outcome journal. With a ``root`` directory
    every record is its own atomically-written
    ``rec_<seq>_p<pid>_<kind>.json`` (the reshard journal's crash
    discipline — a torn record is impossible, a readable prefix always
    survives); without one the bounded in-memory ring still feeds
    ``GET /autopilot`` and the bench gates."""

    def __init__(self, root: Optional[str] = None, keep: int = 256):
        self.root = root
        self._mem: "deque[Dict]" = deque(maxlen=keep)
        self._lock = threading.Lock()
        self._seq = 0
        if root is not None:
            from persia_tpu.storage import PersiaPath

            PersiaPath(root).makedirs()
            for seq, _p in self._list_record_files():
                self._seq = max(self._seq, seq)

    def _list_record_files(self):
        from persia_tpu.storage import PersiaPath

        out = []
        for p in PersiaPath(self.root).listdir():
            name = os.path.basename(p)
            if (not name.startswith("rec_") or name.endswith(".tmp")
                    or not name.endswith(".json")):
                continue
            try:
                out.append((int(name.split("_")[1]), p))
            except (IndexError, ValueError):
                continue
        out.sort()
        return out

    def append(self, kind: str, /, **fields) -> Dict:
        reserved = {"seq", "kind", "ts"} & set(fields)
        if reserved:
            raise ValueError(
                f"journal fields shadow record keys: {sorted(reserved)}")
        with self._lock:
            self._seq += 1
            seq = self._seq
        rec = {"seq": seq, "kind": kind, "ts": time.time(), **fields}
        with self._lock:
            self._mem.append(rec)
        if self.root is not None:
            from persia_tpu.storage import PersiaPath

            path = os.path.join(
                self.root,
                f"rec_{seq:06d}_p{os.getpid()}_{kind}.json")
            PersiaPath(path).write_bytes_atomic(
                json.dumps(rec, sort_keys=True,
                           default=str).encode("utf-8"))
        return rec

    def records(self) -> List[Dict]:
        """Every durable record (or the in-memory ring when the
        journal has no directory), in sequence order."""
        if self.root is None:
            with self._lock:
                return list(self._mem)
        from persia_tpu.storage import PersiaPath

        out = []
        for _seq, p in self._list_record_files():
            out.append(json.loads(
                PersiaPath(p).read_bytes().decode("utf-8")))
        out.sort(key=lambda r: int(r.get("seq", 0)))
        return out

    def tail(self, n: int = 32) -> List[Dict]:
        with self._lock:
            return list(self._mem)[-n:]


class Policy:
    """One decision the autopilot can make. Subclasses contribute
    declarative rules via :meth:`rules` (installed into the monitor's
    SLO engine, so triggers share the operator-visible alert surface)
    and propose at most one action per tick via :meth:`decide`.

    A proposal is a dict:

    - ``kind``     — ``scale_out`` | ``scale_in`` | ``rebalance`` |
      ``variant_shed`` (dispatched by :meth:`Autopilot._execute`)
    - ``action``   — the operator-call parameters
    - ``reason``   — one operator-readable sentence
    - ``trigger_rule``      — rule whose firing alerts become the
      journal evidence (omit for history-driven policies)
    - ``watch_rule``        — rule name the deferred verification
      re-checks (still firing after ``verify_sec`` == regressed).
      Not always the trigger: a scale-IN's trigger is the low-load
      rule, but the regression to watch for is the HIGH-load rule
      firing after the shrink
    - ``evidence_spec``     — ``[(metric, service_regex, window_sec)]``
      history excerpts to bundle into the journal record
    - ``postmortem_service`` — whose flight snapshot to capture when
      the action fails or regresses
    """

    name = "policy"
    verify_sec = 60.0
    cooldown_sec: Optional[float] = None  # None -> the global knob

    def rules(self) -> List[SloRule]:
        return []

    def decide(self, pilot: "Autopilot", now: float,
               firing: Dict[str, List[Dict]]) -> Optional[Dict]:
        raise NotImplementedError


class PsScalePolicy(Policy):
    """Scale the PS tier on sustained fleet row load.

    The signal is ``ps_lookup_row_rate`` summed across replicas
    (fleet scope): under the workers' all-to-all fanout the total
    rows/sec IS the offered load, independent of replica count, so
    the same thresholds stay meaningful across every fleet size.
    ``sustained()`` makes one spike powerless; the gap between
    ``scale_out_at`` and ``scale_in_below`` is the hysteresis band
    that prevents flapping at a single threshold."""

    name = "ps_scale"

    def __init__(self, job: str, scale_out_at: float,
                 scale_in_below: float, window_sec: float = 300.0,
                 for_sec: float = 0.0, min_replicas: int = 1,
                 max_replicas: int = 8, step: int = 1,
                 metric: str = "ps_lookup_row_rate",
                 service: str = r"^ps", verify_sec: float = 60.0):
        if scale_in_below >= scale_out_at:
            raise ValueError(
                "hysteresis band inverted: scale_in_below "
                f"({scale_in_below}) must sit strictly below "
                f"scale_out_at ({scale_out_at})")
        self.job = job
        self.scale_out_at = float(scale_out_at)
        self.scale_in_below = float(scale_in_below)
        self.window_sec = float(window_sec)
        self.for_sec = float(for_sec)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.step = int(step)
        self.metric = metric
        self.service = service
        self.verify_sec = float(verify_sec)

    @property
    def rule_high(self) -> str:
        return f"autopilot_{self.name}_load_high"

    @property
    def rule_low(self) -> str:
        return f"autopilot_{self.name}_load_low"

    def rules(self) -> List[SloRule]:
        return [
            SloRule(self.rule_high, f"sustained({self.metric})", ">",
                    self.scale_out_at, window_sec=self.window_sec,
                    for_sec=self.for_sec, service=self.service,
                    scope="fleet", severity="autopilot",
                    description="fleet row load never dipped below the "
                                "scale-out threshold for the whole "
                                "window"),
            SloRule(self.rule_low, f"sustained({self.metric})", "<",
                    self.scale_in_below, window_sec=self.window_sec,
                    for_sec=self.for_sec, service=self.service,
                    scope="fleet", severity="autopilot",
                    description="fleet row load never rose above the "
                                "scale-in threshold for the whole "
                                "window"),
        ]

    def _hottest_service(self, pilot: "Autopilot", now: float):
        shares = pilot.monitor.history.breakdown(
            self.metric, self.window_sec, "avg", self.service, now)
        if not shares:
            return None
        return max(shares, key=shares.get)

    def decide(self, pilot, now, firing):
        replicas = pilot.operator.ps_replicas(self.job)
        if self.rule_high in firing and replicas < self.max_replicas:
            to = min(replicas + self.step, self.max_replicas)
            return {
                "kind": "scale_out",
                "action": {"job": self.job, "replicas": to},
                "reason": (f"fleet {self.metric} sustained above "
                           f"{self.scale_out_at:g} for "
                           f"{self.window_sec:g}s at {replicas} "
                           f"replicas -> scale to {to}"),
                "trigger_rule": self.rule_high,
                "watch_rule": self.rule_high,
                "evidence_spec": [(self.metric, self.service,
                                   self.window_sec)],
                "postmortem_service": self._hottest_service(pilot, now),
            }
        if self.rule_low in firing and replicas > self.min_replicas:
            to = max(replicas - self.step, self.min_replicas)
            return {
                "kind": "scale_in",
                "action": {"job": self.job, "replicas": to},
                "reason": (f"fleet {self.metric} sustained below "
                           f"{self.scale_in_below:g} for "
                           f"{self.window_sec:g}s at {replicas} "
                           f"replicas -> scale to {to}"),
                "trigger_rule": self.rule_low,
                # shrinking while load stays low is the POINT — the
                # regression to catch is the high-load rule firing
                # after the shrink (capacity was actually needed)
                "watch_rule": self.rule_high,
                "evidence_spec": [(self.metric, self.service,
                                   self.window_sec)],
                "postmortem_service": self._hottest_service(pilot, now),
            }
        return None


class RebalancePolicy(Policy):
    """Re-place slots by hotness when one replica carries an outsized
    share of the fleet row rate.

    Shares are cross-service ratios the rule grammar cannot express,
    so this policy reads the history ring directly: per-service
    ``breakdown`` of the row-rate over its window. A breach must HOLD
    for ``hold_sec`` (policy-side pending state, same shape as a
    rule's for_sec), and the hotness planner's plan must predict at
    least ``min_gain`` share improvement — a skew the plan cannot fix
    (one hot row) is not worth a migration."""

    name = "ps_rebalance"

    def __init__(self, job: str, share_threshold: float = 0.45,
                 hold_sec: float = 60.0, min_gain: float = 0.05,
                 window_sec: float = 60.0,
                 metric: str = "ps_lookup_row_rate",
                 service: str = r"^ps", verify_sec: float = 60.0):
        self.job = job
        self.share_threshold = float(share_threshold)
        self.hold_sec = float(hold_sec)
        self.min_gain = float(min_gain)
        self.window_sec = float(window_sec)
        self.metric = metric
        self.service = service
        self.verify_sec = float(verify_sec)
        self._pending_since: Optional[float] = None

    def measured_share(self, pilot: "Autopilot", now: float):
        """(max_share, service, per_service) from the history ring,
        or (None, None, {}) when fewer than two replicas report."""
        shares = pilot.monitor.history.breakdown(
            self.metric, self.window_sec, "avg", self.service, now)
        total = sum(shares.values())
        if len(shares) < 2 or total <= 0:
            return None, None, {}
        top = max(shares, key=shares.get)
        return shares[top] / total, top, {
            s: round(v / total, 4) for s, v in shares.items()}

    def decide(self, pilot, now, firing):
        share, top, per = self.measured_share(pilot, now)
        # hysteresis: pending state only clears once the share drops
        # clearly below the band, not the instant it grazes it
        if share is None or share < self.share_threshold * 0.9:
            self._pending_since = None
            return None
        if share < self.share_threshold:
            return None
        if self._pending_since is None:
            self._pending_since = now
        if now - self._pending_since < self.hold_sec:
            return None
        replicas = pilot.operator.ps_replicas(self.job)
        plan = pilot.plan_placement(replicas)
        if plan is None:
            return None
        predicted = plan.get("max_replica_share")
        if predicted is None or predicted > share - self.min_gain:
            # the planner cannot improve this skew enough to justify
            # moving slots — hold, and let the scale policy react if
            # absolute load is also high
            return None
        return {
            "kind": "rebalance",
            "action": {"job": self.job, "replicas": replicas},
            "reason": (f"{top} carries {share:.0%} of fleet "
                       f"{self.metric} (threshold "
                       f"{self.share_threshold:.0%} held "
                       f"{self.hold_sec:g}s); hotness plan predicts "
                       f"max share {predicted:.0%}"),
            "watch_rule": None,
            "plan": {
                "max_replica_share": predicted,
                "hash_even_max_share": plan.get("hash_even_max_share"),
                "moved_slots": plan.get("moved_slots"),
                "measured_shares": per,
            },
            "evidence_spec": [(self.metric, self.service,
                               self.window_sec)],
            "postmortem_service": top,
        }


class VariantShedPolicy(Policy):
    """Shed a burning model variant's split traffic.

    Reacts to any firing by_label alert of ``rule_name`` (default:
    the built-in per-variant degradation rule) whose alert key names
    a variant — ``serving0[variant=canary]`` — and lowers THAT
    variant's weight to ``shed_to`` through the operator's variant
    driver, so the healthy arms absorb its share. Promote/rollback
    stays a human call; the autopilot only stops the bleeding."""

    name = "variant_shed"

    def __init__(self, job: str, rule_name: str = "variant_degraded",
                 shed_to: float = 0.0, verify_sec: float = 120.0):
        self.job = job
        self.rule_name = rule_name
        self.shed_to = float(shed_to)
        self.verify_sec = float(verify_sec)

    def decide(self, pilot, now, firing):
        for alert in firing.get(self.rule_name, []):
            svc = alert.get("service", "")
            if "[variant=" not in svc:
                continue
            variant = svc.split("[variant=", 1)[1].rstrip("]")
            return {
                "kind": "variant_shed",
                "action": {"job": self.job, "name": variant,
                           "weight": self.shed_to},
                "reason": (f"{self.rule_name} firing for variant "
                           f"{variant!r} on {svc} (value "
                           f"{alert.get('value')}) -> shed split "
                           f"weight to {self.shed_to:g}"),
                "trigger_rule": self.rule_name,
                "watch_rule": self.rule_name,
                "evidence_spec": [],
                "postmortem_service": svc.split("[", 1)[0],
            }
        return None


def default_policies(job: str) -> List[Policy]:
    """The paved-road policy set with production-shaped bands — the
    bench and tests build their own with compressed windows."""
    return [
        PsScalePolicy(job, scale_out_at=500_000.0,
                      scale_in_below=100_000.0, window_sec=300.0),
        RebalancePolicy(job, share_threshold=0.45, hold_sec=120.0,
                        window_sec=120.0),
        VariantShedPolicy(job),
    ]


class Autopilot:
    """The decision loop: rules fire, policies propose, gates pace,
    the journal remembers, and (enforce mode only) the operator acts.

    ``tick()`` is pure control flow over injected time — the bench
    and tests drive it manually with explicit ``now``/``alerts`` so a
    recommend-mode shadow pilot and an enforce pilot can be stepped
    at identical instants and compared decision-for-decision.
    ``start()`` runs it on a daemon thread for real deployments.
    """

    MAX_RECENT = 64

    def __init__(self, monitor, operator, job: str,
                 policies: Optional[List[Policy]] = None,
                 mode: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 cooldown_sec: Optional[float] = None,
                 max_actions_per_hour: Optional[int] = None,
                 table_fn: Optional[Callable] = None,
                 tick_interval: float = 10.0):
        self.monitor = monitor
        self.operator = operator
        self.job = job
        self.policies = (list(policies) if policies is not None
                         else default_policies(job))
        mode = (mode if mode is not None
                else knobs.get("PERSIA_AUTOPILOT_MODE"))
        if mode not in ("recommend", "enforce"):
            raise ValueError(f"bad autopilot mode {mode!r} "
                             "(recommend|enforce)")
        self.mode = mode
        journal_dir = (journal_dir if journal_dir is not None
                       else knobs.get("PERSIA_AUTOPILOT_JOURNAL_DIR"))
        self.journal = ActionJournal(journal_dir)
        self.cooldown_sec = float(
            cooldown_sec if cooldown_sec is not None
            else knobs.get("PERSIA_AUTOPILOT_COOLDOWN_SEC"))
        self.max_actions_per_hour = int(
            max_actions_per_hour if max_actions_per_hour is not None
            else knobs.get("PERSIA_AUTOPILOT_MAX_ACTIONS_PER_HOUR"))
        # current routing table for plan slot-count pinning (embedders
        # that hold a live ReshardController pass its table); None
        # lets the planner assume a fresh hash-even layout
        self.table_fn = table_fn
        self.tick_interval = float(tick_interval)
        self._lock = threading.Lock()
        self._last_action: Dict[tuple, float] = {}
        self._action_times: "deque[float]" = deque()
        self._pending_checks: List[Dict] = []
        self._recent: "deque[Dict]" = deque(maxlen=self.MAX_RECENT)
        self._seq = itertools.count(1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the policies' rules join the live alert surface (idempotent
        # by name, retention re-widens)
        self.monitor.engine.add_rules(
            [r for p in self.policies for r in p.rules()])

    # --- gates -----------------------------------------------------------

    def _gate(self, policy: Policy, kind: str,
              now: float) -> Optional[str]:
        """Why this proposal may not proceed right now (None = clear).
        Applied BEFORE mode branching, so recommend-mode decisions
        pace exactly as enforcement would."""
        cooldown = (policy.cooldown_sec
                    if policy.cooldown_sec is not None
                    else self.cooldown_sec)
        with self._lock:
            last = self._last_action.get((policy.name, kind))
            if last is not None and now - last < cooldown:
                return (f"cooldown: last {policy.name}/{kind} "
                        f"{now - last:.0f}s ago < {cooldown:g}s")
            while (self._action_times
                   and now - self._action_times[0] > 3600.0):
                self._action_times.popleft()
            if len(self._action_times) >= self.max_actions_per_hour:
                return (f"rate limit: {len(self._action_times)} "
                        f"actions in the trailing hour >= "
                        f"{self.max_actions_per_hour}")
        return None

    def _arm(self, policy: Policy, kind: str, now: float):
        with self._lock:
            self._last_action[(policy.name, kind)] = now
            self._action_times.append(now)

    # --- evidence --------------------------------------------------------

    def _evidence(self, proposal: Dict, triggering: List[Dict],
                  now: float) -> Dict:
        excerpts = []
        for metric, service, window in proposal.get(
                "evidence_spec", []):
            excerpts.extend(self.monitor.history.excerpt(
                metric, window, service, points=16, now=now))
        return {
            "firing_rules": [
                {k: a.get(k) for k in ("rule", "service", "expr",
                                       "op", "threshold", "value",
                                       "firing_since")}
                for a in triggering],
            "history": excerpts,
        }

    def plan_placement(self, num_replicas: int) -> Optional[Dict]:
        """The hotness planner's placement plan for ``num_replicas``,
        pinned to the live table's slot count when an embedder
        provided ``table_fn``. None when telemetry is unarmed or the
        planner fails — a policy treats that as "cannot justify a
        rebalance", never as an error."""
        try:
            table = self.table_fn() if self.table_fn is not None \
                else None
            plan = self.monitor.hotness_plan(num_replicas,
                                             current_table=table)
        except Exception as e:
            _logger.warning("autopilot placement plan failed: %s", e)
            return None
        if not plan or not plan.get("assignment"):
            return None
        return plan

    # --- the loop --------------------------------------------------------

    def tick(self, now: Optional[float] = None,
             alerts: Optional[List[Dict]] = None) -> List[Dict]:
        """One decision pass. Returns the decisions that cleared the
        gates this tick (journaled; executed too in enforce mode).
        ``now``/``alerts`` injection keeps the pass deterministic for
        the recommend==enforce bench gate."""
        now = time.monotonic() if now is None else now
        if alerts is None:
            alerts = self.monitor.engine.evaluate(now)
        firing: Dict[str, List[Dict]] = {}
        for a in alerts:
            if a["firing"]:
                firing.setdefault(a["rule"], []).append(a)
        decisions = []
        for policy in self.policies:
            try:
                proposal = policy.decide(self, now, firing)
            except Exception:
                _logger.exception("policy %s decide() failed",
                                  policy.name)
                continue
            if proposal is None:
                continue
            kind = proposal["kind"]
            blocked = self._gate(policy, kind, now)
            if blocked is not None:
                self.journal.append(
                    "deferred", policy=policy.name, action_kind=kind,
                    action=proposal["action"], mode=self.mode,
                    reason=proposal["reason"], blocked_by=blocked)
                continue
            trigger = (proposal.get("trigger_rule")
                       or proposal.get("watch_rule"))
            triggering = firing.get(trigger, []) if trigger else []
            decision = {
                "decision_seq": next(self._seq),
                "policy": policy.name,
                "kind": kind,
                "action": proposal["action"],
                "reason": proposal["reason"],
                "mode": self.mode,
                "t": now,
                "evidence": self._evidence(proposal, triggering, now),
            }
            if proposal.get("plan") is not None:
                decision["plan"] = proposal["plan"]
            # cooldowns arm in BOTH modes: a recommend soak must pace
            # its decision stream exactly as enforcement would, or
            # graduating to enforce changes behavior
            self._arm(policy, kind, now)
            # nested, not splatted: the decision dict's own "kind"
            # (the ACTION kind) must not shadow the record kind
            self.journal.append("decision", decision=decision)
            if self.mode == "enforce":
                self._execute(policy, proposal, decision, now)
            with self._lock:
                self._recent.append(decision)
            decisions.append(decision)
        self._verify_outcomes(now, firing)
        return decisions

    def _execute(self, policy: Policy, proposal: Dict, decision: Dict,
                 now: float):
        kind = proposal["kind"]
        action = proposal["action"]
        try:
            if kind in ("scale_out", "scale_in"):
                event = self.operator.scale_ps(action["job"],
                                               action["replicas"])
            elif kind == "rebalance":
                event = self.operator.rebalance_ps(action["job"])
            elif kind == "variant_shed":
                event = self.operator.variant_op(
                    action["job"], "weight",
                    {"name": action["name"],
                     "weight": action["weight"]})
            else:
                raise ValueError(f"unknown action kind {kind!r}")
        except Exception as e:
            _logger.exception("autopilot action %s failed", kind)
            self.journal.append(
                "action_failed",
                decision_seq=decision["decision_seq"],
                policy=policy.name, action_kind=kind, action=action,
                error=repr(e))
            self._postmortem(proposal, decision,
                             f"autopilot_action_failed:{kind}")
            return
        self.journal.append(
            "executed", decision_seq=decision["decision_seq"],
            policy=policy.name, action_kind=kind, action=action,
            operator_event={k: v for k, v in (event or {}).items()
                            if k != "spec"})
        with self._lock:
            self._pending_checks.append({
                "decision_seq": decision["decision_seq"],
                "policy": policy.name, "kind": kind,
                "watch_rule": proposal.get("watch_rule"),
                "postmortem_service": proposal.get("postmortem_service"),
                "check_after": now + policy.verify_sec,
                "proposal": proposal,
            })

    def _verify_outcomes(self, now: float,
                         firing: Dict[str, List[Dict]]):
        """The deferred verdicts: after an action's verify window, a
        triggering rule still firing means the action did not move
        its target signal — journal ``regressed`` and capture a
        postmortem. Quiet rules journal ``outcome`` (improved)."""
        with self._lock:
            due = [c for c in self._pending_checks
                   if now >= c["check_after"]]
            if not due:
                return
            self._pending_checks = [c for c in self._pending_checks
                                    if now < c["check_after"]]
        for check in due:
            rule = check.get("watch_rule")
            still = rule is not None and rule in firing
            if still:
                self.journal.append(
                    "regressed", decision_seq=check["decision_seq"],
                    policy=check["policy"], action_kind=check["kind"],
                    watch_rule=rule,
                    detail="triggering rule still firing after the "
                           "verify window — the action did not move "
                           "its target signal")
                self._postmortem(check["proposal"], check,
                                 f"autopilot_regressed:{check['kind']}")
            else:
                self.journal.append(
                    "outcome", decision_seq=check["decision_seq"],
                    policy=check["policy"], action_kind=check["kind"],
                    watch_rule=rule, improved=True)

    def _postmortem(self, proposal: Dict, context: Dict, reason: str):
        recorder = getattr(self.monitor, "recorder", None)
        service = proposal.get("postmortem_service")
        if recorder is None or service is None:
            return
        try:
            recorder.capture(service, reason,
                             extra={"decision_seq":
                                    context.get("decision_seq")})
        except Exception:
            _logger.exception("autopilot postmortem capture failed")

    # --- background loop -------------------------------------------------

    def start(self) -> "Autopilot":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autopilot")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self.tick()
            except Exception:
                _logger.exception("autopilot tick failed")
            elapsed = time.monotonic() - t0
            self._stop.wait(max(self.tick_interval - elapsed, 0.05))

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # --- views -----------------------------------------------------------

    def decisions(self) -> List[Dict]:
        with self._lock:
            return list(self._recent)

    def describe(self) -> Dict:
        with self._lock:
            recent = list(self._recent)[-16:]
            n_hour = len(self._action_times)
            pending = len(self._pending_checks)
        return {
            "mode": self.mode,
            "job": self.job,
            "policies": [p.name for p in self.policies],
            "cooldown_sec": self.cooldown_sec,
            "max_actions_per_hour": self.max_actions_per_hour,
            "actions_trailing_hour": n_hour,
            "pending_verifications": pending,
            "journal": {"root": self.journal.root,
                        "tail": self.journal.tail(16)},
            "recent_decisions": [
                {k: d.get(k) for k in ("decision_seq", "policy",
                                       "kind", "action", "reason",
                                       "mode", "t")}
                for d in recent],
        }
