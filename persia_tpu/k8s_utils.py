"""Kubernetes manifest generation for persia_tpu jobs.

The reference ships a Rust operator + CRD (k8s/src/crd.rs:174-467
synthesizes per-role pods from a PersiaJob spec). The TPU-idiomatic
equivalent is declarative manifest generation: a job YAML in the same
shape (per-role replicas/resources/env) renders to plain Pod + Service
manifests, wiring REPLICA_INDEX/REPLICA_SIZE and the coordinator address
the way crd.rs does. Apply with kubectl or any GitOps pipeline; no
long-running operator binary is required for the core workflow.

Job spec shape::

    jobName: my-job
    image: persia-tpu-runtime:latest
    coordinatorPort: 23333
    embeddingConfigPath: /config/embedding_config.yml
    globalConfigPath: /config/global_config.yml
    roles:
      embeddingParameterServer: {replicas: 2, env: {...}}
      embeddingWorker: {replicas: 2}
      nnWorker: {replicas: 1, tpu: {type: v5p-8}}
      dataloader: {replicas: 1, entry: data_loader.py}

CLI: ``python -m persia_tpu.k8s_utils gen job.yml > manifests.yml``
"""

import argparse
import sys
from typing import Dict, List

import yaml

from persia_tpu.utils import load_yaml

_ROLE_LAUNCHER = {
    "embeddingParameterServer": "embedding-parameter-server",
    "embeddingWorker": "embedding-worker",
    "nnWorker": "nn-worker",
    "dataloader": "data-loader",
}


def _pod(job: str, image: str, role: str, index: int, replicas: int,
         command: List[str], env: Dict[str, str], extra: dict) -> dict:
    env_list = [{"name": k, "value": str(v)} for k, v in env.items()]
    container = {
        "name": role.lower(),
        "image": image,
        "command": command,
        "env": env_list,
    }
    if extra.get("resources"):
        container["resources"] = extra["resources"]
    spec = {"containers": [container], "restartPolicy": "OnFailure"}
    if extra.get("tpu"):
        # TPU attachment via the standard GKE node selectors
        spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": extra["tpu"]["type"],
            "cloud.google.com/gke-tpu-topology": extra["tpu"].get(
                "topology", "2x2"),
        }
        container.setdefault("resources", {}).setdefault("limits", {})[
            "google.com/tpu"] = extra["tpu"].get("chips", 4)
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{job}-{role.lower()}-{index}",
            "labels": {"persia-job": job, "persia-role": role,
                       "replica-index": str(index)},
        },
        "spec": spec,
    }


def gen_manifests(spec: dict) -> List[dict]:
    job = spec["jobName"]
    image = spec.get("image", "persia-tpu-runtime:latest")
    coord_port = int(spec.get("coordinatorPort", 23333))
    coord_host = f"{job}-coordinator"
    manifests: List[dict] = []

    manifests.append(_pod(
        job, image, "coordinator", 0, 1,
        ["python", "-m", "persia_tpu.launcher", "coordinator",
         "--host", "0.0.0.0", "--port", str(coord_port)],
        {}, {},
    ))
    manifests.append({
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": coord_host, "labels": {"persia-job": job}},
        "spec": {
            "selector": {"persia-job": job, "persia-role": "coordinator"},
            "ports": [{"port": coord_port, "targetPort": coord_port}],
        },
    })

    # Prometheus pushgateway (reference synthesizes one per job when
    # metrics are enabled, k8s/src/crd.rs:435-464); every role pod gets
    # PERSIA_METRICS_GATEWAY_ADDR pointing at it.
    metrics = spec.get("metrics", {})
    gateway_env = {}
    if metrics.get("enabled"):
        gw_host = f"{job}-metrics-gateway"
        gw_port = int(metrics.get("port", 9091))
        manifests.append({
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": gw_host,
                "labels": {"persia-job": job,
                           "persia-role": "metricsGateway"},
            },
            "spec": {
                "containers": [{
                    "name": "pushgateway",
                    "image": metrics.get("image", "prom/pushgateway:v1.9.0"),
                    # the process defaults to :9091; a non-default port
                    # must reach the listener, not just the Service
                    "args": [f"--web.listen-address=:{gw_port}"],
                    "ports": [{"containerPort": gw_port}],
                }],
                "restartPolicy": "OnFailure",
            },
        })
        manifests.append({
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": gw_host, "labels": {"persia-job": job}},
            "spec": {
                "selector": {"persia-job": job,
                             "persia-role": "metricsGateway"},
                "ports": [{"port": gw_port, "targetPort": gw_port}],
            },
        })
        gateway_env = {"PERSIA_METRICS_GATEWAY_ADDR": f"{gw_host}:{gw_port}"}

    roles = spec.get("roles", {})
    unknown = set(roles) - set(_ROLE_LAUNCHER)
    if unknown:
        raise ValueError(
            f"unknown role(s) {sorted(unknown)}; valid roles: "
            f"{sorted(_ROLE_LAUNCHER)}")
    def _replica_count(role_name: str) -> int:
        # same default (1) the pod-rendering loop uses: a role present
        # without an explicit replicas key is one replica, not zero
        conf = roles.get(role_name)
        return int(conf.get("replicas", 1)) if conf is not None else 0

    n_ps = _replica_count("embeddingParameterServer")
    n_workers = _replica_count("embeddingWorker")
    n_loaders = _replica_count("dataloader")
    n_trainers = _replica_count("nnWorker")
    for role, conf in roles.items():
        replicas = int(conf.get("replicas", 1))
        launcher_role = _ROLE_LAUNCHER[role]
        for i in range(replicas):
            env = {
                "REPLICA_INDEX": i,
                "REPLICA_SIZE": replicas,
                "PERSIA_COORDINATOR_ADDR": f"{coord_host}:{coord_port}",
                "PERSIA_NUM_PS": n_ps,
                # fleet sizes every role needs for rendezvous waits
                "PERSIA_NUM_WORKERS": n_workers,
                "PERSIA_NUM_DATALOADERS": n_loaders,
                **gateway_env,
                **conf.get("env", {}),
            }
            # every role may need the trainer count (data-loaders wait
            # for all trainers before streaming); trainers additionally
            # follow the RANK/WORLD_SIZE contract (env.py), matching the
            # reference's torch.distributed launch env
            env.setdefault("WORLD_SIZE", n_trainers)
            if role == "nnWorker":
                env.setdefault("RANK", i)
            command = ["python", "-m", "persia_tpu.launcher", launcher_role]
            if role == "embeddingWorker":
                command += ["--embedding-config",
                            spec["embeddingConfigPath"],
                            "--num-ps", str(n_ps)]
                if spec.get("globalConfigPath"):
                    command += ["--global-config", spec["globalConfigPath"]]
            elif role == "embeddingParameterServer":
                command += ["--port", str(conf.get("port", 8887))]
                if spec.get("globalConfigPath"):
                    command += ["--global-config", spec["globalConfigPath"]]
            elif conf.get("entry"):
                command += [conf["entry"]]
            manifests.append(_pod(job, image, role, i, replicas, command,
                                  env, conf))
    return manifests


def gen_crd() -> dict:
    """The PersiaJob CustomResourceDefinition (reference: gencrd.rs
    emitting jobs.persia.com from the Rust CRD types, crd.rs:42-64).

    A PersiaJob resource's spec is exactly the job-spec shape
    ``gen_manifests`` consumes; the operator (k8s_operator.py
    ``--from-crd``) watches these resources and reconciles them."""
    role_schema = {
        "type": "object",
        "properties": {
            "replicas": {"type": "integer", "minimum": 0},
            "entry": {"type": "string"},
            "port": {"type": "integer"},
            "env": {"type": "object",
                    "additionalProperties": {"type": "string"}},
            "resources": {"type": "object",
                          "x-kubernetes-preserve-unknown-fields": True},
            "tpu": {
                "type": "object",
                "properties": {
                    "type": {"type": "string"},
                    "topology": {"type": "string"},
                    "chips": {"type": "integer"},
                },
            },
        },
    }
    spec_schema = {
        "type": "object",
        "required": ["jobName"],
        "properties": {
            "jobName": {"type": "string"},
            "image": {"type": "string"},
            "coordinatorPort": {"type": "integer"},
            "embeddingConfigPath": {"type": "string"},
            "globalConfigPath": {"type": "string"},
            "metrics": {
                "type": "object",
                "properties": {
                    "enabled": {"type": "boolean"},
                    "port": {"type": "integer"},
                    "image": {"type": "string"},
                },
            },
            "roles": {
                "type": "object",
                # only the four launcher roles exist; an open schema
                # would admit CRs that can never converge (the manifest
                # generator has no launcher for unknown roles)
                "properties": {name: role_schema for name in _ROLE_LAUNCHER},
                "additionalProperties": False,
            },
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "persiajobs.persia.com"},
        "spec": {
            "group": "persia.com",
            "scope": "Namespaced",
            "names": {
                "plural": "persiajobs",
                "singular": "persiajob",
                "kind": "PersiaJob",
                "shortNames": ["pj"],
            },
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {"spec": spec_schema},
                }},
            }],
        },
    }


def _validate_structural(manifest: dict) -> List[str]:
    """Fallback schema checks when kubectl is absent: the structural
    invariants `kubectl apply --dry-run=client` would reject."""
    errs = []
    meta = manifest.get("metadata")
    name = meta.get("name", "?") if isinstance(meta, dict) else "?"
    where = f"{manifest.get('kind', '?')}/{name}"
    for key in ("apiVersion", "kind"):
        if not manifest.get(key):
            errs.append(f"{where}: missing {key}")
    if not isinstance(meta, dict) or not meta.get("name"):
        errs.append(f"{where}: missing metadata.name")
    elif not all(c.isalnum() or c in "-." for c in meta["name"]) or \
            meta["name"] != meta["name"].lower():
        errs.append(f"{where}: invalid DNS-1123 name {meta['name']!r}")
    kind = manifest.get("kind")
    spec = manifest.get("spec", {})
    if not isinstance(spec, dict):
        errs.append(f"{where}: spec must be a mapping, "
                    f"got {type(spec).__name__}")
        return errs
    if kind == "Pod":
        containers = spec.get("containers")
        if not isinstance(containers, list) or not containers:
            errs.append(f"{where}: Pod needs spec.containers")
        else:
            for c in containers:
                if not isinstance(c, dict):
                    errs.append(f"{where}: container entries must be "
                                f"mappings, got {type(c).__name__}")
                    continue
                if not c.get("name") or not c.get("image"):
                    errs.append(f"{where}: container needs name + image")
                if "command" in c and not isinstance(c["command"], list):
                    errs.append(f"{where}: command must be a list")
                env = c.get("env", [])
                for e in (env if isinstance(env, list) else []):
                    if not isinstance(e, dict):
                        errs.append(f"{where}: env entries must be mappings")
                        continue
                    if not isinstance(e.get("value", ""), str):
                        errs.append(
                            f"{where}: env {e.get('name')} value must be a "
                            f"string, got {type(e.get('value')).__name__}")
    elif kind == "Service":
        if not spec.get("ports"):
            errs.append(f"{where}: Service needs spec.ports")
        if not spec.get("selector"):
            errs.append(f"{where}: Service needs spec.selector")
    elif kind == "CustomResourceDefinition":
        names = spec.get("names")
        names = names if isinstance(names, dict) else {}
        if not (spec.get("group") and spec.get("versions") and
                names.get("plural") and names.get("kind")):
            errs.append(f"{where}: CRD needs group/versions/names")
        elif isinstance(meta, dict) and meta.get("name") != \
                f"{names['plural']}.{spec['group']}":
            # only meaningful once group+names exist; otherwise it's a
            # spurious cascade comparing against the literal "None.None"
            errs.append(f"{where}: CRD name must be <plural>.<group>")
    return errs


def _validate_all_structural(manifests: List[dict]) -> None:
    errs = [e for m in manifests for e in _validate_structural(m)]
    if errs:
        raise ValueError("manifest validation failed:\n" +
                         "\n".join(f"  - {e}" for e in errs))


def validate_manifests(manifests: List[dict],
                       kubectl: str = "kubectl") -> None:
    """Validate rendered manifests before they near a cluster: through
    ``kubectl apply --dry-run=client`` when the CLI exists (the intent of
    the reference's e2e harness, k8s/src/bin/e2e.rs:13-17), else through
    the structural checks. Raises ValueError with every problem found.

    kubectl with no reachable cluster/kubeconfig fails for connectivity
    reasons, not manifest reasons — that case falls back to the
    structural checks instead of rejecting valid manifests."""
    import shutil
    import subprocess

    if shutil.which(kubectl):
        doc = yaml.safe_dump_all(manifests, sort_keys=False)
        proc = subprocess.run(
            [kubectl, "apply", "--dry-run=client", "--validate=true",
             "-o", "name", "-f", "-"],
            input=doc, capture_output=True, text=True,
        )
        if proc.returncode == 0:
            return
        stderr = proc.stderr.strip()
        connectivity = any(tok in stderr.lower() for tok in (
            "connection refused", "unable to connect", "dial tcp",
            "no configuration has been provided", "missing or incomplete",
            "failed to download openapi", "cluster unreachable",
            "no such host",
        ))
        if not connectivity:
            raise ValueError(
                f"kubectl client dry-run rejected manifests:\n{stderr}")
        # fall through: kubectl present but no cluster — structural checks
    _validate_all_structural(manifests)


def validate_spec(spec: dict) -> List[dict]:
    """Render a job spec and structurally validate every manifest (no
    kubectl/cluster dependence — what the REST /apply pre-check needs).
    Returns the rendered manifests; raises on any problem."""
    manifests = gen_manifests(spec)
    _validate_all_structural(manifests)
    return manifests


def main(argv=None):
    p = argparse.ArgumentParser(prog="persia-tpu-k8s")
    p.add_argument("action", choices=["gen", "gencrd", "validate"])
    p.add_argument("job_yaml", nargs="?")
    args = p.parse_args(argv)
    if args.action == "gencrd":
        yaml.safe_dump(gen_crd(), sys.stdout, sort_keys=False)
        return
    if not args.job_yaml:
        p.error(f"{args.action} requires a job YAML file")
    spec = load_yaml(args.job_yaml)
    manifests = gen_manifests(spec)
    if args.action == "validate":
        validate_manifests(manifests + [gen_crd()])
        print(f"ok: {len(manifests)} manifests + CRD valid")
        return
    yaml.safe_dump_all(manifests, sys.stdout, sort_keys=False)


if __name__ == "__main__":
    main()
