"""Coordinator: service discovery + rendezvous control plane.

Plays the role NATS plays in the reference (persia-nats-client subject
scheme, master discovery in persia-core/src/nats.rs:22-100, address
polling in embedding_worker_service/mod.rs:139-339): a tiny in-memory
registry behind the TCP RPC. Services register ``(role, replica_index,
addr)``; clients poll until the expected replica count is present. A
kv namespace covers master-addr rendezvous and optimizer broadcast.

Run: ``python -m persia_tpu.service.coordinator --port 23333``
"""

import argparse
import threading
import time
from typing import Dict, Optional, Tuple

import msgpack

from persia_tpu.logger import get_default_logger
from persia_tpu.rpc import RpcClient, RpcServer

_logger = get_default_logger(__name__)

ROLE_PS = "embedding-parameter-server"
ROLE_WORKER = "embedding-worker"
ROLE_TRAINER = "nn-worker"
ROLE_DATALOADER = "data-loader"
ROLE_INFERENCE = "inference-server"


class Coordinator:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        # role -> {replica_index: addr}
        self._services: Dict[str, Dict[int, str]] = {}
        # role -> {replica_index: observability sidecar addr} (optional
        # field of register; the fleet monitor's discovery channel)
        self._http: Dict[str, Dict[int, str]] = {}
        self._kv: Dict[str, bytes] = {}
        self.server = RpcServer(host, port)
        self.server.register("register", self._register)
        self.server.register("deregister", self._deregister)
        self.server.register("list", self._list)
        self.server.register("topology", self._topology)
        self.server.register("kv_put", self._kv_put)
        self.server.register("kv_get", self._kv_get)
        self.server.register("ping", lambda p: b"pong")

    @property
    def addr(self) -> str:
        return self.server.addr

    def _register(self, payload: bytes) -> bytes:
        req = msgpack.unpackb(payload, raw=False)
        with self._lock:
            self._services.setdefault(req["role"], {})[req["replica_index"]] = (
                req["addr"]
            )
            if req.get("http_addr"):
                self._http.setdefault(
                    req["role"], {})[req["replica_index"]] = req["http_addr"]
            else:
                # re-registration WITHOUT a sidecar (restarted with the
                # sidecar off, or an older binary mid-rollout) must not
                # leave the dead previous sidecar address in topology
                self._http.get(req["role"], {}).pop(
                    req["replica_index"], None)
        _logger.info("registered %s[%d] at %s (sidecar %s)", req["role"],
                     req["replica_index"], req["addr"],
                     req.get("http_addr") or "none")
        return b""

    def _deregister(self, payload: bytes) -> bytes:
        req = msgpack.unpackb(payload, raw=False)
        with self._lock:
            self._services.get(req["role"], {}).pop(req["replica_index"], None)
            self._http.get(req["role"], {}).pop(req["replica_index"], None)
        return b""

    def _list(self, payload: bytes) -> bytes:
        req = msgpack.unpackb(payload, raw=False)
        with self._lock:
            members = self._services.get(req["role"], {})
            addrs = [members[i] for i in sorted(members)]
        return msgpack.packb({"addrs": addrs}, use_bin_type=True)

    def _topology(self, payload: bytes) -> bytes:
        """The fleet monitor's discovery read: every registered service
        with its replica index, RPC address, and (when the service
        published one) observability sidecar address."""
        with self._lock:
            members = [
                {"role": role, "replica": i, "addr": addr,
                 "http_addr": self._http.get(role, {}).get(i)}
                for role, reps in sorted(self._services.items())
                for i, addr in sorted(reps.items())
            ]
        return msgpack.packb({"members": members}, use_bin_type=True)

    def _kv_put(self, payload: bytes) -> bytes:
        req = msgpack.unpackb(payload, raw=False)
        with self._lock:
            self._kv[req["key"]] = req["value"]
        return b""

    def _kv_get(self, payload: bytes) -> bytes:
        req = msgpack.unpackb(payload, raw=False)
        with self._lock:
            value = self._kv.get(req["key"])
        return msgpack.packb({"value": value}, use_bin_type=True)


class CoordinatorClient:
    """Client with the exponential-backoff wait patterns the reference
    uses on every NATS call (nats.rs:77-95, :163-203)."""

    def __init__(self, addr: str):
        self.client = RpcClient(addr)

    def register(self, role: str, replica_index: int, addr: str,
                 http_addr: Optional[str] = None):
        # http_addr (the observability sidecar) is an optional extra
        # field: an old coordinator ignores unknown keys, so mixed
        # versions keep registering fine — the fleet view just lacks
        # the sidecar address for that replica
        self.client.call_msg("register", role=role,
                             replica_index=replica_index, addr=addr,
                             http_addr=http_addr)

    def topology(self):
        """Full service topology incl. sidecar addresses (fleet
        discovery). Raises RpcError against a pre-fleet coordinator."""
        return self.client.call_msg("topology")["members"]

    def deregister(self, role: str, replica_index: int):
        self.client.call_msg("deregister", role=role,
                             replica_index=replica_index)

    def list(self, role: str):
        return self.client.call_msg("list", role=role)["addrs"]

    def wait_members(self, role: str, count: int, timeout: float = 60.0):
        """Poll until `count` replicas of `role` registered."""
        deadline = time.monotonic() + timeout
        delay = 0.05
        while True:
            addrs = self.list(role)
            if len(addrs) >= count:
                return addrs
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"waited {timeout}s for {count} x {role}, have {addrs}"
                )
            time.sleep(delay)
            delay = min(delay * 2, 1.0)

    def kv_put(self, key: str, value: bytes):
        self.client.call_msg("kv_put", key=key, value=value)

    def kv_get(self, key: str):
        return self.client.call_msg("kv_get", key=key)["value"]

    def wait_kv(self, key: str, timeout: float = 60.0) -> bytes:
        deadline = time.monotonic() + timeout
        delay = 0.05
        while True:
            v = self.kv_get(key)
            if v is not None:
                return v
            if time.monotonic() > deadline:
                raise TimeoutError(f"waited {timeout}s for kv key {key!r}")
            time.sleep(delay)
            delay = min(delay * 2, 1.0)

    def ping(self) -> bool:
        try:
            return self.client.call("ping") == b"pong"
        except Exception:
            return False


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=23333)
    p.add_argument("--addr-file", default=None,
                   help="write the bound address here after listen; with "
                        "--port 0 this is the race-free way for a parent "
                        "to learn the port (probing a free port before "
                        "spawn is a TOCTOU race under load)")
    args = p.parse_args()
    coord = Coordinator(args.host, args.port)
    _logger.info("coordinator listening on %s", coord.addr)
    if args.addr_file:
        from persia_tpu.utils import write_addr_file

        write_addr_file(coord.addr, args.addr_file)
    coord.server.serve_forever()


if __name__ == "__main__":
    main()
