"""Dataflow: batch routing from data-loaders to workers and trainers.

The reference pushes ID features to embedding workers and the rest of the
batch to nn-workers over NATS, routed by ``batch_id % world_size``
(persia-core/src/nats.rs:145-353). Here:

- :class:`DataflowClient` (data-loader side): ingests the batch's ID
  features into a worker replica (round-robin, with backoff-retry on
  ``ForwardBufferFull`` — reference nats.rs:267-291), then ships the
  batch + its ``(worker_addr, ref_id)`` handle to the owning trainer.
- :class:`DataflowReceiver` (trainer side): a tiny RPC endpoint feeding a
  bounded queue that :class:`~persia_tpu.data.dataloader.StreamingDataset`
  drains (reference: DataflowService, nats.rs:102-140).
"""

import queue
import time
from typing import List, Optional, Sequence

import msgpack

from persia_tpu.data.batch import PersiaBatch
from persia_tpu.logger import get_default_logger
from persia_tpu.rpc import RpcClient, RpcError, RpcServer

_logger = get_default_logger(__name__)

_EOS = object()


class DataflowReceiver:
    """Trainer-side ingestion endpoint.

    ``num_senders`` is the number of data-loader replicas feeding this
    trainer: the stream ends only after EVERY sender reports
    end-of-stream, otherwise the fastest loader's EOS would terminate
    the trainer while slower replicas are still mid-stream."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 buffer_size: int = 128, num_senders: int = 1):
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        self.num_senders = max(1, num_senders)
        self._eos_seen = 0
        self._eos_ids: set = set()  # identified senders already counted
        self._eos_lock = threading.Lock()
        self.server = RpcServer(host, port)
        self.server.register("enqueue_batch", self._enqueue)
        self.server.register("end_of_stream", self._eos)
        self.server.serve_background()

    @property
    def addr(self) -> str:
        return self.server.addr

    def _enqueue(self, payload: bytes) -> bytes:
        head_len = int.from_bytes(payload[:4], "little")
        head = msgpack.unpackb(payload[4 : 4 + head_len], raw=False)
        batch = PersiaBatch.from_bytes(payload[4 + head_len :])
        if head.get("worker_addr") is not None:
            batch.remote_ref = (head["worker_addr"], head["ref_id"])
        self._q.put(batch)
        return b""

    def _eos(self, payload: bytes) -> bytes:
        # Identified EOS (payload = msgpack {"sender": id}) is counted at
        # most once per sender, so a monitor aborting a replica that
        # already sent its own EOS cannot double-count it and cut the
        # stream while other replicas are mid-send. Empty payload keeps
        # the legacy anonymous count-only behavior.
        sender = None
        if payload:
            sender = msgpack.unpackb(payload, raw=False).get("sender")
        with self._eos_lock:
            if sender is not None:
                if sender in self._eos_ids:
                    return b""
                self._eos_ids.add(sender)
            self._eos_seen += 1
            done = self._eos_seen >= self.num_senders
        if done:
            self._q.put(_EOS)
        return b""

    def get(self, timeout: Optional[float] = None) -> Optional[PersiaBatch]:
        item = self._q.get(timeout=timeout)
        return None if item is _EOS else item

    def abort_sender(self, sender_id=None):
        """Count a dead sender as end-of-stream: the hook for whatever
        watches loader liveness (tests/test_flagship_e2e.py's watchdog
        today; a deployment monitor in production wiring) to call when a
        data-loader replica dies without sending EOS, so the trainer
        drains what arrived and exits instead of blocking on the queue
        forever. Pass the same ``sender_id`` the replica uses for
        ``send_eos`` — then an abort racing the replica's own EOS counts
        once, not twice."""
        self._eos(msgpack.packb({"sender": sender_id})
                  if sender_id is not None else b"")

    def close(self):
        self.server.stop()


class DataflowClient:
    """Data-loader side: worker ingestion + trainer routing.

    ``worker=None`` skips the embedding-worker ingestion leg entirely:
    the loader ships the raw batch (id features included) straight to
    the trainer. That is the wiring for device-cache / device-mode
    trainers, which do their own lookups — ingesting into a worker tier
    would leak forward-buffer entries no trainer ever consumes (their
    expiry sweep would clean them, but only after holding buffer slots
    for buffered_data_expired_sec)."""

    def __init__(self, worker, trainer_addrs: Sequence[str],
                 max_retries: int = 60):
        self.worker = worker
        self.trainer_addrs = list(trainer_addrs)
        self._trainers = [RpcClient(a) for a in self.trainer_addrs]
        self.max_retries = max_retries

    def send(self, batch: PersiaBatch):
        ref = None
        if batch.requires_grad and self.worker is not None:
            delay = 0.05
            for attempt in range(self.max_retries):
                try:
                    ref = self.worker.put_batch(batch.id_type_features)
                    break
                except RpcError as e:
                    if "ForwardBufferFull" not in str(e):
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
            else:
                raise TimeoutError("embedding workers stayed full")
        head = msgpack.packb(
            {
                "worker_addr": ref[0] if ref else None,
                "ref_id": ref[1] if ref else None,
            },
            use_bin_type=True,
        )
        payload = len(head).to_bytes(4, "little") + head + batch.to_bytes()
        trainer = self._trainers[
            (batch.batch_id or 0) % len(self._trainers)
        ]
        # dedup id: a blind retry after an ambiguous connection death
        # would deliver (and train on) the batch twice, double-consuming
        # its forward-buffer ref on the embedding worker
        trainer.call("enqueue_batch", payload, dedup=True)

    def send_eos(self, sender_id=None):
        # dedup id: an ambiguous connection death would otherwise re-send
        # the EOS, double-counting this sender against the receiver's
        # num_senders threshold and ending the stream early. sender_id
        # additionally lets the receiver dedupe this EOS against an
        # abort_sender() from a liveness monitor (process-level dedup,
        # not just retry-level).
        payload = (msgpack.packb({"sender": sender_id})
                   if sender_id is not None else b"")
        for t in self._trainers:
            t.call("end_of_stream", payload, dedup=True)
